"""FusedTrainer: the TPU-native fast path — one jitted SPMD train step for a
StandardWorkflow-shaped graph.

The unit-at-a-time engine (Workflow.run) preserves the reference's execution
semantics but pays one dispatch + host sync per unit.  The fused trainer
stages the whole minibatch pipeline

    gather(dataset, idx) -> forwards -> loss -> grads -> per-layer sgd_update

into ONE ``jax.jit`` with sharding annotations: dataset/batch sharded over
the mesh ``data`` axis, params replicated (or column-sharded over ``model``
for wide FC layers), gradients reduced by the psum XLA inserts — the
reference's entire master/slave ZeroMQ stack (SURVEY.md §3.4) becomes a
single compiled collective over ICI.

Semantics guaranteed identical to the unit path:
  - forward math IS the units' own pure ``apply`` (same code objects);
  - the update rule IS ``nn_units.sgd_update`` with each GD unit's own
    hyperparameters (per-layer lr/momentum/L1+L2/clip survive);
  - loss/cotangent match the evaluators (softmax-CE at logits; masked MSE);
  - dropout/stochastic pooling draw per-layer per-step keys from the same
    seeded stream design (mask reuse is implicit — fwd and bwd live in one
    autodiff graph).

Mixed precision: with ``root.common.engine.precision = "bfloat16"``, the
forward/backward graph runs in bf16 on the MXU while master params, velocity
and the update stay float32.

Unit-Array refresh cadence: training state lives in device arrays; the
units' ``Array`` views are refreshed by ``writeback`` only when an
epoch-granular consumer needs them (a wired plotter) and once at the end
of the run — NOT unconditionally every epoch (a fixed ~100ms/RTT tax on
tunneled hosts).  A due HOST-FORMAT snapshot no longer pays even that:
``snapshot_from_trees`` hands donation-safe device copies to the
snapshotter's background writer, which pulls and writes while the next
epoch computes (r5; the deep pipeline checkpoints the same way at flush
boundaries).  Ad-hoc observers that read weights mid-run must account
for this.
"""

from __future__ import annotations

import sys as _sys
from typing import Dict

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.nn_units import sgd_update


class FusedUnsupportedError(ValueError):
    """The workflow's graph cannot run on the fused path (e.g. tied
    weights).  The engine catches exactly this to fall back to the unit
    engine; any other error propagates (ADVICE r3: a blanket ValueError
    catch masked unrelated failures)."""


class FusedStagingUnsupportedError(FusedUnsupportedError):
    """A fused SLAVE cannot serve a host-staged streaming loader
    (FusedClient needs the dataset device-resident).  A dedicated type so
    the engine's slave fallback catches exactly the two known refusals —
    this and the base FusedUnsupportedError — instead of a blanket
    ``ValueError`` that would also swallow real config errors."""


class FusedTrainer:
    """Compile and drive fused steps for a built+initialized workflow with
    ``forwards``, ``gds``, ``loader``, ``evaluator``, ``decision``."""

    def __init__(self, workflow, mesh=None, remat=None):
        from znicz_tpu.all2all import All2AllSoftmax
        from znicz_tpu.attention import SeqAll2AllSoftmax
        from znicz_tpu.dropout import DropoutForward
        from znicz_tpu.evaluator import EvaluatorSoftmax
        from znicz_tpu.pooling import StochasticPoolingBase

        if remat is None:
            remat = bool(root.common.engine.get("remat", False))
        self.remat = remat
        self.scan_chunk = int(root.common.engine.get("scan_chunk",
                                                     type(self).scan_chunk))
        self.pipeline_depth = int(root.common.engine.get(
            "pipeline_depth", type(self).pipeline_depth))
        self.workflow = workflow
        self.forwards = list(workflow.forwards)
        self.loader = workflow.loader
        self.decision = workflow.decision
        self.mesh = mesh
        #: seq_parallel ring attention on the TRAINING mesh (ISSUE 18):
        #: with the knob on and a >1 ``model`` axis in this slice, every
        #: attention core shard_maps over THIS mesh (batch x sequence)
        #: instead of building a private ("sp",) device grid — one mesh
        #: serves the jitted steps AND the ring rotation
        if mesh is not None and "model" in mesh.axis_names \
                and mesh.shape["model"] > 1:
            from znicz_tpu.attention import (MultiHeadAttention,
                                             seq_parallel_size)

            if seq_parallel_size() > 1:
                for f in self.forwards:
                    if isinstance(f, MultiHeadAttention):
                        f.bind_sequence_mesh(mesh)
        self.loss_kind = ("softmax"
                          if isinstance(workflow.evaluator, EvaluatorSoftmax)
                          else "mse")
        #: the fused path sums the (C,C) confusion ON DEVICE (scan carry +
        #: ``epoch_conf``) and transfers it once per epoch, so the unit
        #: path's width-based auto-off (per-minibatch transfer cost) does
        #: not apply: confusion is ALWAYS collected unless the user
        #: explicitly disabled it on the evaluator.  ``None`` (evaluator
        #: not yet initialized) counts as unresolved, not as disabled
        #: (ADVICE r3 / VERDICT r3 missing #4).
        ev = workflow.evaluator
        if getattr(ev, "confusion_explicit", False):
            self.compute_confusion = bool(ev.compute_confusion)
        else:
            self.compute_confusion = True
        self._softmax_cls = All2AllSoftmax
        #: the per-position softmax head (ISSUE 15): like All2AllSoftmax,
        #: the fused path emits its LOGITS and derives loss/cotangent in
        #: the loss head (seq logits flatten tokens into the batch axis)
        self._seq_softmax_cls = SeqAll2AllSoftmax
        self._dropout_cls = DropoutForward
        self._stochpool_cls = StochasticPoolingBase
        self.gd_of = {gd.forward.name: gd for gd in workflow.gds}
        # tied weights (shared Arrays) need joint-update logic the fused
        # path doesn't implement — detect and refuse (unit path handles it)
        seen = {}
        for f in self.forwards:
            for k, arr in f.params().items():
                if id(arr) in seen:
                    raise FusedUnsupportedError(
                        f"fused trainer does not support tied weights "
                        f"({f.name}.{k} shares {seen[id(arr)]})")
                seen[id(arr)] = f"{f.name}.{k}"
        from znicz_tpu.lr_adjust import LearningRateAdjust

        #: a user-wired LearningRateAdjust unit advances once per TRAIN
        #: step here too (the unit graph runs it per lap, gated like the
        #: gds); scans take per-step hypers as xs so LR schedules apply
        #: with per-step granularity, exactly as in the unit path
        self._lr_adjust = next(
            (u for u in workflow.units
             if isinstance(u, LearningRateAdjust)), None)
        self._train_step = None
        self._train_scan = None
        self._eval_step = None
        self._eval_scan = None
        #: the live DeviceStager while a staged run is inside
        #: _run_segmented with async staging on (tests/bench observe it)
        self._stager = None
        self._key0 = prng.get("fused_trainer").jax_key(0)
        self.steps_done = 0
        #: per-step timing accumulated by run() (SURVEY.md §5 Tracing —
        #: the fast path reports like the unit path's timing table does);
        #: surfaced by Workflow.print_stats and web_status /status.json
        #: via ``workflow.fused_stats``
        #: ``warm_*`` exclude each dispatch kind's FIRST call (which pays
        #: jit compilation) — the steady-state numbers; ``wall_s`` etc.
        #: are totals including compiles
        self.stats = {"train_steps": 0, "eval_steps": 0, "images": 0,
                      "wall_s": 0.0, "steps_per_sec": 0.0,
                      "img_per_sec": 0.0, "last_step_ms": 0.0,
                      "warm_steps": 0, "warm_images": 0, "warm_wall_s": 0.0,
                      "warm_img_per_sec": 0.0}
        workflow.fused_stats = self.stats
        # telemetry (ISSUE 5): hot-loop metrics + spans.  The registry
        # counters/histogram observe only while telemetry is enabled —
        # bench.py --telemetry gates the whole layer's cost (<2%) by
        # interleaving enabled/disabled windows of this very loop.
        from znicz_tpu import telemetry

        self._telemetry = telemetry
        self._tracer = telemetry.tracer()
        _sc = telemetry.scope("trainer")
        self._m_train_steps = _sc.counter("train_steps",
                                          "fused train steps dispatched")
        self._m_images = _sc.counter("images", "training images consumed")
        self._m_step_seconds = _sc.histogram(
            "step_seconds", "per-step wall time (pipelined intervals)",
            size=4096)
        #: compute dtype (activations + gradients; master weights stay
        #: f32): ``root.common.engine.compute_dtype`` is the canonical
        #: knob ("float32" | "bf16" | "bfloat16"); the pre-r12
        #: ``precision`` spelling is kept as the legacy alias and applies
        #: only when compute_dtype is unset.
        cd = root.common.engine.get("compute_dtype", None)
        if cd is None:
            cd = root.common.engine.get("precision", "float32")
        cd = {"bf16": "bfloat16"}.get(str(cd), str(cd))
        if cd not in ("float32", "bfloat16"):
            raise ValueError(
                f"root.common.engine.compute_dtype={cd!r}: must be "
                "'float32' or 'bf16'/'bfloat16'")
        self.compute_dtype = (np.dtype("float32") if cd == "float32"
                              else "bfloat16")
        #: the per-step compute_dtype label on /metrics (ISSUE 7
        #: satellite): a labeled gauge, so the TPU session's dashboards
        #: can tell WHICH precision a run's step timings belong to
        #: without a profiler
        _sc.gauge("compute_dtype", "active compute dtype (value always 1;"
                  " read the dtype label)", dtype=cd).set(1)
        #: trace-time tick per compiled fused executable (the serving
        #: layer's zero-recompile method, now on the training path):
        #: Python runs a jitted wrapper's body only when jax (re)traces,
        #: so ``compiles`` == executable-cache entries, cross-checkable
        #: against ``jit_cache_sizes()``
        self._m_compiles = _sc.counter(
            "compiles", "traces of the fused step/scan executables == "
            "jit cache entries")
        #: OPT-IN bf16 MASTER weights (root.common.engine.master_dtype =
        #: "bfloat16", fused path only): params are STORED bf16 — the
        #: per-step read+write of the full param set halves (AlexNet fc:
        #: the dominant non-MXU traffic after the r4 bf16 velocities) —
        #: while the update arithmetic stays f32 (cast up, update, cast
        #: back).  This CHANGES convergence semantics (weight rounding):
        #: a labeled bench variant (--master-bf16), never the headline
        #: or the anchors.
        md = str(root.common.engine.get("master_dtype", "float32"))
        if md not in ("float32", "bfloat16"):
            raise ValueError(
                f"root.common.engine.master_dtype={md!r}: must be "
                "'float32' or 'bfloat16'")
        self._master_dtype = None if md == "float32" else "bfloat16"
        #: u8 storage decodes to ``u8*scale + shift`` in-graph
        #: (loader/streaming.py; plain f32 loaders never hit the decode)
        self._decode_params = (np.float32(getattr(self.loader, "scale", 1.0)),
                               np.float32(getattr(self.loader, "shift", 0.0)))

    @property
    def staging(self) -> bool:
        """True when the dataset is host-side and every dispatch's samples
        must be staged through host_gather + device_put (streaming regime 3
        — loader/streaming.py).  Resolved lazily: ``device_resident`` is
        decided by the loader's initialize."""
        ldr = self.loader
        return (bool(getattr(ldr, "streaming", False))
                and not ldr.device_resident)

    # -- state extraction ------------------------------------------------------

    def _op_value(self, arr):
        """An Array's value for the fused step's operands.  Multi-
        controller meshes take the HOST buffer: global_put re-distributes
        it shard-by-shard, and detouring through ``devmem`` would pay a
        full extra H2D+D2H round trip on local device 0 first."""
        if self.mesh is not None:
            import jax

            if jax.process_count() > 1:
                if arr.cross_host_sharded:
                    # devmem already spans hosts (e.g. restore_sharded
                    # placed it) — hand the global array straight through.
                    # But only while it is CURRENT: a host write since
                    # (map_write/map_invalidate) means the sharded buffer
                    # is stale, and host collection cannot reshard a
                    # cross-host Array implicitly — silently returning it
                    # would train on outdated state.
                    if arr.host_dirty:
                        raise RuntimeError(
                            "cross-host-sharded Array has a NEWER host "
                            "copy than its device shards; re-distribute "
                            "it explicitly (global_put / restore_sharded) "
                            "before extracting fused-step state")
                    # A DELETED buffer (donated into a prior step) must
                    # not fall through here: it would surface later as a
                    # confusing "Array has been deleted" inside jit
                    # (ADVICE r4).
                    if arr._devmem.is_deleted():
                        raise RuntimeError(
                            "param/velocity device buffer was donated "
                            "away; refresh the unit Arrays (writeback) "
                            "before re-extracting state")
                    return arr._devmem
                return arr.map_read()
        return arr.devmem

    def _cast_master(self, v):
        """Storage-dtype cast for a param leaf (jax array or host numpy)
        under the bf16-master option; identity otherwise."""
        md = self._master_dtype
        if md is None or str(v.dtype) == md:
            return v
        import ml_dtypes

        if isinstance(v, np.ndarray):
            return v.astype(ml_dtypes.bfloat16)
        return v.astype(md)

    def extract_params(self) -> Dict[str, Dict[str, object]]:
        return {f.name: {k: self._cast_master(self._op_value(a))
                         for k, a in f.params().items()}
                for f in self.forwards if f.has_weights}

    def extract_velocities(self):
        out = {}
        for f in self.forwards:
            gd = self.gd_of.get(f.name)
            if gd is not None and f.has_weights:
                out[f.name] = {k: self._op_value(a)
                               for k, a in gd._velocities.items()}
        return out

    def hypers(self):
        out = {}
        for f in self.forwards:
            gd = self.gd_of.get(f.name)
            if gd is not None and f.has_weights:
                out[f.name] = tuple(np.float32(v) for v in (
                    gd.learning_rate, gd.learning_rate_bias,
                    gd.weights_decay, gd.weights_decay_bias, gd.l1_vs_l2,
                    gd.gradient_moment, gd.gradient_moment_bias,
                    gd.gradient_clip))
        return out

    def tiled_hypers(self, k: int):
        """Per-step hypers rows for a k-step scan with CONSTANT hypers —
        the one home for the scan's hypers-xs layout (callers without an
        LR schedule: bench, dryrun, hypers_rows' fast path)."""
        return {name: np.tile(np.asarray(t, np.float32), (k, 1))
                for name, t in self.hypers().items()}

    def restore_sharded(self, path: str):
        """Cross-topology checkpoint resume (SURVEY §5 checkpoint row):
        load an orbax checkpoint saved under ANY mesh topology and deliver
        every param/velocity leaf already placed in THIS trainer's
        shardings — orbax/tensorstore reads each target shard directly, no
        host-gather round-trip.  Loader/decision/prng metadata is applied
        like the standard restore.  Returns the meta dict.

        Dtype: the checkpoint stores each leaf in whatever precision was
        configured WHEN IT WAS SAVED (``state_dtype`` may differ between
        the saving and resuming runs).  The restore template asks orbax
        for the leaf in the dtype of the LIVE Array — i.e. the currently
        configured precision — and any residual mismatch is cast
        explicitly below rather than left to tensorstore's implicit
        behavior (ADVICE r4)."""
        import jax
        from jax.sharding import SingleDeviceSharding

        from znicz_tpu import snapshotter as snap_mod

        def sds(name, k, shape, dtype):
            probe = jax.ShapeDtypeStruct(tuple(shape), dtype)
            sharding = (self.param_sharding(name, k, probe)
                        if self.mesh is not None
                        else SingleDeviceSharding(jax.local_devices()[0]))
            return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                        sharding=sharding)

        units = {f.name: {k: sds(f.name, k, a.shape, a.dtype)
                          for k, a in f.params().items()}
                 for f in self.forwards if f.has_weights}
        vels = {gd.name: {k: sds(gd.forward.name, k, a.shape, a.dtype)
                          for k, a in gd._velocities.items()}
                for gd in self.workflow.gds}
        arrays = snap_mod.load_orbax_arrays(
            path, {"units": units, "velocities": vels})

        def adopt(leaf, a):
            a.devmem = (leaf if leaf.dtype == a.dtype
                        else leaf.astype(a.dtype))

        for f in self.forwards:
            if not f.has_weights:
                continue
            for k, a in f.params().items():
                adopt(arrays["units"][f.name][k], a)
            gd = self.gd_of.get(f.name)
            if gd is not None:
                for k, a in gd._velocities.items():
                    adopt(arrays["velocities"][gd.name][k], a)
        meta = snap_mod.load_orbax_meta(path)
        snap_mod.restore(self.workflow,
                         {**meta, "units": {}, "velocities": {}})
        return meta

    def snapshot_from_trees(self, params, velocities) -> Dict:
        """A snapshot dict built DIRECTLY from the fused device trees —
        no unit-Array writeback, no host round-trip on the training
        thread.  Param/velocity leaves stay device arrays; the
        snapshotter's async worker pulls them while the next epoch
        computes (VERDICT r4 item 4).  Velocities are saved in their live
        ``state_dtype`` (bf16 state -> bf16 checkpoint, half the bytes)."""
        from znicz_tpu import snapshotter as snap_mod

        snap = snap_mod.collect_meta(self.workflow)
        snap["config"] = root.to_dict()
        for f in self.forwards:
            if not f.has_weights:
                continue
            snap["units"][f.name] = dict(params[f.name])
            gd = self.gd_of.get(f.name)
            if gd is not None:
                snap["velocities"][gd.name] = dict(velocities[f.name])
        return snap

    def _async_snapshot_enabled(self, snap) -> bool:
        """Async (non-stalling) snapshots apply to host-format saves when
        ``root.common.engine.async_snapshot`` (default True) is on; orbax
        saves are multi-process collectives and stay synchronous."""
        return (snap is not None and snap.format != "orbax"
                and bool(root.common.engine.get("async_snapshot", True)))

    def _drain_snapshots(self, suppress: bool) -> None:
        """Block until queued async saves are durably written.  With
        ``suppress`` (an exception already in flight) a writer error is
        swallowed rather than masking the real failure."""
        snap = getattr(self.workflow, "snapshotter", None)
        if snap is None:
            return
        try:
            snap.flush_async()
        except Exception:
            if not suppress:
                raise

    def writeback(self, params, velocities) -> None:
        """Push fused-step results back into the unit Arrays (snapshotter /
        plotters / unit-mode interop see the same state)."""
        for f in self.forwards:
            if f.has_weights:
                for k, a in f.params().items():
                    a.devmem = params[f.name][k]
                gd = self.gd_of.get(f.name)
                if gd is not None:
                    for k, a in gd._velocities.items():
                        a.devmem = velocities[f.name][k]

    # -- the pure step ---------------------------------------------------------

    def forward_pass(self, params, x, key, train: bool, cast=None):
        """Compose the units' pure applies; returns the last unit's output
        (LOGITS for a softmax last layer — loss and probs both derive from
        them, matching the evaluator's math).  ``cast`` re-casts activations
        between layers in mixed precision (matmul/conv accumulate f32 via
        preferred_element_type, outputs drop back to bf16).

        With ``root.common.engine.fused_elementwise`` on, every matched
        conv1/conv2-style block (Conv+bias+StrictRELU -> LRN -> exactly-
        tiling MaxPooling) runs as the raw conv plus ONE single-pass
        Pallas kernel whose custom vjp is the fused backward — the graph
        the GradientDescent* chain would otherwise differentiate op by op
        (pallas_fused_block; plan computed per trace, shapes unchanged).

        With ``root.common.engine.fused_tail`` on (ISSUE 7), the REST of
        the AlexNet shape fuses too: conv3-5-style bias+StrictRELU as one
        Pallas pass each way (``fused_bias_relu``), and the FC layers'
        bias+ReLU+dropout epilogue as one custom-vjp stage whose backward
        recomputes the masks from (input, bias, key) instead of loading
        them from HBM (``fused_fc_epilogue`` — the dropout key is the
        absorbed unit's own ``fold_in(key, i)`` draw, so masks are
        bit-identical to the unit path's)."""
        import jax

        from znicz_tpu.ops.linear import linear
        from znicz_tpu.pallas_fused_block import (fused_bias_relu,
                                                  fused_block,
                                                  fused_fc_epilogue,
                                                  plan_fused_blocks,
                                                  plan_fused_tail)

        plan = plan_fused_blocks(self.forwards)
        tail_plan = plan_fused_tail(self.forwards, plan)
        h = x
        last = self.forwards[-1]
        i = 0
        while i < len(self.forwards):
            f = self.forwards[i]
            if cast is not None:
                h = cast(h)
            p = params.get(f.name, {})
            blk = plan.get(i)
            if blk is not None:
                h = f.apply_linear(p, h)
                h = fused_block(h, p["bias"], blk.n, blk.alpha, blk.beta,
                                blk.k, blk.pool)
                # dropout/stochpool never sit inside a fused block, so
                # later units keep their own fold_in(key, i) indices
                i += blk.span
                continue
            tl = tail_plan.get(i)
            if tl is not None:
                if tl.kind == "conv_bias_relu":
                    h = f.apply_linear(p, h)
                    h = fused_bias_relu(h, p["bias"])
                elif tl.kind == "seq_epilogue":
                    # position-wise FFN (ISSUE 15): the raw per-token
                    # matmul plus the SAME fused bias+ReLU custom-vjp
                    # epilogue fc6/fc7 ride (no dropout absorbed; the
                    # backward recomputes the gate from (y, bias))
                    from znicz_tpu.ops.linear import seq_linear

                    y = seq_linear(h, p["weights"],
                                   weights_transposed=f.weights_transposed)
                    h = fused_fc_epilogue(y, p["bias"], None, 0.0, False)
                else:                           # fc_epilogue
                    y = linear(h, p["weights"],
                               weights_transposed=f.weights_transposed)
                    masked = train and tl.dropout_index >= 0
                    k = (jax.random.fold_in(key, tl.dropout_index)
                         if masked else None)
                    y = fused_fc_epilogue(y, p["bias"], k, tl.ratio,
                                          masked)
                    h = y.reshape((x.shape[0],) + f.output_sample_shape)
                i += tl.span
                continue
            if isinstance(f, self._dropout_cls):
                if train:
                    k = jax.random.fold_in(key, i)
                    m = f.make_mask(k, h.shape, f.dropout_ratio)
                    h = h * m
                # eval: identity
            elif isinstance(f, self._stochpool_cls):
                win = f.windows(h)
                if train:
                    k = jax.random.fold_in(key, i)
                    h, _ = f._select_stochastic(win, k)
                else:
                    h, _ = f._select_expected(win)
            elif f is last and isinstance(f, self._softmax_cls):
                h = linear(h, p["weights"], p.get("bias"),
                           weights_transposed=f.weights_transposed)
                h = h.reshape((x.shape[0],) + f.output_sample_shape)
            elif f is last and isinstance(f, self._seq_softmax_cls):
                # per-position logits (ISSUE 15): the softmax is folded
                # into the loss head exactly like the All2AllSoftmax path
                from znicz_tpu.ops.linear import seq_linear

                h = seq_linear(h, p["weights"], p.get("bias"),
                               weights_transposed=f.weights_transposed)
            else:
                h = f.apply(p, h)
            i += 1
        return h

    def loss_and_metrics(self, params, data, target, batch_size, key,
                         train: bool):
        import jax.numpy as jnp

        import jax

        if self.compute_dtype == np.dtype("float32"):
            cast = None
            cparams = params
            out = self.forward_pass(cparams, data, key, train)
        else:
            def cast(t):
                return t.astype("bfloat16") if t.dtype == jnp.float32 else t

            cparams = jax.tree_util.tree_map(cast, params)
            out = self.forward_pass(cparams, cast(data), key, train,
                                    cast=cast)
        out = out.astype("float32")
        n = out.shape[0]
        valid = (jnp.arange(n) < batch_size)
        denom = jnp.maximum(batch_size, 1)
        if self.loss_kind == "softmax":
            logits = out
            labels = target
            if logits.ndim == 3:
                # sequence head (ISSUE 15): every token of every valid
                # row is one classification — flatten tokens into the
                # batch axis and keep the identical per-class math
                # (EvaluatorSeqSoftmax mirrors this; they must not
                # drift).  denom scales to tokens so the reported loss
                # stays a per-token mean.
                t = logits.shape[1]
                logits = logits.reshape(n * t, logits.shape[-1])
                labels = labels.reshape(n * t).astype(jnp.int32)
                valid = jnp.repeat(valid, t)
                denom = jnp.maximum(batch_size * t, 1)
            from znicz_tpu.pallas_fused_block import (fused_softmax_xent,
                                                      fused_tail_enabled)

            if fused_tail_enabled():
                # ISSUE 7: loss + logits-cotangent as ONE custom-vjp
                # epilogue (same formula; backward re-reads logits
                # instead of consuming saved softmax/logsumexp residuals)
                loss = fused_softmax_xent(logits, labels, valid, denom)
            else:
                logz = jax.nn.logsumexp(logits, axis=-1)
                ll = jnp.take_along_axis(logits, labels[:, None],
                                         axis=-1)[:, 0]
                loss = jnp.sum(jnp.where(valid, logz - ll, 0.0)) / denom
            pred = jnp.argmax(logits, axis=-1)
            n_err = jnp.sum((pred != labels) & valid)
            if self.compute_confusion:
                n_classes = logits.shape[-1]
                conf = jnp.zeros((n_classes, n_classes), jnp.int32).at[
                    pred, labels].add(valid.astype(jnp.int32))
            else:
                conf = jnp.zeros((1, 1), jnp.int32)
            return loss, (loss, n_err, conf)
        else:
            y = out.reshape(n, -1)
            t = target.reshape(n, -1)
            diff = (y - t) * valid[:, None]
            loss = 0.5 * jnp.sum(jnp.square(diff)) / denom
            return loss, (loss, jnp.int32(0), jnp.zeros((1, 1), jnp.int32))

    #: FC layers at least this wide get tensor-parallel row sharding when
    #: the mesh has a ``model`` axis (AlexNet's 4096-wide fc6/fc7)
    tp_threshold = 1024

    #: rematerialize the forward during backward (``jax.checkpoint``) —
    #: trades ~1/3 more FLOPs for not keeping activations live, the
    #: standard HBM lever for big batches/models
    #: (root.common.engine.remat or FusedTrainer(..., remat=True))
    remat = False

    def param_sharding(self, name, k, arr):
        """Per-param placement: wide (out, in) FC weights shard their output
        rows over the ``model`` axis (and the matching bias over ``model``);
        everything else replicates.  The rule itself lives in the shared
        placement home (``parallel.mesh.param_sharding``); this method keeps
        the historical (name, k, arr) signature for serving/restore."""
        from znicz_tpu.parallel.mesh import param_sharding

        return param_sharding(self.mesh, arr, self.tp_threshold)

    @property
    def mesh_shape(self):
        """``{"data": dp, "model": mp}`` (None single-device) — the
        heartbeat form, piggybacked on slave registration."""
        from znicz_tpu.parallel.mesh import mesh_shape_dict

        return mesh_shape_dict(self.mesh)

    def place_state(self, tree):
        """Distribute a params/velocities tree onto the mesh per the
        shared ``param_sharding`` rule; identity when single-device (the
        tree is already placed by extraction)."""
        if self.mesh is None:
            return tree
        from znicz_tpu.parallel.mesh import place_tree

        return place_tree(self.mesh, tree, self.tp_threshold)

    def _state_shardings(self):
        """(params tree shardings, velocities tree shardings, replicated)
        for the live mesh — the explicit ``in_shardings``/``out_shardings``
        every mesh-jitted step/scan declares.  Params replicate or
        column-shard per ``param_sharding``; with the batch split over
        ``data``, jax.grad's gradients demand replication, so GSPMD
        inserts the ``lax.psum`` over the ``data`` axis INSIDE the
        executable — the intra-slice (ICI) tier of the two-tier
        reduction.  The host-side wire-v3 delta tier never sees it."""
        from znicz_tpu.parallel.mesh import replicated, tree_shardings

        psh = tree_shardings(
            self.mesh,
            {f.name: dict(f.params())
             for f in self.forwards if f.has_weights},
            self.tp_threshold)
        vsh = tree_shardings(
            self.mesh,
            {f.name: dict(self.gd_of[f.name]._velocities)
             for f in self.forwards
             if f.has_weights and self.gd_of.get(f.name) is not None},
            self.tp_threshold)
        return psh, vsh, replicated(self.mesh)

    def _jit_shardings(self, in_specs, out_specs):
        """jax.jit kwargs: explicit shardings on a mesh, empty (the
        byte-identical historical jit call) single-device."""
        if self.mesh is None:
            return {}
        return {"in_shardings": in_specs, "out_shardings": out_specs}

    def _decode(self, data):
        """Storage decode IN-GRAPH: u8 data (HBM u8-residency or a
        host-staged u8 segment — loader/streaming.py) decodes
        ``u8*scale + shift``, fused by XLA into whatever produced it, so
        HBM/link traffic stays 1 byte/value and the f32 tensor only ever
        exists inside the step."""
        import jax.numpy as jnp

        if data.dtype == jnp.uint8:
            scale, shift = self._decode_params
            data = data.astype(jnp.float32) * scale + shift
        return data

    def _gather_decode(self, dataset, idx):
        import jax.numpy as jnp

        return self._decode(jnp.take(dataset, idx, axis=0))

    def _step_core(self, params, velocities, hypers, dataset, targets, idx,
                   batch_size, key):
        """One pure train step (traced): gather -> fwd -> grads -> per-layer
        sgd update.  Shared by the single-step jit and the scan chunk.
        The gather hands RAW storage-dtype rows to ``_update_core``, which
        owns the decode (single decode point on the update path)."""
        import jax.numpy as jnp

        return self._update_core(params, velocities, hypers,
                                 jnp.take(dataset, idx, axis=0),
                                 jnp.take(targets, idx, axis=0),
                                 batch_size, key)

    def _update_core(self, params, velocities, hypers, data, tgt,
                     batch_size, key):
        """The post-gather step math: fwd -> grads -> per-layer sgd
        update, on an already-materialized minibatch (the gather path and
        the staged-direct path share it)."""
        import jax

        data = self._decode(data)
        if self.mesh is not None:
            # the minibatch is what shards over the data axis (XLA then
            # keeps the whole fwd/bwd batch-sharded and psums the grads
            # over ICI); for staged-direct inputs already sharded this
            # way the constraint is a no-op
            from znicz_tpu.parallel.mesh import data_sharding

            shard = data_sharding(self.mesh)
            data = jax.lax.with_sharding_constraint(data, shard)
            tgt = jax.lax.with_sharding_constraint(tgt, shard)

        def lf(p):
            return self.loss_and_metrics(p, data, tgt, batch_size, key,
                                         train=True)

        if self.remat:
            # recompute the forward during the backward instead of keeping
            # activations live (SURVEY hot-path note: remat is the HBM
            # lever; ~1/3 extra FLOPs)
            lf = jax.checkpoint(lf)
        grads, metrics = jax.grad(lf, has_aux=True)(params)
        new_p, new_v = {}, {}
        for name, layer_p in params.items():
            lr, lrb, wd, wdb, l1l2, mom, momb, clip = hypers[name]
            new_p[name], new_v[name] = {}, {}
            for k, w in layer_p.items():
                g = grads[name][k].astype("float32")
                is_bias = (k == "bias")
                # bf16-master: storage bf16, update arithmetic f32 (the
                # cast pair fuses into the update; traffic is what the
                # storage dtype says)
                w_in = (w if self._master_dtype is None
                        else w.astype("float32"))
                p_new, v_new = sgd_update(
                    w_in, g, velocities[name][k],
                    lr=(lrb if is_bias else lr),
                    weights_decay=(wdb if is_bias else wd),
                    l1_vs_l2=l1l2,
                    momentum=(momb if is_bias else mom), clip=clip)
                if self._master_dtype is not None:
                    p_new = p_new.astype(self._master_dtype)
                new_p[name][k], new_v[name][k] = p_new, v_new
        return new_p, new_v, metrics

    def make_train_step(self):
        """The step takes ``hypers`` as a traced argument so per-epoch lr
        adjustment (LearningRateAdjust) never recompiles.  On a mesh the
        jit declares explicit shardings (``_state_shardings``): params
        pinned to their placements, batch operands replicated (the
        in-step gather + constraint shard the minibatch over ``data``)."""
        import jax

        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            psh, vsh, repl = self._state_shardings()
            kw = self._jit_shardings(
                (psh, vsh, repl, repl, repl, repl, repl, repl),
                (psh, vsh, repl))

        def step(params, velocities, hypers, dataset, targets, idx,
                 batch_size, key):
            compiles.inc()              # trace-time tick (one per compile)
            return self._step_core(params, velocities, hypers, dataset,
                                   targets, idx, batch_size, key)

        return jax.jit(step, donate_argnums=(0, 1), **kw)

    def jit_cache_sizes(self) -> Dict[str, int]:
        """jax's own executable-cache entry counts for the live jitted
        step/scan functions (the pjit cache behind ``_cache_size``; absent
        entries mean the jax version does not expose it).  After warmup
        the SUM equals ``compiles`` and must stay put — the training-path
        zero-recompile proof (same method as serving's ModelRunner)."""
        out: Dict[str, int] = {}
        for name in ("_train_step", "_train_scan", "_eval_step",
                     "_eval_scan"):
            fn = getattr(self, name, None)
            if fn is None:
                continue
            try:
                out[name] = int(fn._cache_size())
            except Exception:           # pragma: no cover - jax-version dep
                pass
        return out

    def _n_confusion(self) -> int:
        return (self.forwards[-1].output_samples_number
                if self.loss_kind == "softmax" and self.compute_confusion
                else 1)

    def _train_body(self, base_key, unpack):
        """The ONE home of the scanned train-step body — the gather
        variant (resident datasets, xs carry indices) and the staged-
        direct variant (xs carry the minibatches themselves) share it via
        ``unpack(xs) -> (data, tgt, bs, step, hypers)``: carry = (params,
        velocities, confusion sum).  Per-step keys are ``fold_in(base,
        step)`` IN-GRAPH — identical to the sequential path's draws
        (eager key construction costs several dispatches each, ~3ms/key
        on tunneled links).  Confusion SUMS on device in the carry:
        stacking K (C,C) matrices and pulling them per step was the
        real-training bottleneck on slow links (28MB/segment for the
        1000-class head); the Decision only accumulates."""
        import jax

        def body(carry, xs):
            p, v, conf_acc = carry
            data, tgt, bs, step, hypers = unpack(xs)
            key = jax.random.fold_in(base_key, step)
            p, v, (loss, n_err, conf) = self._update_core(
                p, v, hypers, data, tgt, bs, key)
            return (p, v, conf_acc + conf), (loss, n_err)

        return body

    def _train_scan_body(self, dataset, targets, base_key):
        """Gather variant of ``_train_body``: xs = (idx, batch_size,
        step_number, hypers row), rows gathered from the resident
        dataset (used by the segmented chunks and the deep epoch fn)."""
        import jax.numpy as jnp

        def unpack(xs):
            idx, bs, step, hypers = xs
            return (jnp.take(dataset, idx, axis=0),
                    jnp.take(targets, idx, axis=0), bs, step, hypers)

        return self._train_body(base_key, unpack)

    def _eval_body(self, params, unpack):
        """The ONE home of the scanned eval body (params frozen — a pure
        map): carry = confusion sum; ``unpack(xs) -> (decoded data, tgt,
        bs)``."""

        def body(conf_acc, xs):
            data, tgt, bs = unpack(xs)
            _, (loss, n_err, conf) = self.loss_and_metrics(
                params, data, tgt, bs, self._key0, train=False)
            return conf_acc + conf, (loss, n_err)

        return body

    def _eval_scan_body(self, params, dataset, targets):
        """Gather variant of ``_eval_body``: xs = (idx, batch_size)."""
        import jax.numpy as jnp

        def unpack(xs):
            idx, bs = xs
            return (self._gather_decode(dataset, idx),
                    jnp.take(targets, idx, axis=0), bs)

        return self._eval_body(params, unpack)

    def make_train_scan(self):
        """K steps in ONE dispatch via ``lax.scan`` over stacked
        (idx, batch_size, step_number) rows — K is static per (K,) shape.
        Each scanned step is the same ``_step_core`` with the same per-step
        key the sequential path would draw, so semantics are identical;
        what changes is dispatch count, which dominates wall time on
        high-latency links (tunneled TPU: ~20ms/dispatch vs ~5ms compute —
        bench r3).  Metrics come back stacked, one per step."""
        import jax

        import jax.numpy as jnp

        nc = self._n_confusion()
        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            psh, vsh, repl = self._state_shardings()
            kw = self._jit_shardings(
                (psh, vsh, repl, repl, repl, repl, repl, repl, repl),
                (psh, vsh, repl, repl))

        def chunk(params, velocities, hypers_mat, dataset, targets,
                  idx_mat, bs_vec, base_key, step_nums):
            compiles.inc()
            (p, v, conf_sum), ms = jax.lax.scan(
                self._train_scan_body(dataset, targets, base_key),
                (params, velocities, jnp.zeros((nc, nc), jnp.int32)),
                (idx_mat, bs_vec, step_nums, hypers_mat))
            return p, v, ms, conf_sum

        return jax.jit(chunk, donate_argnums=(0, 1), **kw)

    def make_eval_scan(self):
        """Metrics for K eval minibatches (TEST/VALID) in one dispatch —
        params don't change between eval steps, so the scan is a pure map;
        metrics come back stacked and are fed to the Decision in order."""
        import jax

        import jax.numpy as jnp

        nc = self._n_confusion()
        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            psh, _, repl = self._state_shardings()
            kw = self._jit_shardings((psh, repl, repl, repl, repl),
                                     (repl, repl))

        def chunk(params, dataset, targets, idx_mat, bs_vec):
            compiles.inc()
            conf_sum, ms = jax.lax.scan(
                self._eval_scan_body(params, dataset, targets),
                jnp.zeros((nc, nc), jnp.int32), (idx_mat, bs_vec))
            return ms, conf_sum

        return jax.jit(chunk, **kw)

    def make_eval_step(self):
        """Metrics-only step.  ``train`` is static: True replays the exact
        train-mode forward (dropout/stochastic masks from the same key) —
        used at epoch tails to let the Decision rule on this minibatch's
        metrics BEFORE the update is adopted, matching the unit path where
        gd_skip gates the final update off once ``complete`` flips."""
        import jax

        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            # in_shardings entries cover the DYNAMIC args only (the
            # static ``train`` flag is excluded)
            psh, _, repl = self._state_shardings()
            kw = self._jit_shardings((psh, repl, repl, repl, repl, repl),
                                     repl)

        def step(params, dataset, targets, idx, batch_size, key, train):
            compiles.inc()
            data = self._gather_decode(dataset, idx)
            tgt = jax.numpy.take(targets, idx, axis=0)
            _, metrics = self.loss_and_metrics(
                params, data, tgt, batch_size, key, train=train)
            return metrics

        return jax.jit(step, static_argnums=(6,), **kw)

    # -- the epoch driver ------------------------------------------------------

    #: scan this many consecutive TRAIN steps per dispatch (the epoch tail
    #: and eval minibatches always go one-at-a-time, preserving the
    #: Decision's gd_skip semantics).  1 disables scanning.
    scan_chunk = 8

    def _advance(self):
        """Advance the loader one minibatch and snapshot its state (the
        fused path consumes index state only — ``indices_only``)."""
        loader = self.loader
        loader.run()
        return {
            "idx": np.array(loader.minibatch_indices.mem, np.int32),
            "class": int(loader.minibatch_class),
            "size": int(loader.minibatch_size),
            "last_minibatch": bool(loader.last_minibatch),
            "class_ended": bool(loader.class_ended),
            "epoch_number": int(loader.epoch_number),
        }

    #: >1 enables the DEEP pipeline: whole epochs dispatched as single
    #: executables with every metric pull deferred by up to this many
    #: epochs (one fused scalar transfer per epoch).  Engages only when
    #: nothing consumes host state at epoch granularity (no plotters,
    #: snapshotter absent/gated) — see ``_deep_eligible``.  Identical
    #: training semantics: stops are rolled back to the exact stopping
    #: state (``root.common.engine.pipeline_depth``).
    pipeline_depth = 1

    def _feed_decision(self, mb, metrics):
        loss, n_err, conf = metrics
        decision = self.decision
        decision.minibatch_class = mb["class"]
        decision.last_minibatch = mb["last_minibatch"]
        decision.class_ended = mb["class_ended"]
        decision.epoch_number = mb["epoch_number"]
        decision.class_lengths = self.loader.class_lengths
        decision.minibatch_size = mb["size"]
        decision.minibatch_loss = float(loss)
        if hasattr(decision, "minibatch_n_err"):
            decision.minibatch_n_err = int(n_err)
            # None = already accounted via a device-side running sum
            # (DecisionBase skips None); the matrix stays a DEVICE
            # array — the decision accumulates it on device and the
            # (C,C) transfer happens only when a consumer reads it
            decision.confusion_matrix = conf
        decision.run()

    def _reset_accounting(self):
        self._acct_seen = set()
        self._acct_last_end = None

    def _account(self, n_steps, n_images, t0, is_train, kind="train",
                 n_eval=0):
        # charge [max(t0, last interval end), now]: with the pipeline,
        # segment N's flush happens during iteration N+1, whose own
        # t0 predates the flush — naive (now - t0) intervals overlap
        # and double-count wall time.  ``n_eval`` books the eval share of
        # a mixed (whole-epoch) interval under eval_steps.
        import time as _time

        stats = self.stats
        now = _time.perf_counter()
        start = t0 if self._acct_last_end is None \
            else max(t0, self._acct_last_end)
        dt = max(now - start, 1e-9)
        self._acct_last_end = now
        if self._tracer.enabled:            # the optional layer (ISSUE 5)
            self._m_step_seconds.observe(dt / max(n_steps + n_eval, 1))
        if is_train:
            # accounting, not overhead-sensitive spans: progress counters
            # keep moving even with telemetry disabled (a dashboard
            # watching train_steps must never read a live run as stalled)
            self._m_train_steps.inc(n_steps)
            self._m_images.inc(n_images)
        stats["wall_s"] += dt
        stats["last_step_ms"] = round(dt / (n_steps + n_eval) * 1e3, 3)
        if is_train:
            stats["train_steps"] += n_steps
            stats["images"] += n_images
            stats["eval_steps"] += n_eval
        else:
            stats["eval_steps"] += n_steps + n_eval
        total = stats["train_steps"] + stats["eval_steps"]
        stats["steps_per_sec"] = round(total / stats["wall_s"], 2)
        stats["img_per_sec"] = round(
            stats["images"] / stats["wall_s"], 2)
        if kind in self._acct_seen:     # first call of a kind pays compile
            stats["warm_steps"] += n_steps + n_eval
            stats["warm_images"] += n_images
            stats["warm_wall_s"] += dt
            if stats["warm_wall_s"] > 0:
                stats["warm_img_per_sec"] = round(
                    stats["warm_images"] / stats["warm_wall_s"], 2)
        self._acct_seen.add(kind)

    def _device_state(self):
        """Params/velocities/dataset/targets as device values (mesh
        placement applied) plus ``put`` for per-dispatch host operands.
        In staging mode dataset/targets are None — every dispatch ships
        its own staged segment instead."""
        loader = self.loader
        params = self.extract_params()
        velocities = self.extract_velocities()
        if self.staging:
            dataset = targets = None
        elif self.loss_kind == "softmax":
            dataset = self._op_value(loader.original_data)
            targets = self._op_value(loader.original_labels)
        else:
            dataset = self._op_value(loader.original_data)
            targets = self._op_value(loader.original_targets)
        if self.mesh is None:
            if self.staging:
                # explicit async put: the staged segment's transfer starts
                # immediately and overlaps the in-flight dispatch, instead
                # of riding the next jit call's implicit transfer
                import jax

                return params, velocities, None, None, jax.device_put
            return params, velocities, dataset, targets, lambda x: x
        from znicz_tpu.parallel.mesh import global_put, replicated

        repl = replicated(self.mesh)
        params = self.place_state(params)
        velocities = self.place_state(velocities)
        if dataset is not None:
            dataset = global_put(dataset, repl)
            targets = global_put(targets, repl)
        return (params, velocities, dataset, targets,
                lambda x: global_put(x, repl))

    def _stage_direct(self, idx_rows, put):
        """Assemble + ship ONE dispatch's samples (streaming regime 3) as
        (K, B, ...) minibatch tensors consumed DIRECTLY by the staged
        step/scan variants (no in-step gather).  Storage dtype crosses
        the link (u8 is 4x less traffic; decode happens in-graph).

        Placement: on a mesh the tensors are batch-sharded
        ``P(None, "data")``; in a MULTI-CONTROLLER run each process
        host-gathers ONLY the rows of the batch shards its own devices
        hold (jax.make_array_from_callback) — the SPMD analogue of the
        reference's master/slave per-slave minibatch feed: no host ever
        touches another host's samples.  Dispatch is async either way, so
        segment N+1's assembly overlaps segment N's compute."""
        loader = self.loader
        idx_mat = np.stack([np.asarray(r, np.int32) for r in idx_rows])
        n_steps, batch = idx_mat.shape
        if self.loss_kind == "softmax":
            tgt_gather = loader.host_gather_labels
            tgt_sample = ()
        else:
            tgt_gather = loader.host_gather_targets
            tgt_sample = tuple(loader.original_targets.mem.shape[1:])
        shape_d = (n_steps, batch) + tuple(loader.source.sample_shape)
        shape_t = (n_steps, batch) + tgt_sample
        if self.mesh is None:
            flat = idx_mat.reshape(-1)
            return (put(loader.host_gather(flat).reshape(shape_d)),
                    put(tgt_gather(flat).reshape(shape_t)))
        if batch % self.mesh.shape["data"]:
            # explicit batch-sharded placement needs divisibility (unlike
            # the in-step constraint, which pads) — stage replicated and
            # let the constraint shard.  Multi-controller loses the
            # gather-own-rows-only property for such batch sizes.
            flat = idx_mat.reshape(-1)
            return (put(loader.host_gather(flat).reshape(shape_d)),
                    put(tgt_gather(flat).reshape(shape_t)))
        from znicz_tpu.parallel.mesh import (put_sharded_segment,
                                             segment_sharding)

        sh = segment_sharding(self.mesh)
        return (put_sharded_segment(shape_d, sh, loader.host_gather,
                                    idx_mat),
                put_sharded_segment(shape_t, sh, tgt_gather, idx_mat))

    def _staging_donation(self) -> bool:
        """Donate the staged (K, B, ...) segment buffers into the direct
        train scan (``root.common.engine.staging_donate``, default on):
        with the async double-buffer at most two staged segments exist —
        the one the device is consuming (its HBM reusable for activations
        the instant the scan's slice reads it) and the one the stager is
        putting — the serving layer's ping-pong discipline on the
        training path.  Auto-off on CPU, where the runtime ignores
        donation (and warns per compile) — same backend resolution as
        ``ModelRunner.donate``."""
        import jax

        return (bool(root.common.engine.get("staging_donate", True))
                and jax.default_backend() != "cpu")

    def make_train_scan_direct(self):
        """The staged twin of ``make_train_scan``: K steps in one
        dispatch, with the K minibatches riding in the scan xs as
        (K, B, ...) tensors instead of being gathered from a resident
        dataset (same ``_train_body``).  Sliced per step, each (B, ...)
        batch keeps its ``data`` sharding — no gather, no resharding.
        The staged segment buffers are DONATED where the backend supports
        it (``_staging_donation``); callers must not reuse them after the
        dispatch (the run loop never does — each segment is staged
        fresh)."""
        import jax
        import jax.numpy as jnp

        nc = self._n_confusion()
        compiles = self._m_compiles
        donate = (0, 1, 3, 4) if self._staging_donation() else (0, 1)
        kw = {}
        if self.mesh is not None:
            # staged segments keep whatever placement _stage_direct chose
            # (batch-sharded, or the replicated fallback for batches the
            # data axis doesn't divide) — None = infer from the operand,
            # so BOTH placements hit the same executable family without
            # a reshard
            psh, vsh, repl = self._state_shardings()
            kw = self._jit_shardings(
                (psh, vsh, repl, None, None, repl, repl, repl),
                (psh, vsh, repl, repl))

        def chunk(params, velocities, hypers_mat, data_seg, tgt_seg,
                  bs_vec, base_key, step_nums):
            compiles.inc()
            (p, v, conf_sum), ms = jax.lax.scan(
                self._train_body(base_key, lambda xs: xs),
                (params, velocities, jnp.zeros((nc, nc), jnp.int32)),
                (data_seg, tgt_seg, bs_vec, step_nums, hypers_mat))
            return p, v, ms, conf_sum

        return jax.jit(chunk, donate_argnums=donate, **kw)

    def make_eval_scan_direct(self):
        import jax
        import jax.numpy as jnp

        nc = self._n_confusion()
        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            psh, _, repl = self._state_shardings()
            kw = self._jit_shardings((psh, None, None, repl),
                                     (repl, repl))

        def chunk(params, data_seg, tgt_seg, bs_vec):
            compiles.inc()

            def unpack(xs):
                data, tgt, bs = xs
                return self._decode(data), tgt, bs

            conf_sum, ms = jax.lax.scan(
                self._eval_body(params, unpack),
                jnp.zeros((nc, nc), jnp.int32),
                (data_seg, tgt_seg, bs_vec))
            return ms, conf_sum

        return jax.jit(chunk, **kw)

    def make_train_step_direct(self):
        """Tail-update twin of ``make_train_step`` for staged (1, B, ...)
        minibatch tensors.  NO data donation here: the tail path feeds
        the same staged buffers to the eval step first and (gd_skip
        permitting) this step second."""
        import jax

        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            psh, vsh, repl = self._state_shardings()
            kw = self._jit_shardings(
                (psh, vsh, repl, None, None, repl, repl),
                (psh, vsh, repl))

        def step(params, velocities, hypers, data_seg, tgt_seg,
                 batch_size, key):
            compiles.inc()
            return self._update_core(params, velocities, hypers,
                                     data_seg[0], tgt_seg[0], batch_size,
                                     key)

        return jax.jit(step, donate_argnums=(0, 1), **kw)

    def make_eval_step_direct(self):
        import jax

        compiles = self._m_compiles
        kw = {}
        if self.mesh is not None:
            psh, _, repl = self._state_shardings()
            kw = self._jit_shardings((psh, None, None, repl, repl), repl)

        def step(params, data_seg, tgt_seg, batch_size, key, train):
            compiles.inc()
            _, metrics = self.loss_and_metrics(
                params, self._decode(data_seg[0]), tgt_seg[0], batch_size,
                key, train=train)
            return metrics

        return jax.jit(step, static_argnums=(5,), **kw)

    def _advance_lr(self):
        if self._lr_adjust is not None:
            self._lr_adjust.run()

    def _hypers_rows(self, k, advance_last=True):
        """Per-step hypers for a k-step scan, advancing any LR schedule
        between steps exactly like the unit graph does.  ``advance_last``
        False skips the advance after the final row — the deep path's
        epoch tail whose update will not be adopted (the adjust is gated
        like the gds — unit-path parity)."""
        if self._lr_adjust is None:
            return self.tiled_hypers(k)
        rows = []
        for i in range(k):
            rows.append({name: np.asarray(t, np.float32)
                         for name, t in self.hypers().items()})
            if i < k - 1 or advance_last:
                self._advance_lr()
        return {name: np.stack([r[name] for r in rows])
                for name in rows[0]}

    def run(self) -> None:
        """Train until the decision completes, mirroring the loader's
        epoch/class state machine but with fused steps.  Feeds the Decision
        unit per-minibatch so its improvement/stop/log semantics (and the
        snapshotter trigger) behave exactly like the unit path.

        Two host-sync profiles, identical training semantics:

          - default (``pipeline_depth`` 1): consecutive non-tail TRAIN
            minibatches run as ONE ``lax.scan`` dispatch of up to
            ``scan_chunk`` steps, with a one-deep flush pipeline; epoch
            tails and eval feed the Decision synchronously (so epoch-
            granular consumers — snapshotter, plotters — see every epoch);
          - deep (``pipeline_depth`` > 1 and ``_deep_eligible``): whole
            epochs as single dispatches, metrics pulled one fused transfer
            per epoch, up to depth epochs late (VERDICT r4: the product
            path on ~100ms-RTT links)."""
        if self.loss_kind != "softmax" and \
                getattr(self.loader, "streaming", False) and \
                not self.loader.original_targets:
            raise ValueError(
                f"{self.loader.name}: a streaming loader with an MSE "
                "loss needs regression targets — build the StreamingLoader "
                "source with targets= (ADVICE r4: this used to surface as "
                "an opaque error deep inside the staging/operand path)")
        if self.pipeline_depth > 1 and self._deep_eligible():
            self._run_deep()
        else:
            self._run_segmented()

    def _run_segmented(self) -> None:
        from znicz_tpu.loader.base import TRAIN

        wf = self.workflow
        loader, decision = self.loader, self.decision
        staging = self.staging
        if staging:
            if self._train_step is None:
                self._train_step = self.make_train_step_direct()
                self._eval_step = self.make_eval_step_direct()
                self._train_scan = self.make_train_scan_direct()
                self._eval_scan = self.make_eval_scan_direct()
        else:
            if self._train_step is None:
                self._train_step = self.make_train_step()
                self._eval_step = self.make_eval_step()
            if self._train_scan is None and self.scan_chunk > 1:
                self._train_scan = self.make_train_scan()
                self._eval_scan = self.make_eval_scan()
        self._reset_accounting()
        params, velocities, dataset, targets, put = self._device_state()
        feed_decision = self._feed_decision
        account = self._account
        advance_lr = self._advance_lr
        hypers_rows = self._hypers_rows

        def epoch_end_hook():
            # writeback is NEED-driven: device->host param+velocity pulls
            # cost a fixed per-epoch tax on slow host links (~100ms/RTT),
            # so pay it only when something will consume the state this
            # epoch — a due snapshot or a wired plotter (VERDICT r3
            # weak #3).  run() still does one final writeback at the end.
            # A due HOST-FORMAT snapshot doesn't even pay that: the trees
            # are device-copied (donation safety) and handed to the
            # snapshotter's background worker, which pulls and writes
            # while the next epoch computes (VERDICT r4 item 4).
            snap = getattr(wf, "snapshotter", None)
            snap_open = snap is not None and not bool(snap.gate_skip)
            snap_due = snap_open and snap.due(decision.epoch_number,
                                              decision.improved)
            snap_async = snap_due and self._async_snapshot_enabled(snap)
            plotters = list(getattr(wf, "plotters", None) or [])
            if (snap_due and not snap_async) or plotters:
                self.writeback(params, velocities)
            if snap_open:
                snap.epoch_number = decision.epoch_number
                snap.improved = decision.improved
                if snap_async:
                    import jax
                    import jax.numpy as jnp

                    tags = snap.tags_for(decision.epoch_number,
                                         decision.improved)
                    if tags:
                        copy = jax.tree_util.tree_map
                        snap.save_async(self.snapshot_from_trees(
                            copy(jnp.copy, params),
                            copy(jnp.copy, velocities)), tags)
                elif snap_due:
                    snap.run()
            # wired plotters count as consumers, so whenever they run the
            # unit Arrays hold this epoch's weights.  Ad-hoc observers
            # (e.g. a decision.on_epoch_end callback reading weights)
            # see Arrays refreshed only on consumer epochs + at run end —
            # the documented cost of need-driven writeback (ImageSaver
            # stays unit-engine-only: it needs per-minibatch host data
            # the fast path never pulls)
            for plotter in plotters:
                plotter.run()

        import time as _time
        from collections import deque

        was_indices_only = loader.indices_only
        loader.indices_only = True
        fifo = deque()                  # advanced-but-unprocessed mbs
        inflight = None                 # (seg, kind, device results, t0)
        epoch_conf = None               # device-side confusion running sum

        # -- lookahead prefetch (loader/ingest.py): for host-staged
        # sources with a decode pool, advance the loader's index state
        # machine ahead of processing and SUBMIT future minibatches' rows
        # so their decode overlaps the in-flight dispatch's compute.
        # Bounded to ``prefetch_segments`` scan segments; never advances
        # past an epoch tail (last_minibatch), so the loader state the
        # snapshotter sees at epoch boundaries is identical to the
        # unprefetched run's.
        prefetch_segments = int(root.common.engine.get(
            "prefetch_segments", 2))
        can_prefetch = (
            staging and prefetch_segments > 0
            and getattr(loader, "prefetch_rows", None) is not None
            and getattr(loader.source, "prefetch", None) is not None)
        look_mbs = prefetch_segments * max(self.scan_chunk, 1)
        sel_cache = {}

        # -- async double-buffered device staging (ISSUE 7): a one-worker
        # stager assembles + device_puts the NEXT train segment while the
        # current one computes, so host gather/decode and the H2D copy
        # hide under the step instead of serializing against it.  The
        # prediction is the dispatch loop's own segment-collection rule
        # replayed over the lookahead fifo; a mispredicted segment falls
        # back to inline staging (counted — never wrong data).  Single-
        # controller only: the multi-process gather-own-rows callback
        # stays on the training thread.
        stager = None
        if staging and bool(root.common.engine.get("async_staging", True)):
            import jax as _jax

            if self.mesh is None or _jax.process_count() == 1:
                from znicz_tpu.loader.ingest import DeviceStager

                stager = DeviceStager(
                    lambda rows: self._stage_direct(rows, put))
                self._stager = stager       # observable (tests, bench)
        # the lookahead must advance even for memcpy-cheap sources (no
        # decode pool): the stager needs the fifo to predict from
        look_mbs = max(look_mbs if can_prefetch else 0,
                       2 * max(self.scan_chunk, 1) if stager else 0)

        def stage_segment(seg):
            """Staged device tensors for a dispatch group — from the
            stager when armed (a predicted group is a cache pop; the
            fallback assembles inline and counts a miss)."""
            rows = [s["idx"] for s in seg]
            if stager is not None:
                return stager.take(rows)
            return self._stage_direct(rows, put)

        def upcoming_segments():
            """The dispatch groups the loop WILL form from the fifo — the
            segment-collection rules replayed without consuming: TRAIN
            segments (consecutive non-tail, up to scan_chunk), eval runs
            (same class, up to scan_chunk), the tail as its own group.
            Stops at the first group whose boundary the fifo cannot
            prove yet (the lookahead refill will)."""
            from znicz_tpu.loader.base import TRAIN as _TRAIN

            groups, i, n = [], 0, len(fifo)
            while i < n:
                m = fifo[i]
                if m["class"] == _TRAIN and m["last_minibatch"]:
                    groups.append([m])          # the tail dispatches alone
                    i += 1
                    continue
                is_train = m["class"] == _TRAIN
                scan = self._train_scan if is_train else self._eval_scan
                cap = self.scan_chunk if scan else 1
                seg = [m]
                i += 1
                while i < n and len(seg) < cap:
                    nxt = fifo[i]
                    same = (nxt["class"] == _TRAIN
                            and not nxt["last_minibatch"]
                            if is_train else nxt["class"] == m["class"])
                    if not same:
                        break
                    seg.append(nxt)
                    i += 1
                if len(seg) < cap and i >= n:
                    break                       # boundary not proven
                groups.append(seg)
            return groups

        def submit_upcoming():
            """Start staging the provable upcoming groups, oldest first,
            until the ping-pong is full (``stager.depth``)."""
            if stager is None:
                return
            for seg in upcoming_segments():
                if stager.outstanding >= stager.depth:
                    break
                stager.submit([s["idx"] for s in seg])

        def local_rows(idx):
            """The rows of a minibatch THIS process will stage (multi-
            controller prefetch keeps _stage_direct's gather-own-rows-
            only property; single-host returns everything)."""
            if self.mesh is None:
                return idx
            import jax

            if jax.process_count() == 1:
                return idx
            batch = len(idx)
            if batch % self.mesh.shape["data"]:
                return idx      # replicated staging fallback: all rows
            mask = sel_cache.get(batch)
            if mask is None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                sh = NamedSharding(self.mesh, P("data"))
                mask = np.zeros(batch, bool)
                pidx = jax.process_index()
                for d, ind in sh.devices_indices_map((batch,)).items():
                    if d.process_index == pidx:
                        mask[ind[0]] = True
                sel_cache[batch] = mask
            return idx[mask]

        def take_mb():
            return fifo.popleft() if fifo else self._advance()

        def extend_lookahead():
            if not (can_prefetch or stager is not None):
                return
            # a put-back mb (segment collection overshoot) may sit in the
            # fifo without having been submitted — cover it first
            if can_prefetch:
                for m in fifo:
                    if not m.get("pf"):
                        loader.prefetch_rows(local_rows(m["idx"]))
                        m["pf"] = True
            while len(fifo) < look_mbs and \
                    not (fifo and fifo[-1]["last_minibatch"]):
                nxt = self._advance()
                if can_prefetch:
                    loader.prefetch_rows(local_rows(nxt["idx"]))
                    nxt["pf"] = True
                fifo.append(nxt)

        def flush():
            """Sync + feed the in-flight TRAIN segment's metrics.  Runs
            AFTER the next segment is dispatched, so the host round-trip
            overlaps device compute (one-deep pipeline); non-tail TRAIN
            feeds cannot flip `complete`/`gd_skip`, so deferring them one
            segment changes no control flow — tails/eval flush first.
            Confusion stays on device (``epoch_conf``), transferred once
            at the epoch tail."""
            nonlocal inflight, epoch_conf
            if inflight is None:
                return
            seg, kind, res, t0 = inflight
            inflight = None
            t_flush = _time.perf_counter()
            if kind == "single":
                loss, n_err, conf = res
                epoch_conf = conf if epoch_conf is None \
                    else epoch_conf + conf
                stacked = [(loss, n_err, None)]
            else:
                ms, conf_sum = res
                epoch_conf = conf_sum if epoch_conf is None \
                    else epoch_conf + conf_sum
                losses, n_errs = (np.asarray(m) for m in ms)
                stacked = [(losses[i], n_errs[i], None)
                           for i in range(len(seg))]
            if self._tracer.enabled:
                # the host-sync span: waiting out the previous dispatch's
                # device work + pulling its metrics
                self._tracer.add("train", "flush", t_flush,
                                 _time.perf_counter() - t_flush,
                                 {"steps": len(seg), "kind": kind})
            for s, m in zip(seg, stacked):
                feed_decision(s, m)
            account(len(seg), sum(s["size"] for s in seg), t0, True,
                    kind=f"train_{kind}_{len(seg)}")

        try:
            while not bool(decision.complete):
                t_iter = _time.perf_counter()
                mb = take_mb()
                is_train = (mb["class"] == TRAIN)
                if is_train and not mb["last_minibatch"]:
                    # collect the segment of consecutive non-tail TRAIN
                    # minibatches (they cannot flip `complete`) and run it
                    # as one scan dispatch
                    seg = [mb]
                    max_seg = self.scan_chunk if self._train_scan else 1
                    while len(seg) < max_seg:
                        nxt = take_mb()
                        if nxt["class"] == TRAIN and \
                                not nxt["last_minibatch"]:
                            seg.append(nxt)
                        else:
                            fifo.appendleft(nxt)
                            break
                    extend_lookahead()  # future segments' decode starts
                    if stager is not None:
                        # ping-pong ordering (ISSUE 7): upcoming groups'
                        # assemblies are already in flight — sync the
                        # PREVIOUS segment FIRST so its device compute
                        # overlaps them, then take this segment's staged
                        # buffers (ready by then; the wait histogram is
                        # the proof the --ingest gate checks)
                        submit_upcoming()
                        flush()
                    gen = prng.get("fused_trainer")

                    def seg_ops():
                        return (put(np.array([s["size"] for s in seg],
                                             np.int32)),
                                put(np.arange(self.steps_done,
                                              self.steps_done + len(seg),
                                              dtype=np.int32)))

                    # ISSUE 5: named profiler step (--profile-dir) +
                    # a dispatch span; t_disp measures HOST dispatch time
                    # (the device work lands in flush()'s sync span)
                    t_disp = _time.perf_counter()
                    step0 = self.steps_done
                    with self._telemetry.step_annotation(step0):
                        if staging:
                            # staged-direct: minibatches ride in the scan xs
                            # (even a lone step goes through the K=1 scan);
                            # with the async stager the buffers were
                            # assembled + put while the PREVIOUS segment
                            # computed
                            dseg, tseg = stage_segment(seg)
                            bs_vec, steps = seg_ops()
                            params, velocities, ms, conf_sum = \
                                self._train_scan(
                                    params, velocities,
                                    put(hypers_rows(len(seg))), dseg, tseg,
                                    bs_vec, put(gen.jax_base_key()), steps)
                            result = ("scan", (ms, conf_sum))
                        elif len(seg) == 1:
                            key = gen.jax_key(self.steps_done)
                            params, velocities, metrics = self._train_step(
                                params, velocities, self.hypers(), dataset,
                                targets, put(seg[0]["idx"]),
                                np.int32(seg[0]["size"]), key)
                            advance_lr()
                            result = ("single", metrics)
                        else:
                            idx_op = put(np.stack([s["idx"] for s in seg]))
                            bs_vec, steps = seg_ops()
                            params, velocities, ms, conf_sum = \
                                self._train_scan(
                                    params, velocities,
                                    put(hypers_rows(len(seg))), dataset,
                                    targets, idx_op, bs_vec,
                                    put(gen.jax_base_key()), steps)
                            result = ("scan", (ms, conf_sum))
                    if self._tracer.enabled:
                        self._tracer.add(
                            "train", f"dispatch:{result[0]}", t_disp,
                            _time.perf_counter() - t_disp,
                            {"steps": len(seg), "step0": step0})
                    self.steps_done += len(seg)
                    # start staging the NEXT groups before anything
                    # blocks: their host assembly + H2D overlap this
                    # segment's compute
                    submit_upcoming()
                    if stager is None:
                        flush()         # previous segment, AFTER dispatch
                    inflight = (seg, result[0], result[1], t_iter)
                elif is_train:
                    flush()
                    # epoch tail: metrics first, Decision rules, and the
                    # update applies only if gd_skip stayed open
                    # (unit-path parity).  The epoch's device-side
                    # confusion sum rides along in this one transfer.
                    bs = np.int32(mb["size"])
                    key = prng.get("fused_trainer").jax_key(self.steps_done)
                    if staging:
                        dseg, tseg = stage_segment([mb])
                        loss, n_err, conf = self._eval_step(
                            params, dseg, tseg, bs, key, True)
                    else:
                        idx = put(mb["idx"])
                        loss, n_err, conf = self._eval_step(
                            params, dataset, targets, idx, bs, key, True)
                    if epoch_conf is not None:
                        conf = epoch_conf + conf
                        epoch_conf = None
                    feed_decision(mb, (loss, n_err, conf))
                    if not bool(decision.gd_skip):
                        with self._telemetry.step_annotation(
                                self.steps_done):
                            if staging:
                                params, velocities, _ = self._train_step(
                                    params, velocities, self.hypers(),
                                    dseg, tseg, bs, key)
                            else:
                                params, velocities, _ = self._train_step(
                                    params, velocities, self.hypers(),
                                    dataset, targets, idx, bs, key)
                        advance_lr()    # adj is gated like the gds
                    self.steps_done += 1
                    if self._tracer.enabled:
                        self._tracer.add(
                            "train", "tail", t_iter,
                            _time.perf_counter() - t_iter,
                            {"epoch": int(mb["epoch_number"])})
                    account(1, mb["size"], t_iter, True, kind="tail")
                else:
                    flush()
                    # TEST/VALID: params are frozen, so consecutive eval
                    # minibatches of the SAME class scan as a pure map in
                    # one dispatch (segments must not span the TEST|VALID
                    # boundary — the segment's summed confusion is booked
                    # to the first minibatch's class)
                    seg = [mb]
                    max_seg = self.scan_chunk if self._eval_scan else 1
                    while len(seg) < max_seg:
                        nxt = take_mb()
                        if nxt["class"] == mb["class"]:
                            seg.append(nxt)
                        else:
                            fifo.appendleft(nxt)
                            break
                    extend_lookahead()
                    # the upcoming groups stage while this eval segment
                    # computes (the eval/train boundary is where each
                    # epoch's first train segment would otherwise pay
                    # the full assembly inline)
                    submit_upcoming()
                    if staging:
                        dseg, tseg = stage_segment(seg)
                        bs_vec = put(np.array([s["size"] for s in seg],
                                              np.int32))
                        ms, conf_sum = self._eval_scan(
                            params, dseg, tseg, bs_vec)
                        losses, n_errs = (np.asarray(m) for m in ms)
                        stacked = [(losses[i], n_errs[i],
                                    conf_sum if i == 0 else None)
                                   for i in range(len(seg))]
                    elif len(seg) == 1:
                        stacked = [self._eval_step(
                            params, dataset, targets, put(mb["idx"]),
                            np.int32(mb["size"]), self._key0, False)]
                    else:
                        idx_op = put(np.stack([s["idx"] for s in seg]))
                        bs_vec = put(np.array([s["size"] for s in seg],
                                              np.int32))
                        ms, conf_sum = self._eval_scan(
                            params, dataset, targets, idx_op, bs_vec)
                        losses, n_errs = (np.asarray(m) for m in ms)
                        # segment confusion fed once, with the first step
                        stacked = [(losses[i], n_errs[i],
                                    conf_sum if i == 0 else None)
                                   for i in range(len(seg))]
                    for s, m in zip(seg, stacked):
                        feed_decision(s, m)
                    if self._tracer.enabled:
                        self._tracer.add("train", "eval", t_iter,
                                         _time.perf_counter() - t_iter,
                                         {"steps": len(seg),
                                          "class": int(mb["class"])})
                    account(len(seg), 0, t_iter, False,
                            kind=f"eval_{len(seg)}")
                if bool(decision.epoch_ended):
                    epoch_end_hook()
                    # consume the flag: with the pipeline, the next loop
                    # iteration may not feed the decision before this
                    # check runs again, and a stale True would re-save
                    # the 'best' snapshot with weights already advanced
                    # past the epoch boundary
                    decision.epoch_ended.set(False)
                if not bool(decision.complete):
                    # refill the lookahead AFTER the epoch hook: a
                    # boundary snapshot must record the tail state, not a
                    # loader already advanced (and reshuffled) into the
                    # next epoch — resume parity depends on this ordering
                    extend_lookahead()
                    submit_upcoming()
            flush()
            self.writeback(params, velocities)
        finally:
            loader.indices_only = was_indices_only
            if stager is not None:
                # drop any mispredicted in-flight segment (a stop can
                # land mid-prediction); staged buffers are just arrays —
                # nothing to unwind
                stager.close()
            # in the FINALLY: an interrupt mid-run must still land the
            # queued async saves (the writer thread is a daemon — without
            # this drain a Ctrl-C drops them); on the exception path the
            # drain must not mask the in-flight error with a writer error
            self._drain_snapshots(suppress=_sys.exc_info()[0] is not None)

    # -- the deep (whole-epoch) pipeline ---------------------------------------

    def _deep_eligible(self) -> bool:
        """Deep pipelining defers every host sync by up to
        ``pipeline_depth`` epochs, so it requires that nothing consumes
        host-side state at epoch granularity: no wired plotters.  An
        ACTIVE snapshotter no longer forces the segmented path (r4 weak
        #3 — the fast configuration couldn't checkpoint at all): a
        host-format snapshotter is served at FLUSH boundaries by the
        async writer, from the flushed epoch's own recorded state
        (loader/prng as of that epoch's tail), so the checkpoint is
        bit-equivalent to the segmented path's.  Only an orbax-format
        snapshotter (collective save) or async_snapshot=False still
        selects segmented mode.  Decision semantics are preserved
        exactly either way — metrics are fed in order, just later in
        wall time, and stops are rolled back to the exact stopping
        state."""
        from znicz_tpu.core.mutable import Bool

        wf = self.workflow
        if self.staging:
            # host-staged streaming ships each dispatch's samples; a whole
            # deep-pipelined epoch would stage the full epoch at once —
            # use the segmented path, whose per-segment staging is the
            # double buffer
            return False
        if getattr(wf, "plotters", None):
            return False
        snap = getattr(wf, "snapshotter", None)
        if snap is not None:
            gate = snap.gate_skip
            # an epoch-wired gate (e.g. ~decision.epoch_ended) is derived
            # and OPENS at epoch ends — that snapshotter is active even
            # though the gate reads True between epochs.  Only a plain
            # constant-True skip counts as disabled.
            disabled = bool(gate) and not (
                isinstance(gate, Bool) and gate.derived)
            if not disabled and not self._async_snapshot_enabled(snap):
                return False
        return True

    def _collect_epoch(self):
        """Drive the loader through ONE full epoch; returns its recorded
        minibatches: eval class runs (loader order: TEST then VALID) and
        the TRAIN run whose last minibatch is the epoch tail."""
        from znicz_tpu.loader.base import TRAIN

        evals, train = [], []
        while True:
            mb = self._advance()
            if mb["class"] == TRAIN:
                train.append(mb)
                if mb["last_minibatch"]:
                    break
            else:
                assert not train, \
                    "deep pipeline expects eval classes before TRAIN"
                if evals and evals[-1][0] == mb["class"]:
                    evals[-1][1].append(mb)
                else:
                    evals.append((mb["class"], [mb]))
        return {"evals": evals, "train": train,
                "epoch_number": train[-1]["epoch_number"]}

    def _epoch_hypers(self, k, apply_tail: bool):
        """Hypers rows for one epoch's k+1 train steps (see
        ``_hypers_rows`` — the one home of the row-build loop)."""
        return self._hypers_rows(k + 1, advance_last=apply_tail)

    def make_epoch_fn(self, eval_layout, n_train: int):
        """The WHOLE epoch as ONE dispatch: eval scans on the incoming
        (pre-epoch) params in loader order, then the k non-tail train
        steps as one scan, then the tail step whose update is adopted
        only when ``apply_tail`` (the gd_skip prediction; a
        late-discovered stop re-dispatches with False).  Returns new
        params/velocities, one packed f32 scalar vector (per eval run:
        losses then n_errs; then train losses, train n_errs, tail loss,
        tail n_err) and stacked confusion sums (one per eval run + one
        for TRAIN incl. tail) — all metrics pullable in a single host
        transfer per epoch (~100ms/RTT links: VERDICT r3 weak #2)."""
        import jax
        import jax.numpy as jnp

        k = n_train - 1
        nc = self._n_confusion()

        def epoch(params, velocities, hypers_mat, dataset, targets,
                  train_idx, train_bs, eval_idx, eval_bs, base_key,
                  step_nums, apply_tail):
            scalars, confs = [], []
            ebody = self._eval_scan_body(params, dataset, targets)
            off = 0
            for _klass, n in eval_layout:
                conf_r, ms = jax.lax.scan(
                    ebody, jnp.zeros((nc, nc), jnp.int32),
                    (eval_idx[off:off + n], eval_bs[off:off + n]))
                scalars += [ms[0], ms[1].astype(jnp.float32)]
                confs.append(conf_r)
                off += n

            head = jax.tree_util.tree_map(lambda h: h[:k], hypers_mat)
            (p, v, conf_tr), tms = jax.lax.scan(
                self._train_scan_body(dataset, targets, base_key),
                (params, velocities, jnp.zeros((nc, nc), jnp.int32)),
                (train_idx[:k], train_bs[:k], step_nums[:k], head))
            key_t = jax.random.fold_in(base_key, step_nums[k])
            hyp_t = jax.tree_util.tree_map(lambda h: h[k], hypers_mat)
            p2, v2, (tl, tn, tconf) = self._step_core(
                p, v, hyp_t, dataset, targets, train_idx[k], train_bs[k],
                key_t)
            p, v = jax.lax.cond(apply_tail,
                                lambda a, b, c, d: (a, b),
                                lambda a, b, c, d: (c, d), p2, v2, p, v)
            scalars += [tms[0], tms[1].astype(jnp.float32),
                        jnp.stack([tl, tn.astype(jnp.float32)])]
            confs.append(conf_tr + tconf)
            return p, v, jnp.concatenate(scalars), jnp.stack(confs)

        return jax.jit(epoch)

    def _run_deep(self) -> None:
        """Whole-epoch dispatches with metric pulls deferred by up to
        ``2 * pipeline_depth`` epochs: the pipeline FILLS to 2x depth and
        then flushes ``depth`` epochs with their scalars pulled in ONE
        fused transfer (a per-epoch pull serializes the host loop at one
        link RTT per epoch — r4).  Costs scale with the window: up to
        ``2*depth - 1`` in-flight epochs each pin a params+velocities
        snapshot in HBM (AlexNet: ~366 MB per epoch -> ~5.5 GB at depth
        8), and a ``fail_iterations`` stop is discovered (and rolled
        back) up to that many epochs late.  Dispatch runs AHEAD of the
        Decision speculatively: every epoch's tail update except the
        last-by-max_epochs is applied optimistically (gd_skip only closes
        when ``complete`` flips — decision.py); when a flush reveals an
        earlier stop, the exact stopping state is recomputed from the
        recorded epoch inputs with ``apply_tail`` False and the
        speculated epochs are discarded, including the host-side
        LR-schedule/prng/loader bookkeeping."""
        import copy
        import time as _time
        from collections import deque

        decision, loader = self.decision, self.loader
        self._reset_accounting()
        params, velocities, dataset, targets, put = self._device_state()
        epoch_fn = None
        layout = None
        inflight = deque()
        was_indices_only = loader.indices_only
        loader.indices_only = True
        gen = prng.get("fused_trainer")

        concat_jit = {}

        def flush_batch(n):
            """Flush the n oldest in-flight epochs with their scalar
            vectors pulled in ONE fused transfer: on ~100ms-RTT hosts a
            per-epoch pull serializes the host loop at one RTT per epoch
            even though the device pipelines ahead (r4 product bench: the
            deep path stalled at ~67% of the scan rate).  Batching the
            pull amortizes the RTT over ``pipeline_depth`` epochs."""
            if n <= 1:
                flush_one()
                return
            import jax.numpy as jnp

            if n not in concat_jit:
                import jax

                concat_jit[n] = jax.jit(
                    lambda *xs: jnp.concatenate(xs))
            recs = [inflight[i] for i in range(n)]
            vals = np.asarray(
                concat_jit[n](*[r["scalars"] for r in recs]))
            size = vals.shape[0] // n
            for i in range(n):
                if bool(decision.complete):
                    break               # late stop: rest was rolled back
                flush_one(vals[i * size:(i + 1) * size])

        def flush_one(vals=None):
            nonlocal params, velocities
            rec = inflight.popleft()
            if vals is None:
                vals = np.asarray(rec["scalars"])   # one transfer/epoch
            confs = rec["confs"]
            off, ci = 0, 0
            for _klass, mbs in rec["evals"]:
                n = len(mbs)
                losses = vals[off:off + n]
                nerrs = vals[off + n:off + 2 * n]
                off += 2 * n
                for i, mb in enumerate(mbs):
                    self._feed_decision(
                        mb, (losses[i], nerrs[i],
                             confs[ci] if i == 0 else None))
                ci += 1
            k = len(rec["train"]) - 1
            losses = vals[off:off + k]
            nerrs = vals[off + k:off + 2 * k]
            off += 2 * k
            for i, mb in enumerate(rec["train"][:k]):
                self._feed_decision(mb, (losses[i], nerrs[i], None))
            self._feed_decision(rec["train"][k],
                                (vals[off], vals[off + 1], confs[ci]))
            # snapshot gating must be read NOW: an epoch-wired gate
            # (~decision.epoch_ended) is only open while the tail feed's
            # epoch_ended=True is live
            snap = getattr(self.workflow, "snapshotter", None)
            snap_open = snap is not None and not bool(snap.gate_skip)
            snap_due = snap_open and snap.due(decision.epoch_number,
                                              decision.improved)
            decision.epoch_ended.set(False)
            n_eval = sum(len(m) for _, m in rec["evals"])
            self._account(k + 1,
                          sum(mb["size"] for mb in rec["train"]),
                          rec["t0"], True, kind="epoch", n_eval=n_eval)
            if bool(decision.complete):
                # stop discovered (possibly late): recompute the exact
                # stopping state — same recorded inputs, tail update NOT
                # adopted — and discard the speculated epochs' device and
                # host state.  For a clean max_epochs stop the restores
                # are no-ops (the tail was already dispatched un-adopted
                # and nothing was speculated past it).
                if rec["applied_tail"] or inflight:
                    params, velocities, _, _ = epoch_fn(
                        rec["params_in"], rec["vels_in"], rec["hypers"],
                        dataset, targets, rec["train_idx"],
                        rec["train_bs"], rec["eval_idx"], rec["eval_bs"],
                        rec["base_key"], rec["step_nums"], False)
                    inflight.clear()
                self.steps_done = rec["steps_end"]
                if self._lr_adjust is not None:
                    self._lr_adjust.restore_iteration(
                        rec["lr_iter_start"] + k)
                for name, state in rec["prng"].items():
                    prng.get(name).state.bit_generator.state = state
                loader.epoch_number, loader.samples_served = \
                    rec["loader_state"]
            if snap_open:
                snap.epoch_number = decision.epoch_number
                snap.improved = decision.improved
                if snap_due:
                    # the flushed epoch's POST-epoch params: the next
                    # in-flight epoch's inputs, or the live trees (which
                    # for a just-rolled-back stop ARE the recomputed
                    # stopping state).  Deep dispatches never donate, so
                    # the refs are stable — no device copy needed.  The
                    # checkpoint records the epoch's OWN loader/prng
                    # state (captured at its tail), not the pipelined-
                    # ahead live state — resume parity.
                    tags = snap.tags_for(decision.epoch_number,
                                         decision.improved)
                    if tags:
                        post_p = (inflight[0]["params_in"] if inflight
                                  else params)
                        post_v = (inflight[0]["vels_in"] if inflight
                                  else velocities)
                        s = self.snapshot_from_trees(post_p, post_v)
                        s["loader"].update(rec["loader_snap"])
                        s["prng"] = rec["prng"]
                        snap.save_async(s, tags)

        try:
            final_dispatched = False
            while not bool(decision.complete):
                if final_dispatched:
                    # the epoch that must flip complete via max_epochs is
                    # already in flight: drain
                    assert inflight, "decision never completed"
                    flush_one()
                    continue
                t0 = _time.perf_counter()
                lr_iter_start = (self._lr_adjust.iteration
                                 if self._lr_adjust is not None else 0)
                rec = self._collect_epoch()
                this_layout = (tuple((kl, len(m)) for kl, m
                                     in rec["evals"]), len(rec["train"]))
                if layout is None:
                    layout = this_layout
                    epoch_fn = self.make_epoch_fn(*layout)
                elif this_layout != layout:
                    raise RuntimeError(
                        f"epoch layout changed mid-training: {layout} "
                        f"-> {this_layout}")
                k = len(rec["train"]) - 1
                # predictable stop: the tail whose epoch hits max_epochs
                # is the last-ever update and is never adopted (matches
                # the segmented path, where Decision flips complete BEFORE
                # the tail update and gd_skip gates it off) — including
                # when resuming with loader.epoch_number already at or
                # past max_epochs - 1
                apply_tail = (rec["epoch_number"] + 1
                              < int(decision.max_epochs))
                final_dispatched = not apply_tail
                mb_len = len(rec["train"][0]["idx"])
                eval_mbs = [mb for _, ms in rec["evals"] for mb in ms]
                rec.update(
                    t0=t0, applied_tail=apply_tail,
                    lr_iter_start=lr_iter_start,
                    params_in=params, vels_in=velocities,
                    hypers=put(self._epoch_hypers(k, apply_tail)),
                    train_idx=put(np.stack(
                        [mb["idx"] for mb in rec["train"]])),
                    train_bs=put(np.array(
                        [mb["size"] for mb in rec["train"]], np.int32)),
                    eval_idx=put(
                        np.stack([mb["idx"] for mb in eval_mbs])
                        if eval_mbs
                        else np.zeros((0, mb_len), np.int32)),
                    eval_bs=put(np.array(
                        [mb["size"] for mb in eval_mbs], np.int32)),
                    base_key=put(gen.jax_base_key()),
                    step_nums=np.arange(self.steps_done,
                                        self.steps_done + k + 1,
                                        dtype=np.int32))
                params, velocities, scal, confs = epoch_fn(
                    params, velocities, rec["hypers"], dataset, targets,
                    rec["train_idx"], rec["train_bs"], rec["eval_idx"],
                    rec["eval_bs"], rec["base_key"], rec["step_nums"],
                    apply_tail)
                self.steps_done += k + 1
                rec.update(scalars=scal, confs=confs,
                           steps_end=self.steps_done,
                           prng={name: copy.deepcopy(
                               s.state.bit_generator.state)
                               for name, s in prng._streams.items()},
                           loader_state=(int(loader.epoch_number),
                                         int(loader.samples_served)),
                           # the state a snapshot of THIS epoch must
                           # record: its tail position and its composed
                           # shuffle order (the next epoch's shuffle has
                           # not run yet — it happens lazily on the next
                           # _advance)
                           loader_snap={
                               "epoch_number": rec["epoch_number"],
                               "samples_served": int(
                                   loader.samples_served),
                               "last_minibatch": True,
                               "shuffled_indices": np.array(
                                   loader._shuffled_indices)})
                inflight.append(rec)
                # let the pipeline FILL to 2x depth, then flush depth
                # epochs with one batched pull — steady state pays one
                # RTT per ``pipeline_depth`` epochs while keeping at
                # least depth epochs in flight
                if len(inflight) >= 2 * self.pipeline_depth:
                    flush_batch(self.pipeline_depth)
            self.writeback(params, velocities)
        finally:
            loader.indices_only = was_indices_only
            # see _run_segmented's finally for the rationale
            self._drain_snapshots(suppress=_sys.exc_info()[0] is not None)
