"""FusedTrainer: the TPU-native fast path — one jitted SPMD train step for a
StandardWorkflow-shaped graph.

The unit-at-a-time engine (Workflow.run) preserves the reference's execution
semantics but pays one dispatch + host sync per unit.  The fused trainer
stages the whole minibatch pipeline

    gather(dataset, idx) -> forwards -> loss -> grads -> per-layer sgd_update

into ONE ``jax.jit`` with sharding annotations: dataset/batch sharded over
the mesh ``data`` axis, params replicated (or column-sharded over ``model``
for wide FC layers), gradients reduced by the psum XLA inserts — the
reference's entire master/slave ZeroMQ stack (SURVEY.md §3.4) becomes a
single compiled collective over ICI.

Semantics guaranteed identical to the unit path:
  - forward math IS the units' own pure ``apply`` (same code objects);
  - the update rule IS ``nn_units.sgd_update`` with each GD unit's own
    hyperparameters (per-layer lr/momentum/L1+L2/clip survive);
  - loss/cotangent match the evaluators (softmax-CE at logits; masked MSE);
  - dropout/stochastic pooling draw per-layer per-step keys from the same
    seeded stream design (mask reuse is implicit — fwd and bwd live in one
    autodiff graph).

Mixed precision: with ``root.common.engine.precision = "bfloat16"``, the
forward/backward graph runs in bf16 on the MXU while master params, velocity
and the update stay float32.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.nn_units import sgd_update


class FusedTrainer:
    """Compile and drive fused steps for a built+initialized workflow with
    ``forwards``, ``gds``, ``loader``, ``evaluator``, ``decision``."""

    def __init__(self, workflow, mesh=None, remat=None):
        from znicz_tpu.all2all import All2AllSoftmax
        from znicz_tpu.dropout import DropoutForward
        from znicz_tpu.evaluator import EvaluatorSoftmax
        from znicz_tpu.pooling import StochasticPoolingBase

        if remat is None:
            remat = bool(root.common.engine.get("remat", False))
        self.remat = remat
        self.scan_chunk = int(root.common.engine.get("scan_chunk",
                                                     type(self).scan_chunk))
        self.workflow = workflow
        self.forwards = list(workflow.forwards)
        self.loader = workflow.loader
        self.decision = workflow.decision
        self.mesh = mesh
        self.loss_kind = ("softmax"
                          if isinstance(workflow.evaluator, EvaluatorSoftmax)
                          else "mse")
        #: mirrors the evaluator's resolved setting (auto-off for wide
        #: heads: the (C,C) reporting transfer dominated training wall
        #: time at ImageNet scale on slow host links)
        self.compute_confusion = bool(
            getattr(workflow.evaluator, "compute_confusion", True))
        self._softmax_cls = All2AllSoftmax
        self._dropout_cls = DropoutForward
        self._stochpool_cls = StochasticPoolingBase
        self.gd_of = {gd.forward.name: gd for gd in workflow.gds}
        # tied weights (shared Arrays) need joint-update logic the fused
        # path doesn't implement — detect and refuse (unit path handles it)
        seen = {}
        for f in self.forwards:
            for k, arr in f.params().items():
                if id(arr) in seen:
                    raise ValueError(
                        f"fused trainer does not support tied weights "
                        f"({f.name}.{k} shares {seen[id(arr)]})")
                seen[id(arr)] = f"{f.name}.{k}"
        from znicz_tpu.lr_adjust import LearningRateAdjust

        #: a user-wired LearningRateAdjust unit advances once per TRAIN
        #: step here too (the unit graph runs it per lap, gated like the
        #: gds); scans take per-step hypers as xs so LR schedules apply
        #: with per-step granularity, exactly as in the unit path
        self._lr_adjust = next(
            (u for u in workflow.units
             if isinstance(u, LearningRateAdjust)), None)
        self._train_step = None
        self._train_scan = None
        self._eval_step = None
        self._eval_scan = None
        self._key0 = prng.get("fused_trainer").jax_key(0)
        self.steps_done = 0
        #: per-step timing accumulated by run() (SURVEY.md §5 Tracing —
        #: the fast path reports like the unit path's timing table does);
        #: surfaced by Workflow.print_stats and web_status /status.json
        #: via ``workflow.fused_stats``
        #: ``warm_*`` exclude each dispatch kind's FIRST call (which pays
        #: jit compilation) — the steady-state numbers; ``wall_s`` etc.
        #: are totals including compiles
        self.stats = {"train_steps": 0, "eval_steps": 0, "images": 0,
                      "wall_s": 0.0, "steps_per_sec": 0.0,
                      "img_per_sec": 0.0, "last_step_ms": 0.0,
                      "warm_steps": 0, "warm_images": 0, "warm_wall_s": 0.0,
                      "warm_img_per_sec": 0.0}
        workflow.fused_stats = self.stats
        self.compute_dtype = (np.dtype("float32")
                              if root.common.engine.get("precision",
                                                        "float32")
                              == "float32" else "bfloat16")

    # -- state extraction ------------------------------------------------------

    def extract_params(self) -> Dict[str, Dict[str, object]]:
        return {f.name: {k: a.devmem for k, a in f.params().items()}
                for f in self.forwards if f.has_weights}

    def extract_velocities(self):
        out = {}
        for f in self.forwards:
            gd = self.gd_of.get(f.name)
            if gd is not None and f.has_weights:
                out[f.name] = {k: a.devmem
                               for k, a in gd._velocities.items()}
        return out

    def hypers(self):
        out = {}
        for f in self.forwards:
            gd = self.gd_of.get(f.name)
            if gd is not None and f.has_weights:
                out[f.name] = tuple(np.float32(v) for v in (
                    gd.learning_rate, gd.learning_rate_bias,
                    gd.weights_decay, gd.weights_decay_bias, gd.l1_vs_l2,
                    gd.gradient_moment, gd.gradient_moment_bias,
                    gd.gradient_clip))
        return out

    def tiled_hypers(self, k: int):
        """Per-step hypers rows for a k-step scan with CONSTANT hypers —
        the one home for the scan's hypers-xs layout (callers without an
        LR schedule: bench, dryrun, hypers_rows' fast path)."""
        return {name: np.tile(np.asarray(t, np.float32), (k, 1))
                for name, t in self.hypers().items()}

    def writeback(self, params, velocities) -> None:
        """Push fused-step results back into the unit Arrays (snapshotter /
        plotters / unit-mode interop see the same state)."""
        for f in self.forwards:
            if f.has_weights:
                for k, a in f.params().items():
                    a.devmem = params[f.name][k]
                gd = self.gd_of.get(f.name)
                if gd is not None:
                    for k, a in gd._velocities.items():
                        a.devmem = velocities[f.name][k]

    # -- the pure step ---------------------------------------------------------

    def forward_pass(self, params, x, key, train: bool, cast=None):
        """Compose the units' pure applies; returns the last unit's output
        (LOGITS for a softmax last layer — loss and probs both derive from
        them, matching the evaluator's math).  ``cast`` re-casts activations
        between layers in mixed precision (matmul/conv accumulate f32 via
        preferred_element_type, outputs drop back to bf16)."""
        import jax

        from znicz_tpu.ops.linear import linear

        h = x
        last = self.forwards[-1]
        for i, f in enumerate(self.forwards):
            if cast is not None:
                h = cast(h)
            p = params.get(f.name, {})
            if isinstance(f, self._dropout_cls):
                if train:
                    k = jax.random.fold_in(key, i)
                    m = f.make_mask(k, h.shape, f.dropout_ratio)
                    h = h * m
                # eval: identity
            elif isinstance(f, self._stochpool_cls):
                win = f.windows(h)
                if train:
                    k = jax.random.fold_in(key, i)
                    h, _ = f._select_stochastic(win, k)
                else:
                    h, _ = f._select_expected(win)
            elif f is last and isinstance(f, self._softmax_cls):
                h = linear(h, p["weights"], p.get("bias"),
                           weights_transposed=f.weights_transposed)
                h = h.reshape((x.shape[0],) + f.output_sample_shape)
            else:
                h = f.apply(p, h)
        return h

    def loss_and_metrics(self, params, data, target, batch_size, key,
                         train: bool):
        import jax.numpy as jnp

        import jax

        if self.compute_dtype == np.dtype("float32"):
            cast = None
            cparams = params
            out = self.forward_pass(cparams, data, key, train)
        else:
            def cast(t):
                return t.astype("bfloat16") if t.dtype == jnp.float32 else t

            cparams = jax.tree_util.tree_map(cast, params)
            out = self.forward_pass(cparams, cast(data), key, train,
                                    cast=cast)
        out = out.astype("float32")
        n = out.shape[0]
        valid = (jnp.arange(n) < batch_size)
        denom = jnp.maximum(batch_size, 1)
        if self.loss_kind == "softmax":
            logits = out
            labels = target
            logz = jax.nn.logsumexp(logits, axis=-1)
            ll = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
            loss = jnp.sum(jnp.where(valid, logz - ll, 0.0)) / denom
            pred = jnp.argmax(logits, axis=-1)
            n_err = jnp.sum((pred != labels) & valid)
            if self.compute_confusion:
                n_classes = logits.shape[-1]
                conf = jnp.zeros((n_classes, n_classes), jnp.int32).at[
                    pred, labels].add(valid.astype(jnp.int32))
            else:
                conf = jnp.zeros((1, 1), jnp.int32)
            return loss, (loss, n_err, conf)
        else:
            y = out.reshape(n, -1)
            t = target.reshape(n, -1)
            diff = (y - t) * valid[:, None]
            loss = 0.5 * jnp.sum(jnp.square(diff)) / denom
            return loss, (loss, jnp.int32(0), jnp.zeros((1, 1), jnp.int32))

    #: FC layers at least this wide get tensor-parallel row sharding when
    #: the mesh has a ``model`` axis (AlexNet's 4096-wide fc6/fc7)
    tp_threshold = 1024

    #: rematerialize the forward during backward (``jax.checkpoint``) —
    #: trades ~1/3 more FLOPs for not keeping activations live, the
    #: standard HBM lever for big batches/models
    #: (root.common.engine.remat or FusedTrainer(..., remat=True))
    remat = False

    def param_sharding(self, name, k, arr):
        """Per-param placement: wide (out, in) FC weights shard their output
        rows over the ``model`` axis (and the matching bias over ``model``);
        everything else replicates.  XLA/GSPMD propagates the activation
        shardings and inserts the collectives."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh
        if ("model" in mesh.axis_names
                and mesh.shape["model"] > 1
                and int(arr.shape[0]) >= self.tp_threshold
                and int(arr.shape[0]) % mesh.shape["model"] == 0):
            if getattr(arr, "ndim", len(arr.shape)) == 2:
                return NamedSharding(mesh, P("model", None))
            if getattr(arr, "ndim", len(arr.shape)) == 1:
                return NamedSharding(mesh, P("model"))
        return NamedSharding(mesh, P())

    def _step_core(self, params, velocities, hypers, dataset, targets, idx,
                   batch_size, key):
        """One pure train step (traced): gather -> fwd -> grads -> per-layer
        sgd update.  Shared by the single-step jit and the scan chunk."""
        import jax

        data = jax.numpy.take(dataset, idx, axis=0)
        tgt = jax.numpy.take(targets, idx, axis=0)
        if self.mesh is not None:
            # dataset stays replicated; the gathered minibatch is what
            # shards over the data axis (XLA then keeps the whole
            # fwd/bwd batch-sharded and psums the grads over ICI)
            from znicz_tpu.parallel.mesh import data_sharding

            shard = data_sharding(self.mesh)
            data = jax.lax.with_sharding_constraint(data, shard)
            tgt = jax.lax.with_sharding_constraint(tgt, shard)

        def lf(p):
            return self.loss_and_metrics(p, data, tgt, batch_size, key,
                                         train=True)

        if self.remat:
            # recompute the forward during the backward instead of keeping
            # activations live (SURVEY hot-path note: remat is the HBM
            # lever; ~1/3 extra FLOPs)
            lf = jax.checkpoint(lf)
        grads, metrics = jax.grad(lf, has_aux=True)(params)
        new_p, new_v = {}, {}
        for name, layer_p in params.items():
            lr, lrb, wd, wdb, l1l2, mom, momb, clip = hypers[name]
            new_p[name], new_v[name] = {}, {}
            for k, w in layer_p.items():
                g = grads[name][k].astype("float32")
                is_bias = (k == "bias")
                new_p[name][k], new_v[name][k] = sgd_update(
                    w, g, velocities[name][k],
                    lr=(lrb if is_bias else lr),
                    weights_decay=(wdb if is_bias else wd),
                    l1_vs_l2=l1l2,
                    momentum=(momb if is_bias else mom), clip=clip)
        return new_p, new_v, metrics

    def make_train_step(self):
        """The step takes ``hypers`` as a traced argument so per-epoch lr
        adjustment (LearningRateAdjust) never recompiles."""
        import jax

        return jax.jit(self._step_core, donate_argnums=(0, 1))

    def make_train_scan(self):
        """K steps in ONE dispatch via ``lax.scan`` over stacked
        (idx, batch_size, step_number) rows — K is static per (K,) shape.
        Each scanned step is the same ``_step_core`` with the same per-step
        key the sequential path would draw (``fold_in(base, step)`` runs
        IN-GRAPH — eager per-step key construction costs several dispatches
        each, ~3ms/key on tunneled links), so semantics are identical; what
        changes is dispatch count, which dominates wall time on
        high-latency links (tunneled TPU: ~20ms/dispatch vs ~5ms compute —
        bench r3).  Metrics come back stacked, one per step."""
        import jax

        import jax.numpy as jnp

        nc = (self.forwards[-1].output_samples_number
              if self.loss_kind == "softmax" and self.compute_confusion
              else 1)

        def chunk(params, velocities, hypers_mat, dataset, targets,
                  idx_mat, bs_vec, base_key, step_nums):
            def body(carry, xs):
                p, v, conf_acc = carry
                idx, bs, step, hypers = xs
                key = jax.random.fold_in(base_key, step)
                p, v, (loss, n_err, conf) = self._step_core(
                    p, v, hypers, dataset, targets, idx, bs, key)
                # confusion SUMS on device in the carry: stacking K
                # (C,C) matrices and pulling them per step was the real-
                # training bottleneck on slow links (28MB/segment for the
                # 1000-class head); the Decision only ever accumulates
                return (p, v, conf_acc + conf), (loss, n_err)

            (p, v, conf_sum), ms = jax.lax.scan(
                body, (params, velocities, jnp.zeros((nc, nc), jnp.int32)),
                (idx_mat, bs_vec, step_nums, hypers_mat))
            return p, v, ms, conf_sum

        return jax.jit(chunk, donate_argnums=(0, 1))

    def make_eval_scan(self):
        """Metrics for K eval minibatches (TEST/VALID) in one dispatch —
        params don't change between eval steps, so the scan is a pure map;
        metrics come back stacked and are fed to the Decision in order."""
        import jax

        import jax.numpy as jnp

        nc = (self.forwards[-1].output_samples_number
              if self.loss_kind == "softmax" and self.compute_confusion
              else 1)

        @jax.jit
        def chunk(params, dataset, targets, idx_mat, bs_vec):
            def body(conf_acc, xs):
                idx, bs = xs
                data = jax.numpy.take(dataset, idx, axis=0)
                tgt = jax.numpy.take(targets, idx, axis=0)
                _, (loss, n_err, conf) = self.loss_and_metrics(
                    params, data, tgt, bs, self._key0, train=False)
                return conf_acc + conf, (loss, n_err)

            conf_sum, ms = jax.lax.scan(
                body, jnp.zeros((nc, nc), jnp.int32), (idx_mat, bs_vec))
            return ms, conf_sum

        return chunk

    def make_eval_step(self):
        """Metrics-only step.  ``train`` is static: True replays the exact
        train-mode forward (dropout/stochastic masks from the same key) —
        used at epoch tails to let the Decision rule on this minibatch's
        metrics BEFORE the update is adopted, matching the unit path where
        gd_skip gates the final update off once ``complete`` flips."""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(6,))
        def step(params, dataset, targets, idx, batch_size, key, train):
            data = jax.numpy.take(dataset, idx, axis=0)
            tgt = jax.numpy.take(targets, idx, axis=0)
            _, metrics = self.loss_and_metrics(
                params, data, tgt, batch_size, key, train=train)
            return metrics

        return step

    # -- the epoch driver ------------------------------------------------------

    #: scan this many consecutive TRAIN steps per dispatch (the epoch tail
    #: and eval minibatches always go one-at-a-time, preserving the
    #: Decision's gd_skip semantics).  1 disables scanning.
    scan_chunk = 8

    def _advance(self):
        """Advance the loader one minibatch and snapshot its state (the
        fused path consumes index state only — ``indices_only``)."""
        loader = self.loader
        loader.run()
        return {
            "idx": np.array(loader.minibatch_indices.mem, np.int32),
            "class": int(loader.minibatch_class),
            "size": int(loader.minibatch_size),
            "last_minibatch": bool(loader.last_minibatch),
            "class_ended": bool(loader.class_ended),
            "epoch_number": int(loader.epoch_number),
        }

    def run(self) -> None:
        """Train until the decision completes, mirroring the loader's
        epoch/class state machine but with fused steps.  Feeds the Decision
        unit per-minibatch so its improvement/stop/log semantics (and the
        snapshotter trigger) behave exactly like the unit path.

        Consecutive non-tail TRAIN minibatches are executed as ONE
        ``lax.scan`` dispatch of up to ``scan_chunk`` steps (identical math
        and per-step keys; Decision is fed each scanned step's metrics in
        order afterwards — it cannot flip ``complete`` mid-class, only at
        the epoch tail, which always runs one-at-a-time)."""
        from znicz_tpu.loader.base import TRAIN

        wf = self.workflow
        loader, decision = self.loader, self.decision
        if self._train_step is None:
            self._train_step = self.make_train_step()
            self._eval_step = self.make_eval_step()
        if self._train_scan is None and self.scan_chunk > 1:
            self._train_scan = self.make_train_scan()
            self._eval_scan = self.make_eval_scan()
        params = self.extract_params()
        velocities = self.extract_velocities()
        dataset = loader.original_data.devmem
        if self.loss_kind == "softmax":
            targets = loader.original_labels.devmem
        else:
            targets = loader.original_targets.devmem
        repl = None
        if self.mesh is not None:
            import jax
            from znicz_tpu.parallel.mesh import replicated

            repl = replicated(self.mesh)
            params = {name: {k: jax.device_put(
                a, self.param_sharding(name, k, a))
                for k, a in layer.items()}
                for name, layer in params.items()}
            velocities = {name: {k: jax.device_put(
                a, self.param_sharding(name, k, a))
                for k, a in layer.items()}
                for name, layer in velocities.items()}
            dataset = jax.device_put(dataset, repl)
            targets = jax.device_put(targets, repl)

        def feed_decision(mb, metrics):
            loss, n_err, conf = metrics
            decision.minibatch_class = mb["class"]
            decision.last_minibatch = mb["last_minibatch"]
            decision.class_ended = mb["class_ended"]
            decision.epoch_number = mb["epoch_number"]
            decision.class_lengths = loader.class_lengths
            decision.minibatch_size = mb["size"]
            decision.minibatch_loss = float(loss)
            if hasattr(decision, "minibatch_n_err"):
                decision.minibatch_n_err = int(n_err)
                # None = already accounted via a device-side running sum
                # (DecisionBase skips None); transferred at segment/epoch
                # granularity, not per minibatch
                decision.confusion_matrix = (None if conf is None
                                             else np.asarray(conf))
            decision.run()

        seen_kinds = set()
        last_end = [None]       # end of the last accounted interval

        def account(n_steps, n_images, t0, is_train, kind="train"):
            # charge [max(t0, last interval end), now]: with the pipeline,
            # segment N's flush happens during iteration N+1, whose own
            # t0 predates the flush — naive (now - t0) intervals overlap
            # and double-count wall time
            now = _time.perf_counter()
            start = t0 if last_end[0] is None else max(t0, last_end[0])
            dt = max(now - start, 1e-9)
            last_end[0] = now
            stats["wall_s"] += dt
            stats["last_step_ms"] = round(dt / n_steps * 1e3, 3)
            if is_train:
                stats["train_steps"] += n_steps
                stats["images"] += n_images
            else:
                stats["eval_steps"] += n_steps
            total = stats["train_steps"] + stats["eval_steps"]
            stats["steps_per_sec"] = round(total / stats["wall_s"], 2)
            stats["img_per_sec"] = round(
                stats["images"] / stats["wall_s"], 2)
            if kind in seen_kinds:      # first call of a kind pays compile
                stats["warm_steps"] += n_steps
                stats["warm_images"] += n_images
                stats["warm_wall_s"] += dt
                if stats["warm_wall_s"] > 0:
                    stats["warm_img_per_sec"] = round(
                        stats["warm_images"] / stats["warm_wall_s"], 2)
            seen_kinds.add(kind)

        def epoch_end_hook():
            self.writeback(params, velocities)
            snap = getattr(wf, "snapshotter", None)
            if snap is not None and not bool(snap.gate_skip):
                snap.epoch_number = decision.epoch_number
                snap.improved = decision.improved
                snap.run()
            # epoch-granular observers work here too: writeback just put
            # current weights into the unit Arrays and the decision holds
            # this epoch's metrics (ImageSaver stays unit-engine-only —
            # it needs per-minibatch host data the fast path never pulls)
            for plotter in getattr(wf, "plotters", None) or []:
                plotter.run()

        def put(x):
            if repl is None:
                return x
            import jax

            return jax.device_put(x, repl)

        def advance_lr():
            if self._lr_adjust is not None:
                self._lr_adjust.run()

        def hypers_rows(k):
            """Per-step hypers for a k-step scan, advancing any LR
            schedule between steps exactly like the unit graph does."""
            if self._lr_adjust is None:
                return self.tiled_hypers(k)
            rows = []
            for _ in range(k):
                rows.append({name: np.asarray(t, np.float32)
                             for name, t in self.hypers().items()})
                advance_lr()
            return {name: np.stack([r[name] for r in rows])
                    for name in rows[0]}

        import time as _time

        stats = self.stats
        was_indices_only = loader.indices_only
        loader.indices_only = True
        pending = None                  # an advanced-but-unprocessed mb
        inflight = None                 # (seg, kind, device results, t0)
        epoch_conf = None               # device-side confusion running sum

        def flush():
            """Sync + feed the in-flight TRAIN segment's metrics.  Runs
            AFTER the next segment is dispatched, so the host round-trip
            overlaps device compute (one-deep pipeline); non-tail TRAIN
            feeds cannot flip `complete`/`gd_skip`, so deferring them one
            segment changes no control flow — tails/eval flush first.
            Confusion stays on device (``epoch_conf``), transferred once
            at the epoch tail."""
            nonlocal inflight, epoch_conf
            if inflight is None:
                return
            seg, kind, res, t0 = inflight
            inflight = None
            if kind == "single":
                loss, n_err, conf = res
                epoch_conf = conf if epoch_conf is None \
                    else epoch_conf + conf
                stacked = [(loss, n_err, None)]
            else:
                ms, conf_sum = res
                epoch_conf = conf_sum if epoch_conf is None \
                    else epoch_conf + conf_sum
                losses, n_errs = (np.asarray(m) for m in ms)
                stacked = [(losses[i], n_errs[i], None)
                           for i in range(len(seg))]
            for s, m in zip(seg, stacked):
                feed_decision(s, m)
            account(len(seg), sum(s["size"] for s in seg), t0, True,
                    kind=f"train_{kind}_{len(seg)}")

        try:
            while not bool(decision.complete):
                t_iter = _time.perf_counter()
                mb = pending if pending is not None else self._advance()
                pending = None
                is_train = (mb["class"] == TRAIN)
                if is_train and not mb["last_minibatch"]:
                    # collect the segment of consecutive non-tail TRAIN
                    # minibatches (they cannot flip `complete`) and run it
                    # as one scan dispatch
                    seg = [mb]
                    max_seg = self.scan_chunk if self._train_scan else 1
                    while len(seg) < max_seg:
                        nxt = self._advance()
                        if nxt["class"] == TRAIN and \
                                not nxt["last_minibatch"]:
                            seg.append(nxt)
                        else:
                            pending = nxt
                            break
                    gen = prng.get("fused_trainer")
                    if len(seg) == 1:
                        key = gen.jax_key(self.steps_done)
                        params, velocities, metrics = self._train_step(
                            params, velocities, self.hypers(), dataset,
                            targets, put(seg[0]["idx"]),
                            np.int32(seg[0]["size"]), key)
                        advance_lr()
                        result = ("single", metrics)
                    else:
                        idx_mat = put(np.stack([s["idx"] for s in seg]))
                        bs_vec = put(np.array([s["size"] for s in seg],
                                              np.int32))
                        steps = np.arange(self.steps_done,
                                          self.steps_done + len(seg),
                                          dtype=np.int32)
                        params, velocities, ms, conf_sum = \
                            self._train_scan(
                                params, velocities,
                                put(hypers_rows(len(seg))), dataset,
                                targets, idx_mat, bs_vec,
                                put(gen.jax_base_key()), put(steps))
                        result = ("scan", (ms, conf_sum))
                    self.steps_done += len(seg)
                    flush()             # previous segment, AFTER dispatch
                    inflight = (seg, result[0], result[1], t_iter)
                elif is_train:
                    flush()
                    # epoch tail: metrics first, Decision rules, and the
                    # update applies only if gd_skip stayed open
                    # (unit-path parity).  The epoch's device-side
                    # confusion sum rides along in this one transfer.
                    idx = put(mb["idx"])
                    bs = np.int32(mb["size"])
                    key = prng.get("fused_trainer").jax_key(self.steps_done)
                    loss, n_err, conf = self._eval_step(
                        params, dataset, targets, idx, bs, key, True)
                    if epoch_conf is not None:
                        conf = epoch_conf + conf
                        epoch_conf = None
                    feed_decision(mb, (loss, n_err, conf))
                    if not bool(decision.gd_skip):
                        params, velocities, _ = self._train_step(
                            params, velocities, self.hypers(), dataset,
                            targets, idx, bs, key)
                        advance_lr()    # adj is gated like the gds
                    self.steps_done += 1
                    account(1, mb["size"], t_iter, True, kind="tail")
                else:
                    flush()
                    # TEST/VALID: params are frozen, so consecutive eval
                    # minibatches of the SAME class scan as a pure map in
                    # one dispatch (segments must not span the TEST|VALID
                    # boundary — the segment's summed confusion is booked
                    # to the first minibatch's class)
                    seg = [mb]
                    max_seg = self.scan_chunk if self._eval_scan else 1
                    while len(seg) < max_seg:
                        nxt = self._advance()
                        if nxt["class"] == mb["class"]:
                            seg.append(nxt)
                        else:
                            pending = nxt
                            break
                    if len(seg) == 1:
                        stacked = [self._eval_step(
                            params, dataset, targets, put(mb["idx"]),
                            np.int32(mb["size"]), self._key0, False)]
                    else:
                        idx_mat = put(np.stack([s["idx"] for s in seg]))
                        bs_vec = put(np.array([s["size"] for s in seg],
                                              np.int32))
                        ms, conf_sum = self._eval_scan(
                            params, dataset, targets, idx_mat, bs_vec)
                        losses, n_errs = (np.asarray(m) for m in ms)
                        # segment confusion fed once, with the first step
                        stacked = [(losses[i], n_errs[i],
                                    conf_sum if i == 0 else None)
                                   for i in range(len(seg))]
                    for s, m in zip(seg, stacked):
                        feed_decision(s, m)
                    account(len(seg), 0, t_iter, False,
                            kind=f"eval_{len(seg)}")
                if bool(decision.epoch_ended):
                    epoch_end_hook()
                    # consume the flag: with the pipeline, the next loop
                    # iteration may not feed the decision before this
                    # check runs again, and a stale True would re-save
                    # the 'best' snapshot with weights already advanced
                    # past the epoch boundary
                    decision.epoch_ended.set(False)
            flush()
            self.writeback(params, velocities)
        finally:
            loader.indices_only = was_indices_only
