"""Wire protocol v3 codec for the async master/slave stack (ISSUE 3).

v2 moved every job and every update as ONE ``pickle.dumps`` blob: the
array data was copied into the pickle stream byte by byte, bytes-on-wire
scaled with full f32 param size, and nothing on the wire said what was
inside without unpickling it.  v3 makes every message a ZMQ MULTIPART:

    frame 0:  b"ZNW3" + pickle of (message skeleton, tensor manifest)
    frame 1+: one RAW buffer per tensor, in manifest order

The skeleton is the original request/reply dict with every ndarray
replaced by a :class:`_Slot` index; the manifest records each tensor's
shape, logical dtype, wire encoding (``raw`` / ``bfloat16`` / ``int8``
+ per-tensor absmax scale), optional compression, and the exact frame
length — so a torn or corrupted tensor frame is DETECTED at decode
(length mismatch), never silently reshaped into garbage.  Tensor bytes
are handed to ZMQ as memoryviews of the arrays themselves (zero-copy:
no pickle of array data, no intermediate blob); metadata stays pickle
(same trusted-cluster assumption server.py documents).

Delta quantization (Seide et al. 2014; Lin et al. 2018): a
:class:`DeltaEncoder` encodes weight deltas as bf16 (2 bytes/el) or int8
with a per-tensor absmax scale (1 byte/el, ~4x fewer bytes than f32) and
keeps an ERROR-FEEDBACK residual per tensor — the quantization error of
update N is added back into update N+1 before quantizing, so the error
never accumulates and convergence matches the f32 wire (proven by
tests/test_wire.py's seeded parity run).  Non-finite deltas are shipped
raw on purpose: int8 cannot carry a NaN, and the server's quarantine
must still see a diverging slave's NaNs.

Cold-path weight broadcasts (master -> slave params) can additionally be
zlib/lz4-compressed per tensor (``root.common.engine.wire_compress``);
compression is only kept when it actually shrinks the frame.

A peer still speaking v2 framing (one pickled frame) is detected by the
missing magic; :func:`decode_message` returns it with ``legacy=True`` so
the server can answer in kind — including the clear protocol-version
refusal an out-of-date slave must receive in a format it can read.

Optional metadata keys ride the pickled skeleton and cost nothing when
absent; old peers decode them as unknown dict entries and ignore them.
The conventions so far: ``trace_id`` (ISSUE 5 cross-process span
correlation), and — serving, ISSUE 6 — ``deadline_ms`` (a per-request
deadline BUDGET; budgets cross the wire, never absolute timestamps,
because peer clocks differ), ``client`` (admission identity for rate
limits / fair queueing), ``policy`` (which admission policy refused a
request: shed / oversized / rate_limited / deadline) and ``gen`` (the
snapshot generation that computed a reply).
"""

from __future__ import annotations

import pickle
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from znicz_tpu.telemetry.metrics import registered_property

#: v3 metadata-frame magic; a frame without it is legacy (v2) pickle
MAGIC = b"ZNW3"

#: supported delta encodings (root.common.engine.wire_dtype)
WIRE_DTYPES = ("float32", "bfloat16", "int8")

#: per-tensor compression is skipped below this many bytes (header
#: overhead would beat the savings) and dropped when it does not shrink
MIN_COMPRESS_BYTES = 512

try:                                    # optional: container may lack lz4
    import lz4.frame as _lz4
except Exception:                       # pragma: no cover - env dependent
    _lz4 = None


class WireError(ValueError):
    """A frame stack that is not a decodable v3 (or legacy v2) message."""


def canonical_wire_dtype(name: str) -> str:
    """Normalize config spellings (``bf16`` -> ``bfloat16``; ``f32``/empty
    -> ``float32``); unknown names raise so a typo cannot silently mean
    'no compression'."""
    alias = {"": "float32", "f32": "float32", "fp32": "float32",
             "bf16": "bfloat16", "none": "float32"}
    out = alias.get(str(name).lower(), str(name).lower())
    if out not in WIRE_DTYPES:
        raise ValueError(f"unknown wire_dtype {name!r}; "
                         f"expected one of {WIRE_DTYPES}")
    return out


class _Slot:
    """Placeholder left in the pickled skeleton where tensor *i* goes."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __reduce__(self):
        return (_Slot, (self.i,))


# -- bf16 <-> f32 (bit-level; no ml_dtypes dependency) -------------------------


def f32_to_bf16(a: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even truncation of float32 to bfloat16 bits
    (uint16).  NaN is pinned to the canonical quiet NaN so the
    round-carry cannot walk a NaN payload into the infinity space."""
    a32 = np.ascontiguousarray(a, np.float32)
    bits = a32.view(np.uint32)
    rounded = (bits + (np.uint32(0x7FFF) + ((bits >> 16) & 1))) >> 16
    out = rounded.astype(np.uint16)
    nan = np.isnan(a32)
    if nan.any():
        out = np.where(nan, np.uint16(0x7FC0), out)
    return out


def bf16_to_f32(u16: np.ndarray) -> np.ndarray:
    return (np.ascontiguousarray(u16, np.uint16).astype(np.uint32)
            << 16).view(np.float32)


# -- quantized tensors ---------------------------------------------------------


class QuantizedTensor:
    """A delta tensor already encoded for the wire: ``data`` is the raw
    uint16 (bf16) or int8 payload, ``scale`` the int8 absmax scale (data
    * scale reconstructs), ``shape`` the logical f32 shape.  The encoder
    ships ``data`` as one zero-copy frame; the decoder dequantizes back
    to float32, so everything downstream (quarantine, apply_deltas) sees
    plain arrays."""

    __slots__ = ("wire", "data", "scale", "shape")

    def __init__(self, wire: str, data: np.ndarray, scale: float,
                 shape: Tuple[int, ...]):
        self.wire = wire
        self.data = data
        self.scale = float(scale)
        self.shape = tuple(shape)


def quantize(arr: np.ndarray, wire_dtype: str):
    """Encode a float delta for the wire; returns a QuantizedTensor, or
    the array itself when no quantization applies (float32 wire, or a
    non-finite payload that must reach the server's quarantine
    undisguised)."""
    wire_dtype = canonical_wire_dtype(wire_dtype)
    # asarray, NOT ascontiguousarray: the latter promotes 0-d to 1-d and
    # the logical shape must survive the trip (the encoder re-packs the
    # buffer contiguously itself)
    a = np.asarray(arr, np.float32)
    if wire_dtype == "float32" or not np.all(np.isfinite(a)):
        return a
    if wire_dtype == "bfloat16":
        return QuantizedTensor("bfloat16", f32_to_bf16(a), 0.0, a.shape)
    absmax = float(np.max(np.abs(a))) if a.size else 0.0
    scale = absmax / 127.0
    if scale == 0.0:
        data = np.zeros(a.shape, np.int8)
    else:
        data = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return QuantizedTensor("int8", data, scale, a.shape)


def dequantize(qt: QuantizedTensor) -> np.ndarray:
    if qt.wire == "bfloat16":
        return bf16_to_f32(qt.data).reshape(qt.shape)
    return (qt.data.astype(np.float32) * np.float32(qt.scale)).reshape(
        qt.shape)


class DeltaEncoder:
    """Per-slave delta quantizer with error feedback (1-bit-SGD style
    residuals): the quantization error of each shipped delta is stored
    and ADDED BACK into the next delta for the same tensor before
    quantizing, so the long-run sum of dequantized deltas tracks the sum
    of true deltas to within one step's quantization error — convergence
    is unchanged while bytes-on-wire drop 2x (bf16) / ~4x (int8)."""

    def __init__(self, wire_dtype: str = "float32"):
        self.wire_dtype = canonical_wire_dtype(wire_dtype)
        self.residuals: Dict[tuple, np.ndarray] = {}

    def encode(self, deltas: Optional[Dict]) -> Optional[Dict]:
        """{layer: {param: f32 array}} -> same structure with
        QuantizedTensor leaves (f32 wire: returned untouched)."""
        if not deltas or self.wire_dtype == "float32":
            return deltas
        out: Dict[str, Dict[str, Any]] = {}
        for name, layer in deltas.items():
            enc: Dict[str, Any] = {}
            for k, d in (layer or {}).items():
                d = np.asarray(d, np.float32)
                key = (name, k)
                r = self.residuals.get(key)
                if r is not None and r.shape == d.shape:
                    d = d + r
                qt = quantize(d, self.wire_dtype)
                if isinstance(qt, QuantizedTensor):
                    self.residuals[key] = d - dequantize(qt)
                else:
                    # raw fallback (non-finite): nothing was lost, so
                    # nothing to feed back
                    self.residuals.pop(key, None)
                enc[k] = qt
            out[name] = enc
        return out


# -- message <-> frames --------------------------------------------------------


def _compress(buf, comp: Optional[str]):
    """(payload, tag): compressed bytes when it helps, else the original
    buffer with no tag."""
    n = buf.nbytes if isinstance(buf, memoryview) else len(buf)
    if comp in (None, "", "none") or n < MIN_COMPRESS_BYTES:
        return buf, None
    if comp == "zlib":
        packed = zlib.compress(bytes(buf), 1)
    elif comp == "lz4":
        if _lz4 is None:                # gated: container may lack it
            return buf, None
        packed = _lz4.compress(bytes(buf))
    else:
        raise ValueError(f"unknown wire compression {comp!r}")
    return (packed, comp) if len(packed) < n else (buf, None)


def _decompress(buf: bytes, tag: Optional[str]) -> bytes:
    if tag is None:
        return buf
    if tag == "zlib":
        return zlib.decompress(buf)
    if tag == "lz4":
        if _lz4 is None:
            raise WireError("peer sent lz4 frames but lz4 is unavailable")
        return _lz4.decompress(buf)
    raise WireError(f"unknown frame compression {tag!r}")


def encode_message(msg: Any, compress: Optional[str] = None
                   ) -> Tuple[List[Any], Dict[str, int]]:
    """Message -> ``[meta_frame, tensor_frame, ...]`` plus an info dict:
    ``raw_bytes`` (f32-equivalent logical tensor bytes), ``wire_bytes``
    (actual tensor frame bytes) and ``tensors``.  ndarray and
    QuantizedTensor leaves anywhere in dicts/lists/tuples become raw
    frames; everything else rides the pickled skeleton."""
    manifest: List[dict] = []
    buffers: List[Any] = []
    info = {"raw_bytes": 0, "wire_bytes": 0, "tensors": 0}

    def _put(x) -> _Slot:
        if isinstance(x, QuantizedTensor):
            data = np.ascontiguousarray(x.data)
            entry = {"w": x.wire, "s": x.scale, "shape": x.shape,
                     "d": "<f4"}
            raw_bytes = int(np.prod(x.shape, dtype=np.int64)) * 4
        else:
            # NB: ascontiguousarray promotes 0-d to 1-d — the manifest
            # must record the ORIGINAL shape or scalars come back (1,)
            data = np.ascontiguousarray(x)
            entry = {"w": "raw", "shape": x.shape, "d": data.dtype.str}
            raw_bytes = data.nbytes
        payload, tag = _compress(memoryview(data.reshape(-1)), compress)
        if tag is not None:
            entry["c"] = tag
            entry["rn"] = data.nbytes       # decompressed length check
        n = payload.nbytes if isinstance(payload, memoryview) \
            else len(payload)
        entry["n"] = n                      # exact frame length check
        manifest.append(entry)
        buffers.append(payload)
        info["raw_bytes"] += raw_bytes
        info["wire_bytes"] += n
        info["tensors"] += 1
        return _Slot(len(manifest) - 1)

    def _walk(obj):
        if isinstance(obj, QuantizedTensor):
            return _put(obj)
        if isinstance(obj, np.ndarray):
            if obj.dtype == object:         # not buffer-backed: pickle it
                return obj
            return _put(obj)
        if isinstance(obj, dict):
            return {k: _walk(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            walked = [_walk(v) for v in obj]
            return walked if isinstance(obj, list) else tuple(walked)
        return obj

    skeleton = _walk(msg)
    meta = MAGIC + pickle.dumps({"m": skeleton, "t": manifest},
                                pickle.HIGHEST_PROTOCOL)
    return [meta] + buffers, info


def decode_message(frames: List[bytes]) -> Tuple[Any, Dict[str, Any]]:
    """``[meta, tensors...]`` (or one legacy v2 pickle frame) -> the
    message plus info (``legacy`` flag + the same byte accounting as
    encode).  Raises :class:`WireError` on anything undecodable,
    INCLUDING a tensor frame whose length disagrees with the manifest —
    a corrupted buffer must be refused, never reshaped into garbage."""
    if not frames:
        raise WireError("empty frame stack")
    head = bytes(frames[0])
    info: Dict[str, Any] = {"legacy": False, "raw_bytes": 0,
                            "wire_bytes": 0, "tensors": 0}
    if not head.startswith(MAGIC):
        # legacy (v2) framing: exactly one pickled frame
        if len(frames) != 1:
            raise WireError(f"no {MAGIC!r} magic on a "
                            f"{len(frames)}-frame message")
        try:
            obj = pickle.loads(head)
        except Exception as exc:
            raise WireError(f"bad frame: {exc}") from None
        info["legacy"] = True
        return obj, info
    try:
        meta = pickle.loads(head[len(MAGIC):])
        skeleton, manifest = meta["m"], meta["t"]
    except WireError:
        raise
    except Exception as exc:
        raise WireError(f"bad v3 metadata frame: {exc}") from None
    if len(frames) != 1 + len(manifest):
        raise WireError(f"manifest lists {len(manifest)} tensors but "
                        f"{len(frames) - 1} buffer frames arrived")
    tensors: List[np.ndarray] = []
    for i, (entry, buf) in enumerate(zip(manifest, frames[1:])):
        buf = bytes(buf)
        if len(buf) != entry["n"]:
            raise WireError(f"tensor frame {i} is {len(buf)} bytes, "
                            f"manifest says {entry['n']}")
        raw = _decompress(buf, entry.get("c"))
        if "rn" in entry and len(raw) != entry["rn"]:
            raise WireError(f"tensor frame {i} decompressed to "
                            f"{len(raw)} bytes, expected {entry['rn']}")
        shape = tuple(entry["shape"])
        try:
            if entry["w"] == "raw":
                arr = np.frombuffer(raw, dtype=np.dtype(entry["d"])
                                    ).reshape(shape)
            elif entry["w"] in ("bfloat16", "int8"):
                # ONE home for the reconstruction math: rebuild the
                # QuantizedTensor and go through dequantize()
                data = np.frombuffer(
                    raw, np.uint16 if entry["w"] == "bfloat16"
                    else np.int8)
                arr = dequantize(QuantizedTensor(
                    entry["w"], data, entry.get("s", 0.0), shape))
            else:
                raise WireError(f"unknown wire encoding {entry['w']!r}")
        except WireError:
            raise
        except Exception as exc:
            raise WireError(f"tensor frame {i} undecodable: {exc}") \
                from None
        tensors.append(arr)
        info["raw_bytes"] += int(np.prod(shape, dtype=np.int64)) * (
            4 if entry["w"] != "raw" else np.dtype(entry["d"]).itemsize)
        info["wire_bytes"] += len(buf)
        info["tensors"] += 1

    def _unwalk(obj):
        if isinstance(obj, _Slot):
            return tensors[obj.i]
        if isinstance(obj, dict):
            return {k: _unwalk(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            walked = [_unwalk(v) for v in obj]
            return walked if isinstance(obj, list) else tuple(walked)
        return obj

    return _unwalk(skeleton), info


def peek_message(frames: List[bytes]) -> Dict[str, Any]:
    """The v3 metadata SKELETON of a multipart message — decoded WITHOUT
    materializing a single tensor byte (the balancer's routing path:
    per-request it needs ``cmd``/``req_id``/``deadline_ms``, never the
    payload, and the whole point of fronting replicas is that the
    balancer does not decode what it forwards).  Tensor frames are only
    LENGTH-checked against the manifest, so a corrupted buffer is still
    refused here rather than forwarded to a replica that would refuse
    it one hop later.  ndarray leaves appear as :class:`_Slot`
    placeholders; scalar keys read normally.  Raises :class:`WireError`
    on anything undecodable (legacy v2 framing included — a peeking
    peer is a v3-only service)."""
    if not frames:
        raise WireError("empty frame stack")
    head = bytes(frames[0])
    if not head.startswith(MAGIC):
        raise WireError(f"no {MAGIC!r} magic — not a v3 message")
    try:
        meta = pickle.loads(head[len(MAGIC):])
        skeleton, manifest = meta["m"], meta["t"]
    except Exception as exc:
        raise WireError(f"bad v3 metadata frame: {exc}") from None
    if not isinstance(skeleton, dict):
        raise WireError(f"skeleton decodes to "
                        f"{type(skeleton).__name__}, not a message dict")
    if len(frames) != 1 + len(manifest):
        raise WireError(f"manifest lists {len(manifest)} tensors but "
                        f"{len(frames) - 1} buffer frames arrived")
    for i, (entry, buf) in enumerate(zip(manifest, frames[1:])):
        n = buf.nbytes if isinstance(buf, memoryview) else len(buf)
        if n != entry.get("n"):
            raise WireError(f"tensor frame {i} is {n} bytes, manifest "
                            f"says {entry.get('n')}")
    return skeleton


def restamp_message(frames: List[bytes], **keys) -> List[bytes]:
    """Rewrite top-level skeleton keys of a v3 message WITHOUT touching
    its tensor frames (they are shared, not copied — the balancer's
    req_id rewrite and ``lb`` reply stamp ride this).  A key set to
    None is REMOVED.  The caller is expected to have
    :func:`peek_message`-validated the stack; undecodable metadata
    raises :class:`WireError` like everywhere else."""
    head = bytes(frames[0])
    if not head.startswith(MAGIC):
        raise WireError(f"no {MAGIC!r} magic — cannot restamp a "
                        f"non-v3 message")
    try:
        meta = pickle.loads(head[len(MAGIC):])
        skeleton = meta["m"]
    except Exception as exc:
        raise WireError(f"bad v3 metadata frame: {exc}") from None
    if not isinstance(skeleton, dict):
        raise WireError("skeleton is not a message dict")
    for k, v in keys.items():
        if v is None:
            skeleton.pop(k, None)
        else:
            skeleton[k] = v
    new_head = MAGIC + pickle.dumps(meta, pickle.HIGHEST_PROTOCOL)
    return [new_head] + list(frames[1:])


class Codec:
    """Stateful message codec: the v3 encode/decode pair PLUS the byte and
    tensor accounting every peer keeps, with no Server/Client instance
    required (ISSUE 4 satellite).  The master's REP loop and the serving
    frontend share this one home, so the counters — and the frames, which
    are byte-identical to calling :func:`encode_message` /
    :func:`decode_message` directly — cannot drift between services.

    Counters: ``bytes_in``/``bytes_out`` (every frame of every message,
    refusals included), ``tensor_bytes_raw_*``/``tensor_bytes_wire_*``
    (f32-equivalent vs actual tensor bytes per direction — the
    compression-ratio inputs), ``bad_frames`` (undecodable messages
    refused via :meth:`refusal`, plus whatever the owner adds for
    requests that decode but trip its handler).

    Counters live in the process-wide telemetry registry (ISSUE 5) under
    ``component=<owner>`` and are exported on ``/metrics``; the
    historical attribute names remain as thin properties (readable AND
    writable — the master's resume restore writes them back), so every
    caller and resume snapshot sees exactly the ints it always did.
    Each metric carries its own lock, so the old one-thread-per-instance
    confinement is no longer a correctness requirement — it remains the
    performance discipline (the serving frontend does all socket+codec
    work on its router thread; the master's REP loop is single-threaded
    already).
    """

    #: registry counters every Codec instance holds: name -> HELP text
    COUNTERS = {
        "bytes_in": "wire bytes received (all frames)",
        "bytes_out": "wire bytes sent (all frames)",
        "messages_in": "messages decoded",
        "messages_out": "messages encoded",
        "bad_frames": "undecodable/garbage frames refused",
        "tensor_bytes_raw_in": "f32-equivalent tensor bytes received",
        "tensor_bytes_wire_in": "actual tensor bytes received",
        "tensor_bytes_raw_out": "f32-equivalent tensor bytes sent",
        "tensor_bytes_wire_out": "actual tensor bytes sent",
    }

    def __init__(self, compress: Optional[str] = None, owner: str = "wire"):
        from znicz_tpu import telemetry

        #: cold-path per-tensor compression applied by :meth:`encode`
        #: ("none"/""/None = off) — the params-broadcast knob
        self.compress = None if compress in (None, "", "none") else compress
        sc = telemetry.scope(owner)
        self._m = {name: sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self._tracer = telemetry.tracer()

    @staticmethod
    def frames_bytes(frames: List) -> int:
        return sum(f.nbytes if isinstance(f, memoryview) else len(f)
                   for f in frames)

    def decode(self, frames: List[bytes]) -> Tuple[Any, Dict[str, Any]]:
        """:func:`decode_message` plus inbound accounting.  The info dict
        gains ``message_bytes`` (total wire bytes of the message — what
        per-message metrics like ``bytes_per_update`` want).  Raises
        :class:`WireError` exactly like the bare function; the caller
        decides whether that refusal ticks :attr:`bad_frames` (via
        :meth:`refusal`) or is fatal."""
        n = self.frames_bytes(frames)
        self._m["bytes_in"].inc(n)
        if self._tracer.enabled:
            t0 = time.perf_counter()
            msg, info = decode_message(frames)
            self._tracer.add("wire", "decode", t0,
                             time.perf_counter() - t0,
                             {"bytes": n, "tensors": info.get("tensors", 0),
                              "trace_id": msg.get("trace_id")
                              if isinstance(msg, dict) else None})
        else:           # disabled hot path: no clock reads at all
            msg, info = decode_message(frames)
        info["message_bytes"] = n
        self._m["messages_in"].inc()
        self._m["tensor_bytes_raw_in"].inc(info.get("raw_bytes", 0))
        self._m["tensor_bytes_wire_in"].inc(info.get("wire_bytes", 0))
        return msg, info

    def encode(self, msg: Any, legacy: bool = False) -> List[Any]:
        """Message -> reply frames plus outbound accounting.  ``legacy``
        answers a v2-framed peer in kind: one pickled frame (no tensor
        accounting — the blob is opaque), so even an out-of-date peer
        can read its reply."""
        t0 = time.perf_counter() if self._tracer.enabled else None
        if legacy:
            frames = [pickle.dumps(msg)]
        else:
            frames, enc = encode_message(msg, compress=self.compress)
            self._m["tensor_bytes_raw_out"].inc(enc["raw_bytes"])
            self._m["tensor_bytes_wire_out"].inc(enc["wire_bytes"])
        n = self.frames_bytes(frames)
        if t0 is not None:
            self._tracer.add("wire", "encode", t0,
                             time.perf_counter() - t0,
                             {"bytes": n, "legacy": legacy,
                              "trace_id": msg.get("trace_id")
                              if isinstance(msg, dict) else None})
        self._m["bytes_out"].inc(n)
        self._m["messages_out"].inc()
        return frames

    def count_message_in(self, frames: List) -> None:
        """Inbound accounting for a message that was PEEKED
        (:func:`peek_message`), not decoded — the balancer's forward
        path moves frames without materializing tensors, but its
        byte/message counters must not go dark for it."""
        self._m["bytes_in"].inc(self.frames_bytes(frames))
        self._m["messages_in"].inc()

    def count_bad_frame(self) -> None:
        """Tick ``bad_frames`` for a request that DECODED but tripped the
        owner's handler (the owner's half of the fault accounting)."""
        self._m["bad_frames"].inc()

    def refusal(self, cause, legacy: bool = True, **extra) -> List:
        """The counted bad-frame refusal reply: ``bad_frames`` ticks and
        the reply defaults to LEGACY framing — an undecodable request's
        peer format is unknown, and a single pickle is the one framing
        every protocol revision can read.  The payload (slug + wording)
        comes from the transport core's ``bad_frame_reply`` — ONE home,
        every plane (ISSUE 14)."""
        from znicz_tpu.transport.core import bad_frame_reply

        self._m["bad_frames"].inc()
        return self.encode(dict(bad_frame_reply(cause), **extra),
                           legacy=legacy)

    def compression_ratio(self, direction: str = "both"
                          ) -> Optional[float]:
        """f32-equivalent tensor bytes / tensor bytes actually on the
        wire — ``"in"``, ``"out"`` or ``"both"``; None before any tensor
        traffic in that direction."""
        raw = ((self.tensor_bytes_raw_in if direction != "out" else 0)
               + (self.tensor_bytes_raw_out if direction != "in" else 0))
        cooked = ((self.tensor_bytes_wire_in if direction != "out" else 0)
                  + (self.tensor_bytes_wire_out if direction != "in"
                     else 0))
        if not cooked:
            return None
        return raw / cooked


for _name, _help in Codec.COUNTERS.items():
    setattr(Codec, _name, registered_property(_name, _help))
del _name, _help


def split_envelope(frames: List[bytes]
                   ) -> Tuple[List[bytes], List[bytes]]:
    """ROUTER-side framing helper: (routing envelope incl. the empty
    delimiter, payload frames).  REQ prepends [request-id?, empty] and
    ROUTER prepends the peer identity, so the payload starts after the
    FIRST empty frame — but a v3 metadata frame seen BEFORE any
    delimiter means the stack is delimiter-less (direct REP traffic)
    and payload from there: an empty TENSOR frame later in the stack
    must not be mistaken for a delimiter.  A stack with neither
    delimiter nor magic (direct legacy pickle) is all payload."""
    for i, f in enumerate(frames):
        if bytes(f[:len(MAGIC)]) == MAGIC:
            return list(frames[:i]), list(frames[i:])
        if len(f) == 0:
            return list(frames[:i + 1]), list(frames[i + 1:])
    return [], list(frames)
