"""Device mesh construction — the rebuild's replacement for the reference's
master/slave topology (SURVEY.md §2.4): instead of a ZeroMQ star, an SPMD
mesh of TPU chips with named axes:

  - ``data``  — batch sharding (the reference's only strategy, made
    synchronous: psum over ICI instead of async pickle-over-TCP);
  - ``model`` — tensor-parallel sharding of wide FC layers (beyond-reference
    capability, used by AlexNet's fc layers when the mesh has a model axis).

Multi-host: call ``distributed_init()`` once per process before building the
mesh; jax.distributed wires DCN and ``jax.devices()`` becomes global.

This module is also the ONE home of the placement machinery both planes
share (ISSUE 18 — extracted from ``serving/model.py``'s PR 12 build-out):
mesh-from-config construction/refusals for serving AND training, the
``param_sharding`` rule (wide FC weights column-shard over ``model``),
params/velocities tree placement via ``global_put``, the batch
divisibility refusal, and direct per-shard segment staging.  Neither
``serving/model.py`` nor ``parallel/fused.py`` re-implements any of it.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` across jax versions: promoted out of
    ``jax.experimental.shard_map`` after the 0.4.x line, and this is
    the one spot that has to know which home this interpreter has."""
    import jax

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    return sm(f, *args, **kwargs)


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axes: Sequence[str] = ("data",), devices=None):
    """Build a Mesh over ``devices`` (default: all).  shape=None puts every
    device on the first axis."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    n = int(np.prod(shape))
    if n > len(devs):
        # a readable refusal instead of the raw XLA reshape failure:
        # on a CPU host the fix is virtual devices, and the operator
        # needs to know that BEFORE the first backend init
        raise ValueError(
            f"mesh shape {dict(zip(axes, shape))} needs {n} devices, "
            f"but jax sees only {len(devs)} "
            f"({jax.default_backend()} backend).  On a CPU host, "
            f"provision virtual devices BEFORE the first jax backend "
            f"init: znicz_tpu.virtdev.provision_cpu_devices({n}) or "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")
    grid = np.asarray(devs[:n]).reshape(shape)
    return Mesh(grid, tuple(axes))


def mesh_from_axes(dp, mp, plane: str = "mesh"):
    """Validate (data, model) axis sizes and build the mesh — or None for
    the 1x1 default, which keeps the caller on the exact single-device
    code path (bit-for-bit the pre-mesh behavior).  ``plane`` names the
    config tree in the refusal ("serving"/"training")."""
    dp, mp = int(dp), int(mp)
    if dp < 1 or mp < 1:
        raise ValueError(f"{plane} mesh axes must be >= 1, got "
                         f"data={dp} model={mp}")
    if dp * mp == 1:
        return None
    return make_mesh((dp, mp), ("data", "model"))


def serving_mesh_from_config():
    """The serving mesh per ``root.common.serving.mesh.*`` (read through
    a local alias so the config-knob lint tracks the keys), or None for
    the default 1x1."""
    from znicz_tpu.core.config import root

    mc = root.common.serving.mesh
    return mesh_from_axes(mc.get("data", 1), mc.get("model", 1), "serving")


def train_mesh_from_config():
    """The TRAINING mesh per ``root.common.engine.mesh.*`` — gated on
    ``root.common.engine.train_shard`` (default OFF: a slave without the
    gate is bit-for-bit the single-device slave, whatever the mesh knobs
    say).  None when gated off or 1x1."""
    from znicz_tpu.core.config import root

    if not root.common.engine.get("train_shard", False):
        return None
    mc = root.common.engine.mesh
    return mesh_from_axes(mc.get("data", 1), mc.get("model", 1), "training")


def mesh_shape_dict(mesh) -> Optional[Dict[str, int]]:
    """``{"data": dp, "model": mp}`` — the heartbeat/panel form of a
    mesh; None when single-device."""
    if mesh is None:
        return None
    return {str(a): int(mesh.shape[a]) for a in mesh.axis_names}


def data_sharding(mesh):
    """Batch-dim sharding over the ``data`` axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("data"))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def column_sharded(mesh):
    """(out, in) weight sharded by output columns over ``model`` —
    tensor parallelism for wide FC layers."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("model", None))


def param_sharding(mesh, arr, tp_threshold: int = 1024):
    """The ONE per-param placement rule (training and serving): wide
    (out, in) FC weights shard their output rows over the ``model`` axis
    (and the matching 1-D bias over ``model``); everything else
    replicates.  XLA/GSPMD propagates the activation shardings and
    inserts the collectives."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if ("model" in mesh.axis_names
            and mesh.shape["model"] > 1
            and int(arr.shape[0]) >= tp_threshold
            and int(arr.shape[0]) % mesh.shape["model"] == 0):
        ndim = getattr(arr, "ndim", len(arr.shape))
        if ndim == 2:
            return NamedSharding(mesh, P("model", None))
        if ndim == 1:
            return NamedSharding(mesh, P("model"))
    return NamedSharding(mesh, P())


def tree_shardings(mesh, tree, tp_threshold: int = 1024):
    """NamedSharding tree for a two-level {unit: {param: leaf}} tree per
    ``param_sharding`` (leaves need only ``.shape``)."""
    return {name: {k: param_sharding(mesh, a, tp_threshold)
                   for k, a in layer.items()}
            for name, layer in tree.items()}


def place_tree(mesh, tree, tp_threshold: int = 1024):
    """Distribute a params/velocities tree onto the mesh per its
    shardings (``global_put``: each process contributes only the shards
    it owns — no device-0 round trip on multi-host)."""
    return {name: {k: global_put(a, param_sharding(mesh, a, tp_threshold))
                   for k, a in layer.items()}
            for name, layer in tree.items()}


def require_batch_divisible(rows: int, mesh) -> int:
    """The batch-vs-data-axis divisibility refusal (explicit sharded
    placement cannot pad); returns dp.  Shared by serving's stage and
    the training staging path."""
    dp = int(mesh.shape["data"])
    if int(rows) % dp:
        raise ValueError(
            f"batch of {rows} rows does not divide across "
            f"the mesh's data axis (dp={dp}); pad to a ladder rung "
            f"(rungs are snapped to multiples of dp)")
    return dp


def segment_sharding(mesh):
    """Staged (K, B, ...) segment tensors shard the BATCH dim:
    ``P(None, "data")`` — sliced per scan step, each (B, ...) minibatch
    keeps its ``data`` sharding with no resharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(None, "data"))


def put_sharded_segment(shape, sharding, gather, idx_mat):
    """Assemble + place ONE staged (K, B, ...) segment batch-sharded,
    DIRECTLY from the host (one transfer per device shard, never a
    gather through device 0).  In a MULTI-CONTROLLER run each process
    host-gathers ONLY the rows of the batch shards its own devices hold
    (jax.make_array_from_callback) — the SPMD analogue of the
    reference's per-slave minibatch feed: no host ever touches another
    host's samples."""
    import jax

    n_steps = int(idx_mat.shape[0])
    if jax.process_count() == 1:
        flat = idx_mat.reshape(-1)
        return jax.device_put(gather(flat).reshape(shape), sharding)

    def cb(index):
        # index: per-shard slices over (step, batch, *sample); only the
        # batch dim is sharded — gather exactly those rows
        ks = range(*index[0].indices(n_steps))
        rows = np.stack([gather(idx_mat[k, index[1]]) for k in ks])
        return rows[(slice(None), slice(None)) + tuple(index[2:])]

    return jax.make_array_from_callback(shape, sharding, cb)


def global_put(value, sharding):
    """``jax.device_put`` that also works when the sharding's mesh spans
    PROCESSES (multi-host): every process contributes the shards it owns
    from its host-replicated ``value`` via make_array_from_callback, so no
    cross-host device transfer is needed (jax refuses plain device_put to
    non-addressable devices).  Single-process meshes take the plain put."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(value, sharding)

    def put_leaf(v):
        if isinstance(v, jax.Array) and v.sharding == sharding:
            return v                     # already globally placed
        v = np.asarray(v)
        return jax.make_array_from_callback(v.shape, sharding,
                                            lambda idx, v=v: v[idx])

    return jax.tree_util.tree_map(put_leaf, value)


def distributed_init(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up over DCN (the reference's master/slave handshake
    collapses to jax.distributed).  No-op when single-process."""
    import jax

    if num_processes and num_processes > 1:
        try:
            # jax 0.4.x CPU backends refuse multiprocess computations
            # ("not implemented") unless a CPU collectives impl is
            # switched on explicitly; newer jax defaults to gloo.  Must
            # happen before initialize() wires the backend.
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, KeyError):
            pass                     # option gone (newer jax): default ok
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
