"""Device mesh construction — the rebuild's replacement for the reference's
master/slave topology (SURVEY.md §2.4): instead of a ZeroMQ star, an SPMD
mesh of TPU chips with named axes:

  - ``data``  — batch sharding (the reference's only strategy, made
    synchronous: psum over ICI instead of async pickle-over-TCP);
  - ``model`` — tensor-parallel sharding of wide FC layers (beyond-reference
    capability, used by AlexNet's fc layers when the mesh has a model axis).

Multi-host: call ``distributed_init()`` once per process before building the
mesh; jax.distributed wires DCN and ``jax.devices()`` becomes global.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` across jax versions: promoted out of
    ``jax.experimental.shard_map`` after the 0.4.x line, and this is
    the one spot that has to know which home this interpreter has."""
    import jax

    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    return sm(f, *args, **kwargs)


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axes: Sequence[str] = ("data",), devices=None):
    """Build a Mesh over ``devices`` (default: all).  shape=None puts every
    device on the first axis."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    n = int(np.prod(shape))
    if n > len(devs):
        # a readable refusal instead of the raw XLA reshape failure:
        # on a CPU host the fix is virtual devices, and the operator
        # needs to know that BEFORE the first backend init
        raise ValueError(
            f"mesh shape {dict(zip(axes, shape))} needs {n} devices, "
            f"but jax sees only {len(devs)} "
            f"({jax.default_backend()} backend).  On a CPU host, "
            f"provision virtual devices BEFORE the first jax backend "
            f"init: znicz_tpu.virtdev.provision_cpu_devices({n}) or "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")
    grid = np.asarray(devs[:n]).reshape(shape)
    return Mesh(grid, tuple(axes))


def data_sharding(mesh):
    """Batch-dim sharding over the ``data`` axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("data"))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def column_sharded(mesh):
    """(out, in) weight sharded by output columns over ``model`` —
    tensor parallelism for wide FC layers."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("model", None))


def global_put(value, sharding):
    """``jax.device_put`` that also works when the sharding's mesh spans
    PROCESSES (multi-host): every process contributes the shards it owns
    from its host-replicated ``value`` via make_array_from_callback, so no
    cross-host device transfer is needed (jax refuses plain device_put to
    non-addressable devices).  Single-process meshes take the plain put."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(value, sharding)

    def put_leaf(v):
        if isinstance(v, jax.Array) and v.sharding == sharding:
            return v                     # already globally placed
        v = np.asarray(v)
        return jax.make_array_from_callback(v.shape, sharding,
                                            lambda idx, v=v: v[idx])

    return jax.tree_util.tree_map(put_leaf, value)


def distributed_init(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up over DCN (the reference's master/slave handshake
    collapses to jax.distributed).  No-op when single-process."""
    import jax

    if num_processes and num_processes > 1:
        try:
            # jax 0.4.x CPU backends refuse multiprocess computations
            # ("not implemented") unless a CPU collectives impl is
            # switched on explicitly; newer jax defaults to gloo.  Must
            # happen before initialize() wires the backend.
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, KeyError):
            pass                     # option gone (newer jax): default ok
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
