"""Relay-tree gradient aggregation (ISSUE 10): O(log N) instead of a
star.

The async master/slave stack (server.py / client.py) is a fixed star:
the master decodes EVERY slave's update, so aggregation cost is
O(slaves) in both CPU and ingress bytes — fine at 5 slaves, a wall at
pod scale (ROADMAP item 3).  Wire v3 made the codec standalone
precisely so "a relay is Codec + psum, no Server needed"; this module
cashes that in.

A :class:`Relay` is a node in a reduction tree.  To its CHILDREN
(slaves or lower relays) it is protocol-indistinguishable from the
master: they dial its endpoint with the unchanged Client — same
register handshake, same job/update commands, same reconnect/backoff/
prefetch machinery.  To its UPSTREAM (the master or a higher relay) it
is one slave-shaped peer that happens to speak two batched extensions
of the same wire:

  - **job batching**: ``{"cmd": "job", "count": k}`` fetches up to k
    jobs with ONE params broadcast; the relay re-serves them to its
    children on demand (a relay child asks with its own ``count``, so
    the amplification compounds per level — at fanout F each tree
    level divides the master's job-request decode count by ~F);
  - **update aggregation**: child deltas are validated at the edge
    (finite/shape/norm checks mirroring the master's quarantine, so one
    poisoned child is refused HERE, never after corrupting a partial
    sum), sum-reduced in float32, and flushed upward as ONE combined
    delta re-encoded per ``root.common.engine.wire_dtype`` through a
    :class:`wire.DeltaEncoder` — the relay keeps its own error-feedback
    residuals, so re-quantizing the sum loses nothing over time — plus
    a per-contributor manifest (slave ids, job ids, metrics, trace_ids)
    the master uses to keep its accounting EXACT: Decision feeds,
    quarantine counters, per-slave job history, adaptive-reap duration
    samples and the requeue-per-child refusal policy all behave as if
    each update had arrived individually.

Failure semantics: a relay holds no training state — jobs sitting in
its queue or contributions in its flush buffer when it dies are
recovered by the master's existing TTL reaper (``jobs_requeued``), and
its children fall back to the UPSTREAM endpoint the relay advertised in
its register reply (the Client switches endpoints when its reconnect
budget is spent and re-registers through the existing path).  A relay
whose own upstream is gone for good stops serving, so its children see
the same silence a dead master produces.

Staleness note (documented, not hidden): batched job fetches share one
params snapshot and the flush window delays updates by up to
``relay_flush_s`` — both are the same delay-staleness the async
protocol already exhibits whenever slaves interleave (and what the
seeded tree-vs-star parity band in tests/test_relay.py covers).  A
contributor whose job was reaped while its delta sat in a flush buffer
is dropped from the master's books as stale while its (already-summed)
delta lands — bounded by the flush window, far inside the adaptive reap
timeout.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from znicz_tpu.telemetry.metrics import registered_property as \
    _relay_counter


def parse_relay_spec(spec: str,
                     default_bind: str = "tcp://*:5571"
                     ) -> Tuple[str, str]:
    """``--relay UPSTREAM[:BIND]`` -> (upstream, bind).  BIND may be a
    full endpoint (``tcp://host:5570:tcp://*:5571``) or a bare port
    (``tcp://host:5570:5571`` -> ``tcp://*:5571``); a plain endpoint
    means "default bind".  Anything else raises with the accepted
    forms spelled out (a typo must not silently bind the default)."""
    import re

    m = re.match(r"^(\w+://[^:/]+:\d+)$", spec)
    if m:
        return m.group(1), default_bind
    m = re.match(r"^(\w+://[^:/]+:\d+):(\d+)$", spec)
    if m:
        return m.group(1), f"tcp://*:{m.group(2)}"
    m = re.match(r"^(\w+://[^:/]+:\d+):(\w+://.+)$", spec)
    if m:
        return m.group(1), m.group(2)
    raise ValueError(
        f"unparseable --relay spec {spec!r}; expected "
        "UPSTREAM, UPSTREAM:PORT or UPSTREAM:BIND_ENDPOINT "
        "(e.g. tcp://host:5570:5571)")


def plan_tree(n_slaves: int, fanout: int, master_endpoint: str,
              host: str = "127.0.0.1", base_port: int = 15700) -> Dict:
    """The ``--tree-fanout`` planner: the relay tiers a fleet of
    ``n_slaves`` needs at ``fanout``, as concrete endpoints.

    Returns ``{"relays": [{"bind", "upstream"}, ...],
    "slave_endpoints": [endpoint per slave], "levels": n_levels}`` —
    relays listed top tier (master's children) first, so starting them
    in order brings the tree up parents-before-children.  Ports are
    assigned sequentially from ``base_port``.
    """
    n_slaves = int(n_slaves)
    fanout = int(fanout)
    if n_slaves < 1:
        raise ValueError(f"n_slaves must be >= 1, got {n_slaves}")
    if fanout < 2:
        # ceil(n / 1) never shrinks — a fanout-1 "tree" is a chain that
        # aggregates nothing; refuse instead of looping forever
        raise ValueError(f"tree fanout must be >= 2, got {fanout}")
    # tier sizes bottom-up: each tier has ceil(below / fanout) nodes,
    # until a tier fits under the master directly
    tiers_up: List[int] = []
    below = n_slaves
    while below > fanout:
        below = -(-below // fanout)          # ceil
        tiers_up.append(below)
    if not tiers_up and n_slaves > 1:
        tiers_up.append(1)                   # one relay proves the hop
    port = int(base_port)
    relays: List[Dict[str, str]] = []
    binds_by_tier: List[List[str]] = []
    for count in reversed(tiers_up):         # top tier first
        binds = []
        for _ in range(count):
            binds.append(f"tcp://{host}:{port}")
            port += 1
        binds_by_tier.append(binds)
        upstreams = (binds_by_tier[-2] if len(binds_by_tier) > 1
                     else [master_endpoint])
        for i, bind in enumerate(binds):
            relays.append({"bind": bind,
                           "upstream": upstreams[i % len(upstreams)]})
    leaves = binds_by_tier[-1] if binds_by_tier else [master_endpoint]
    slave_endpoints = [leaves[i % len(leaves)] for i in range(n_slaves)]
    return {"relays": relays, "slave_endpoints": slave_endpoints,
            "levels": len(binds_by_tier)}


class Relay:
    """One reduction-tree node: ``serve()`` blocks (or ``start()`` runs
    it on a daemon thread) until the upstream reports training done or
    ``stop()`` is called.

    No workflow needed: the relay validates its children's handshakes
    by PASSING the first one upstream under its own id (the master's
    version/digest check is the single source of truth) and caching the
    validated credentials — later children are checked against the
    cache locally, mismatches refused with the master's own wording.
    """

    #: registry counters (component="relay", labeled by bind) — the
    #: ISSUE 10 families: name -> HELP text
    COUNTERS = {
        "relay_bytes_in": "wire bytes received (children + upstream)",
        "relay_bytes_out": "wire bytes sent (children + upstream)",
        "relay_refusals": "child deltas refused at the edge",
        "relay_bad_frames": "undecodable child frames refused",
        "relay_flushes": "aggregated updates flushed upstream",
        "relay_contributions": "child update contributions accepted",
        "relay_jobs_served": "jobs served to children",
        "relay_upstream_reconnects": "fresh-socket retries upstream",
        "relay_rehomes": "upstream re-homes to the advertised fallback",
        # unified transport core (ISSUE 14): deadline propagation
        "relay_jobs_expired": "queued jobs dropped unserved: deadline "
                              "budget spent (master re-queues them)",
    }

    def __init__(self, upstream: str, bind: str,
                 relay_id: Optional[str] = None, fanout: int = None,
                 flush_s: float = None, recv_timeout: float = 15.0,
                 max_reconnects: int = None, wire_dtype: str = None,
                 child_ttl: float = None):
        from znicz_tpu import telemetry
        from znicz_tpu.core.config import root
        from znicz_tpu.parallel import wire

        self.upstream = upstream
        self.bind = bind
        self.relay_id = relay_id or f"relay-{uuid.uuid4().hex[:8]}"
        #: flush threshold ~= the number of direct children expected to
        #: contribute per round; also the job-batch amplification factor
        self.fanout = int(
            root.common.engine.get("tree_fanout", 2)
            if fanout is None else fanout)
        #: max age of a buffered contribution before a partial flush
        self.flush_s = float(
            root.common.engine.get("relay_flush_s", 0.05)
            if flush_s is None else flush_s)
        self.recv_timeout = float(recv_timeout)
        self.max_reconnects = int(
            root.common.engine.get("slave_reconnects", 8)
            if max_reconnects is None else max_reconnects)
        self.quarantine_norm_mult = float(
            root.common.engine.get("quarantine_norm_mult", 25.0))
        #: membership hygiene, the master's TTL rule at the relay tier:
        #: a child silent this long leaves the table — a dead sibling
        #: must not inflate the flush threshold (and the dashboard)
        #: forever; a re-register brings it straight back.  Its OWN
        #: knob (ISSUE 11 satellite): a tree wants a SHORTER leaf TTL
        #: than the master's relay TTL (``slave_ttl``) — leaves churn,
        #: relays should not
        self.child_ttl = float(
            root.common.engine.get("relay_child_ttl", 30.0)
            if child_ttl is None else child_ttl)
        #: upward re-encoding of the summed delta, with the relay's OWN
        #: error-feedback residuals (re-quantization loses nothing over
        #: time; leaves keep their own residuals independently)
        self.wire_dtype = wire.canonical_wire_dtype(
            root.common.engine.get("wire_dtype", "float32")
            if wire_dtype is None else wire_dtype)
        self._enc = wire.DeltaEncoder(self.wire_dtype)

        #: ONE lock guards every field the serve thread mutates that
        #: stats()/web_status read (the thread-shared-state discipline,
        #: znicz-lint enforced — no pragmas)
        self._lock = threading.Lock()
        self._children: Dict[str, float] = {}       # id -> last seen
        self._cred: Optional[Tuple[Any, Any]] = None  # (version, digest)
        self._cred_reply: Dict = {}                 # cached ok register
        self._jobq: List[Tuple[dict, Any]] = []     # (entry, params)
        self._buffer: List[dict] = []               # contributor entries
        self._buffer_msgs = 0                       # direct child msgs
        self._sum: Dict[str, Dict[str, np.ndarray]] = {}
        #: shapes learned from the first ACCEPTED delta, for the
        #: relay's lifetime — the in-progress sum is empty at the start
        #: of every flush window, so without this a wrong-shaped child
        #: arriving first would seed the aggregate and get its healthy
        #: siblings refused instead of itself
        self._shapes: Dict[str, Dict[str, tuple]] = {}
        self._sum_t0: Optional[float] = None
        self._done = False
        #: wait-damping: when the upstream says "wait" (epoch tail), a
        #: relay must not re-ask upstream on EVERY child poll — that
        #: would multiply the master's decode count by the subtree size
        #: instead of dividing it.  Children polling inside this window
        #: get "wait" locally; consecutive upstream waits grow the
        #: window exponentially (capped), so a long drain costs a
        #: handful of upstream polls, not a stream of them.
        self._wait_until = 0.0
        self._wait_streak = 0
        #: runtime tree healing (ISSUE 11): the endpoint OUR upstream
        #: advertised as its own upstream at register time.  When the
        #: upstream reconnect budget is spent, the relay re-homes there
        #: (one hop up the tree) and re-registers instead of going
        #: silent — a dead mid-tier relay costs its subtree one backoff
        #: window, not the whole subtree's membership.  Lock-guarded:
        #: mutated from the serve loop, read by stats()/children.
        self._upstream_fallback: Optional[str] = None
        #: per-child subtree leaf counts (a slave counts 1; a lower
        #: relay reports its own sum on each job request) — summed
        #: upward so the master's quorum sees through the tree
        self._child_leaves: Dict[str, int] = {}
        self._delta_norms: List[float] = []         # accepted, per-child
        self._uregistered = False
        self._ufails = 0
        self._urefusals = 0             # consecutive bad_frame replies
        self._last_evict = 0.0
        #: optional FaultSchedule for the serve loop's built-in ingress
        #: fault hook (ISSUE 14 cross-plane soak); the live loop is on
        #: ``_transport`` while serving
        self.transport_chaos = None
        self._transport = None
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None

        sc = telemetry.scope("relay", bind=str(bind))
        self._m = {name: sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        # the upstream link rides the shared transport Endpoint (ISSUE
        # 14): fresh-socket reconnect + resend-same-bytes; the backoff
        # curve keeps the relay's historical constants (base 0.05s,
        # cap 2s, exponent cap 5).  No breaker on this plane: the
        # bounded budget + rehome-one-rung-up policy IS its fail-fast.
        from znicz_tpu.transport import Endpoint, RetryPolicy
        self._uep = Endpoint(
            self.upstream, recv_timeout_s=self.recv_timeout,
            retry=RetryPolicy.for_relay_upstream(
                self.max_reconnects,
                jitter_key=f"{self.relay_id}/backoff"),
            count_out=self._m["relay_bytes_out"].inc,
            count_in=self._m["relay_bytes_in"].inc)
        from znicz_tpu.telemetry.metrics import weak_fn

        sc.gauge("relay_children", "children registered at this relay",
                 fn=weak_fn(self, lambda r: len(r._children)))
        sc.gauge("relay_queue_depth", "jobs queued for children",
                 fn=weak_fn(self, lambda r: len(r._jobq)))
        self._tracer = telemetry.tracer()
        # fleet observability (ISSUE 20): relay spans/events piggyback
        # upstream on flush messages — the master ingests them under
        # this origin, so a mid-tree hop shows up in stitched traces
        telemetry.set_identity(self.relay_id)
        self._exporter = telemetry.exporter()
        self._obs_ev_seq = 0
        #: children's piggybacked obs payloads awaiting the next flush
        #: (bounded drop-oldest — observability never backs up a flush)
        self._obs_fwd: List[dict] = []

    # -- introspection ---------------------------------------------------------

    @property
    def children(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._children)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._jobq)

    @property
    def complete(self) -> bool:
        with self._lock:
            return self._done

    def stats(self) -> dict:
        """The web_status tree-topology panel's row (assembled under the
        lock; plain values only)."""
        now = time.time()
        with self._lock:
            children = [{"id": sid, "last_seen_s": round(now - seen, 1)}
                        for sid, seen in sorted(self._children.items())]
            queued = len(self._jobq)
            buffered = len(self._buffer)
            done = self._done
            upstream = self.upstream    # may move under re-homing
            leaves = sum(int(self._child_leaves.get(sid, 1))
                         for sid in self._children)
        return {
            "id": self.relay_id, "bind": self.bind,
            "upstream": upstream, "fanout": self.fanout,
            "wire_dtype": self.wire_dtype,
            "children": children, "queue_depth": queued,
            "buffered_contributions": buffered, "complete": done,
            "leaves": leaves,
            "bytes_in": self.bytes_in, "bytes_out": self.bytes_out,
            "refusals": self.refusals, "flushes": self.flushes,
            "contributions": self.contributions,
            "jobs_served": self.jobs_served,
            "bad_frames": self.bad_frames,
            "upstream_reconnects": self.upstream_reconnects,
            "rehomes": self.rehomes,
            "jobs_expired": self.jobs_expired,
        }

    # -- child-side edge validation (the quarantine mirror) --------------------

    def _validate_delta(self, deltas: Dict, n_delta: int) -> Optional[str]:
        """Refusal reason for a child delta that must never touch the
        partial sum: a leaf whose shape disagrees with the aggregate so
        far (summing would raise or broadcast garbage), any non-finite
        value, or a per-contributor norm beyond ``quarantine_norm_mult``
        x the running median of accepted per-contributor norms — the
        master's quarantine, applied at the edge so one poisoned child
        is refused HERE.  NEVER raises (a payload too broken to inspect
        is itself the reason)."""
        try:
            total = 0.0
            for name, layer in deltas.items():
                for k, arr in (layer or {}).items():
                    a = np.asarray(arr, np.float64)
                    # learned lifetime shapes first (the sum is empty
                    # at each window start), then the live aggregate
                    want = self._shapes.get(name, {}).get(k)
                    if want is not None and tuple(a.shape) != want:
                        return (f"shape {tuple(a.shape)} != {want} "
                                f"for {name}.{k}")
                    have = self._sum.get(name, {}).get(k)
                    if have is not None and have.shape != a.shape:
                        return (f"shape {tuple(a.shape)} != aggregate "
                                f"{tuple(have.shape)} for {name}.{k}")
                    if not np.all(np.isfinite(a)):
                        return "non-finite values"
                    total += float(np.dot(a.ravel(), a.ravel()))
        except Exception as exc:
            return f"undecodable delta payload: {exc!r}"
        # per-contributor normalization: a relay child's aggregate of n
        # deltas carries ~n contributors' worth of norm
        norm = float(np.sqrt(total)) / max(1, int(n_delta))
        with self._lock:
            if len(self._delta_norms) >= 5:
                med = float(np.median(self._delta_norms))
                if med > 0.0 and norm > self.quarantine_norm_mult * med:
                    return (f"norm {norm:.3g} > "
                            f"{self.quarantine_norm_mult:g} x median "
                            f"{med:.3g}")
            self._delta_norms.append(norm)
            del self._delta_norms[:-64]
        return None

    def _accumulate(self, deltas: Dict) -> None:
        with self._lock:
            for name, layer in deltas.items():
                dst = self._sum.setdefault(name, {})
                shp = self._shapes.setdefault(name, {})
                for k, arr in (layer or {}).items():
                    a = np.asarray(arr, np.float32)
                    shp.setdefault(k, tuple(a.shape))
                    if k in dst:
                        dst[k] = dst[k] + a
                    else:
                        dst[k] = a.astype(np.float32, copy=True)
            if self._sum_t0 is None:
                self._sum_t0 = time.time()

    # -- child command handlers ------------------------------------------------

    def _child_register(self, req: dict, sid: str) -> dict:
        v, digest = req.get("version"), req.get("workflow_digest")
        with self._lock:
            cred = self._cred
        if cred is None:
            # first child: ITS credentials become the relay's own
            # registration upstream — the master's check_handshake is
            # the single source of truth for the whole subtree
            rep = self._upstream_rpc(
                {"cmd": "register", "id": self.relay_id, "version": v,
                 "workflow_digest": digest, "relay": True,
                 "fanout": self.fanout, "bind": self.bind},
                is_register=True)
            if rep is None:
                return {"ok": False,
                        "error": "relay upstream unreachable"}
            if not rep.get("ok"):
                return {"ok": False, "error": rep.get("error")}
            with self._lock:
                self._cred = (v, digest)
                self._cred_reply = {
                    k: rep.get(k)
                    for k in ("version", "class_lengths", "resumed",
                              "epoch")}
                # the upstream's OWN fallback advertisement: a relay
                # upstream names its upstream, the master names none —
                # the rung this relay re-homes to if upstream dies
                self._upstream_fallback = rep.get("upstream")
            self._uregistered = True
        else:
            # validated subtree: later children are checked locally,
            # refused with the master's own wording on mismatch
            cv, cd = cred
            if v != cv:
                return {"ok": False, "error":
                        f"protocol version mismatch: master speaks "
                        f"{cv}, slave sent {v!r}"}
            if digest != cd:
                return {"ok": False, "error":
                        f"workflow digest mismatch: master runs {cd}, "
                        f"slave runs {digest!r} — same trainable graph "
                        f"(layer names/shapes/hyperparameters) required"}
        with self._lock:
            self._children[sid] = time.time()
            reply = dict(self._cred_reply)
            upstream = self.upstream    # may move under re-homing
        reply.update({"ok": True, "upstream": upstream})
        return reply

    def _live_leaves(self) -> int:
        """Subtree leaf count: the sum of what each live child last
        reported (a slave counts 1) — piggybacked on upstream job
        requests so the master's quorum sees through the tree."""
        with self._lock:
            return sum(int(self._child_leaves.get(sid, 1))
                       for sid in self._children)

    def _child_job(self, req: dict, sid: str) -> dict:
        k = max(1, min(int(req.get("count", 1) or 1), 64))
        with self._lock:
            # a lower relay reports its own subtree size; a slave has
            # no ``leaves`` key and counts 1
            try:
                self._child_leaves[sid] = max(
                    0, int(req.get("leaves", 1)))
            except (TypeError, ValueError):
                self._child_leaves[sid] = 1
            done, have = self._done, len(self._jobq)
            damped = not have and time.time() < self._wait_until
        if done:
            return {"done": True}
        if damped:
            return {"wait": True}           # upstream said wait just now
        if have == 0:
            rep = self._upstream_rpc(
                {"cmd": "job", "id": self.relay_id,
                 "count": k * self.fanout,
                 "leaves": self._live_leaves(),
                 "prefetch": bool(req.get("prefetch"))})
            if rep is None:
                return {"wait": True}       # upstream fault: child re-asks
            if rep.get("done"):
                self._flush()               # drain before the drain ends
                with self._lock:
                    self._done = True
                    self._jobq.clear()      # issued jobs are dead weight
                return {"done": True}
            # (no `unregistered` handling here: _upstream_rpc consumes
            # it internally — re-register + resend — for every
            # non-register call)
            jobs = rep.get("jobs")
            if jobs is None and "job" in rep:
                jobs = [{key: rep.get(key)
                         for key in ("job_id", "job", "trace_id",
                                     "train", "step")}]
            if not jobs:
                # upstream wait (epoch tail): damp the subtree's polls
                # so they do not all re-ask the master
                with self._lock:
                    self._wait_streak += 1
                    damp = min(0.05 * (2 ** min(self._wait_streak - 1,
                                                4)), 0.5)
                    self._wait_until = time.time() + damp
                return {"wait": True}
            params = rep.get("params")
            from znicz_tpu.transport import local_deadline
            now = time.monotonic()
            with self._lock:
                self._wait_streak = 0
                for j in jobs:
                    entry = dict(j)
                    # deadline propagation (ISSUE 14): the budget the
                    # master stamped becomes a LOCAL absolute deadline
                    # at receipt — it burns while the job queues here
                    entry["_deadline_t"] = local_deadline(
                        entry.get("deadline_ms"), now=now)
                    self._jobq.append((entry, params))
        from znicz_tpu.transport import remaining_ms
        now = time.monotonic()
        take: List[Tuple[dict, Any]] = []
        expired = 0
        with self._lock:
            while self._jobq and len(take) < k:
                entry, params = self._jobq.pop(0)
                deadline = entry.pop("_deadline_t", None)
                if deadline is not None and now > deadline:
                    # expired while queued: drop UNSERVED — the master
                    # has (or will have) re-queued it, so serving it
                    # would burn a child's compute on wasted work
                    # (PR 6's "expired work never computed", ISSUE 14)
                    expired += 1
                    continue
                if deadline is not None:
                    # re-stamp the REMAINING budget for the child
                    entry["deadline_ms"] = remaining_ms(deadline, now)
                take.append((entry, params))
        if expired:
            self._m["relay_jobs_expired"].inc(expired)
        if not take:
            return {"wait": True}
        self._m["relay_jobs_served"].inc(len(take))
        params = take[-1][1]                # freshest batch's params
        if int(req.get("count", 1) or 1) <= 1:
            entry = take[0][0]
            return dict(entry, params=take[0][1])
        return {"jobs": [e for e, _ in take], "params": params}

    def _child_update(self, req: dict, sid: str) -> dict:
        self._buffer_child_obs(req, sid)
        deltas = req.get("deltas")
        contributors = req.get("contributors")
        if contributors is not None:
            # a lower relay's aggregate: adopt its manifest wholesale
            entries = [dict(e) for e in contributors]
            n_delta = sum(1 for e in entries if e.get("delta"))
        else:
            entries = [{"id": sid, "job_id": req.get("job_id"),
                        "trace_id": req.get("trace_id"),
                        "step": req.get("step"),
                        "metrics": req.get("metrics")}]
            n_delta = 1 if deltas else 0
            if deltas:
                entries[0]["delta"] = True
        if deltas:
            tv0 = time.perf_counter() if self._tracer.enabled else None
            reason = self._validate_delta(deltas, max(1, n_delta))
            if tv0 is not None:
                # edge-validate span tagged with the contributor's
                # trace_id (ISSUE 20 satellite: the leaf's trace thread
                # survives the relay hop into the master-side timeline)
                self._tracer.add(
                    "relay", "edge_validate", tv0,
                    time.perf_counter() - tv0,
                    {"trace_id": entries[0].get("trace_id"),
                     "refused": bool(reason), "n_delta": n_delta})
            if reason:
                # refused at the edge: the partial sum stays clean, the
                # child hears the master's quarantine wording, and the
                # manifest still reports the refusal upstream so the
                # master counts it and requeues the job per child.
                # ONLY delta-bearing entries are refused — a delta-less
                # sibling (eval metrics) in the same aggregate had
                # nothing in the refused sum, so its finished work
                # passes through intact
                refused = [{"id": e.get("id", sid),
                            "job_id": e.get("job_id"),
                            "refused": reason}
                           for e in entries if e.get("delta")]
                passed = [e for e in entries if not e.get("delta")]
                with self._lock:
                    self._buffer.extend(refused + passed)
                    self._buffer_msgs += 1
                    if self._sum_t0 is None:
                        self._sum_t0 = time.time()
                self._m["relay_refusals"].inc(len(refused))
                if passed:
                    self._m["relay_contributions"].inc(len(passed))
                self._maybe_flush()
                return {"ok": False, "quarantined": True,
                        "error": f"delta quarantined: {reason}"}
            self._accumulate(deltas)
        with self._lock:
            self._buffer.extend(entries)
            self._buffer_msgs += 1
            if self._sum_t0 is None:
                self._sum_t0 = time.time()
            done = self._done
        self._m["relay_contributions"].inc(len(entries))
        self._maybe_flush()
        return {"ok": True, "complete": done}

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        sid = req.get("id", "?")
        with self._lock:                # one acquisition per message:
            known = sid in self._children   # membership + last-seen
            if known:
                self._children[sid] = time.time()
        if cmd == "register":
            return self._child_register(req, sid)
        if cmd in ("job", "update") and not known:
            return {"ok": False, "unregistered": True,
                    "error": f"slave {sid!r} is not registered"}
        if cmd == "job":
            return self._child_job(req, sid)
        if cmd == "update":
            return self._child_update(req, sid)
        return {"error": f"unknown cmd {cmd!r}"}

    # -- the flush -------------------------------------------------------------

    def _flush_due(self) -> bool:
        with self._lock:
            if not self._buffer:
                return False
            if self._buffer_msgs >= max(
                    1, min(len(self._children), self.fanout)):
                return True
            return (self._sum_t0 is not None
                    and time.time() - self._sum_t0 >= self.flush_s)

    def _maybe_flush(self) -> None:
        if self._flush_due():
            self._flush()

    def _evict_children(self) -> None:
        """Drop children silent past ``child_ttl`` (checked at most
        once per second); their in-flight work recovers via the
        master's reaper, and a returning child re-registers through the
        existing unregistered-reply path."""
        if self.child_ttl <= 0:
            return
        now = time.time()
        with self._lock:
            if now - self._last_evict < 1.0:
                return
            self._last_evict = now
            for sid in [s for s, seen in self._children.items()
                        if now - seen > self.child_ttl]:
                del self._children[sid]
                self._child_leaves.pop(sid, None)

    def _flush_message(self, entries: List[dict],
                       summed: Optional[Dict]) -> dict:
        """The ONE home for the aggregated-update message shape (the
        byte-identity test builds flushes through this too): contributor
        manifest + the summed delta re-encoded per ``wire_dtype`` with
        this relay's error-feedback residuals."""
        return {"cmd": "update", "id": self.relay_id,
                "contributors": entries,
                "deltas": self._enc.encode(summed) if summed else None}

    def _buffer_child_obs(self, req: dict, sid: str) -> None:
        """Hold a child's piggybacked spans/events (plus anything a
        LOWER relay already forwarded) for the next upstream flush.
        Each payload keeps the originating leaf's origin; the buffer is
        bounded drop-oldest so a flush-starved window sheds telemetry,
        never deltas."""
        fwd = []
        if req.get("spans") or req.get("events"):
            fwd.append({"origin": str(req.get("origin") or sid),
                        "spans": req.get("spans") or [],
                        "events": req.get("events") or []})
        fwd.extend(f for f in (req.get("fwd_obs") or [])
                   if isinstance(f, dict))
        if not fwd:
            return
        with self._lock:
            self._obs_fwd.extend(fwd)
            del self._obs_fwd[:-32]

    def _obs_payload(self) -> dict:
        """Fleet-observability piggyback for one upstream flush (ISSUE
        20): a bounded batch of this relay's exported spans plus fresh
        journal events, keyed by its fleet origin.  Additive keys — a
        pre-ISSUE-20 upstream ignores them; empty dict when there is
        nothing to ship."""
        from znicz_tpu import telemetry

        out: dict = {}
        spans = self._exporter.drain(telemetry.span_export_batch())
        if spans:
            out["spans"] = spans
        ev = telemetry.journal().since(
            self._obs_ev_seq, limit=telemetry.span_export_batch())
        if ev:
            self._obs_ev_seq = ev[-1]["seq"]
            out["events"] = ev
        if out:
            out["origin"] = telemetry.identity()
        return out

    def _flush(self, final: bool = False) -> None:
        """Ship the buffered contributions upstream as ONE aggregated
        update: summed f32 deltas re-encoded per wire_dtype (error
        feedback in :attr:`_enc`) + the contributor manifest.
        ``final`` (the serve loop's last act) allows one delivery
        attempt even after ``stop()`` — a clean shutdown should not
        silently drop a flush window a healthy upstream would take."""
        from znicz_tpu.parallel import wire

        with self._lock:
            if not self._buffer:
                return
            entries, self._buffer = self._buffer, []
            self._buffer_msgs = 0
            summed, self._sum = self._sum, {}
            self._sum_t0 = None
        t0 = time.perf_counter() if self._tracer.enabled else None
        msg = self._flush_message(entries, summed)
        # fleet observability (ISSUE 20): the flush carries this relay's
        # own spans/events upstream as additive keys — NOT added inside
        # _flush_message, whose output must stay deterministic for the
        # byte-identity test (and the exporter drain is one-shot)
        msg.update(self._obs_payload())
        with self._lock:
            fwd, self._obs_fwd = self._obs_fwd, []
        if fwd:
            msg["fwd_obs"] = fwd
        frames, _ = wire.encode_message(msg)
        rep = self._upstream_rpc(frames=frames, one_shot=final)
        if rep is not None:
            # only a DELIVERED flush counts — rep None means not a
            # byte was sent (stop mid-run, upstream budget spent) and
            # the jobs behind these contributions come back via the
            # master's TTL reaper
            self._m["relay_flushes"].inc()
        if t0 is not None:
            self._tracer.add("relay", "flush", t0,
                             time.perf_counter() - t0,
                             {"contributors": len(entries),
                              "trace_ids": [e.get("trace_id")
                                            for e in entries
                                            if e.get("trace_id")],
                              "delivered": rep is not None,
                              "bind": self.bind})
        if rep is not None and rep.get("complete"):
            with self._lock:
                self._done = True

    # -- the upstream link (rides the shared Endpoint, ISSUE 14) ---------------

    def _upstream_rpc(self, msg: Optional[dict] = None,
                      frames: Optional[List] = None,
                      is_register: bool = False,
                      one_shot: bool = False) -> Optional[dict]:
        """One REQ/REP exchange with the upstream, riding the shared
        client fault model (:class:`~znicz_tpu.transport.Endpoint`): a
        timeout or undecodable reply drops the (EFSM-broken) socket,
        backs off on the relay's historical curve and reconnects fresh
        — re-registering with the cached credentials before any further
        traffic — and re-sends the SAME frames.  Returns None once the
        reconnect budget is spent (the caller treats the upstream as
        gone).  ``one_shot`` permits a single attempt even after
        ``stop()`` — the serve loop's final flush."""
        from znicz_tpu.parallel import wire
        from znicz_tpu.transport import TransportFault

        if frames is None:
            frames, _ = wire.encode_message(msg)
        attempts = 0
        while not self._stop.is_set() or (one_shot and attempts == 0):
            attempts += 1
            try:
                if not self._uregistered and not is_register:
                    cred = self._cred
                    if cred is None:
                        return None     # nothing to re-register as yet
                    reg, _ = wire.encode_message(
                        {"cmd": "register", "id": self.relay_id,
                         "version": cred[0], "workflow_digest": cred[1],
                         "relay": True, "fanout": self.fanout,
                         "bind": self.bind})
                    rep = self._uep.rpc(reg)
                    if rep.get("bad_frame"):
                        if self._count_refusal():
                            return None
                        continue        # alive, never decoded: resend
                    if not rep.get("ok"):
                        import logging

                        logging.getLogger("znicz").warning(
                            "%s: upstream refused re-registration: %s",
                            self.relay_id, rep.get("error"))
                        self._stop.set()
                        return None
                    with self._lock:
                        # the (possibly NEW, post-re-homing) upstream's
                        # own fallback advertisement
                        self._upstream_fallback = rep.get("upstream")
                    self._uregistered = True
                rep = self._uep.rpc(frames)
                self._ufails = 0
                if rep.get("bad_frame"):
                    # the upstream is alive but never decoded our frame
                    # (chaos corrupted the request): resend the SAME
                    # bytes, bounded like the client's refusal cap — a
                    # bad_frame reply is NOT a refusal of the content
                    if self._count_refusal():
                        return None
                    continue
                self._urefusals = 0
                if rep.get("unregistered") and not is_register:
                    self._uregistered = False   # master restarted
                    continue                    # re-register + resend
                return rep
            except TransportFault as exc:
                self._ufails += 1
                self._m["relay_upstream_reconnects"].inc()
                self._uregistered = False
                if self._ufails > self.max_reconnects:
                    import logging

                    with self._lock:
                        fallback = self._upstream_fallback
                        if fallback and fallback != self.upstream:
                            # runtime tree healing (ISSUE 11): re-home
                            # one rung up the tree instead of going
                            # silent — this relay's whole subtree keeps
                            # its membership through a dead mid relay.
                            # One hop per spent budget; the re-register
                            # at the new upstream records ITS
                            # advertisement for the next failure.
                            self.upstream = fallback
                            self._upstream_fallback = None
                        else:
                            fallback = None
                    if fallback:
                        self._uep.endpoint = fallback
                        self._m["relay_rehomes"].inc()
                        self._ufails = 0
                        logging.getLogger("znicz").warning(
                            "%s: upstream gone after %d retries — "
                            "re-homing to its advertised upstream %s",
                            self.relay_id, self.max_reconnects,
                            fallback)
                        continue
                    logging.getLogger("znicz").warning(
                        "%s: upstream %s gone for good after %d retries "
                        "(%r) — relay going silent so children fall "
                        "back", self.relay_id, self.upstream,
                        self._ufails - 1, exc)
                    self._stop.set()
                    return None
                self._uep.backoff(self._ufails)
        return None

    def _count_refusal(self) -> bool:
        """Bounded bad_frame retry budget (the client's ``refused()``
        policy): True once spent — an upstream that refuses EVERY frame
        we send (deterministic corruption, version skew) must not spin
        us forever."""
        self._urefusals += 1
        if self._urefusals <= max(3, self.max_reconnects):
            time.sleep(0.05)
            return False
        import logging

        logging.getLogger("znicz").warning(
            "%s: upstream refused %d consecutive frames (bad_frame) — "
            "relay going silent", self.relay_id, self._urefusals)
        self._stop.set()
        return True

    # -- the serve loop --------------------------------------------------------

    def _reply_frames(self, frames: List[bytes]) -> List:
        """Decode + dispatch one child message; NEVER raises (the
        master's own refusal discipline: garbage is counted and refused
        in legacy framing, not fatal)."""
        import logging
        import pickle

        from znicz_tpu.parallel import wire

        self._m["relay_bytes_in"].inc(sum(len(f) for f in frames))
        try:
            req, info = wire.decode_message(frames)
            if not isinstance(req, dict):
                raise wire.WireError(
                    f"decodes to {type(req).__name__}, not a request "
                    f"dict")
        except Exception as exc:
            from znicz_tpu.transport import bad_frame_reply

            self._m["relay_bad_frames"].inc()
            rep_frames = [pickle.dumps(bad_frame_reply(exc))]
            self._m["relay_bytes_out"].inc(
                sum(len(f) for f in rep_frames))
            return rep_frames
        legacy = bool(info.get("legacy"))
        try:
            with self._tracer.span("relay", f"handle:{req.get('cmd')}",
                                   bind=self.bind, child=req.get("id")):
                rep = self._handle(req)
        except Exception as exc:
            self._m["relay_bad_frames"].inc()
            logging.getLogger("znicz").exception(
                "%s: refused malformed request %r", self.relay_id,
                req.get("cmd"))
            rep = {"ok": False, "bad_frame": True,
                   "error": f"malformed request: {exc!r}"}
        if legacy:
            out = [pickle.dumps(rep)]
        else:
            out, _ = wire.encode_message(rep)
        self._m["relay_bytes_out"].inc(
            sum(f.nbytes if isinstance(f, memoryview) else len(f)
                for f in out))
        return out

    def serve(self, linger: float = 3.0) -> None:
        """Blocks until the upstream reports done (then keeps draining
        ``linger`` seconds so late children get their ``done``) or
        ``stop()``.  Rides the unified
        :class:`~znicz_tpu.transport.TransportLoop` (ISSUE 14): REP
        lockstep dispatch of :meth:`_reply_frames` plus one idle tick
        for flushes, child eviction and the drain linger.  The loop
        keeps its OWN stop flag so a linger-exit leaves ``self._stop``
        unset and the final flush retains its full retry budget."""
        from znicz_tpu.transport import TransportLoop

        loop = TransportLoop("relay", instance=self.bind)
        state = {"deadline": None}

        def tick() -> None:
            if self._stop.is_set():
                loop.stop()
                return
            with self._lock:
                done = self._done and not self._buffer
            if done and state["deadline"] is None:
                state["deadline"] = time.time() + linger
            if state["deadline"] is not None \
                    and time.time() > state["deadline"]:
                loop.stop()
                return
            self._maybe_flush()
            self._evict_children()

        try:
            sock = loop.bind_rep(self.bind)
            loop.register(sock, self._reply_frames, reply=True)
            if self.transport_chaos is not None:
                loop.inject_faults(self.transport_chaos)
            self._transport = loop
            loop.add_tick(tick)
            self._ready.set()
            loop.run(poll_ms=20)
        finally:
            # one delivery attempt even when stop() ended the loop — a
            # clean shutdown should not drop a window a healthy
            # upstream would take (undeliverable: the TTL reaper pays)
            self._flush(final=True)
            loop.close()
            self._uep.close()

    def start(self, linger: float = 3.0) -> "Relay":
        self._thread = threading.Thread(
            target=self.serve, kwargs={"linger": linger}, daemon=True,
            name=f"relay-{self.bind}")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError(f"relay failed to bind {self.bind}")
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


# historical-style counter attributes (relay.refusals, relay.bytes_in,
# ...) generated from COUNTERS — one source of truth per counter
for _name, _help in Relay.COUNTERS.items():
    setattr(Relay, _name[len("relay_"):], _relay_counter(_name, _help))
del _name, _help
