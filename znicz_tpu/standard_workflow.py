"""StandardWorkflow: declarative model assembly (rebuild of
``znicz/standard_workflow.py``, SURVEY.md §2.2 / §3.1).

Builds the canonical training graph from a ``layers`` config list::

    layers = [
        {"type": "conv_relu", "->": {"n_kernels": 32, "kx": 5, "ky": 5,
                                     "padding": (2, 2, 2, 2)}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 100},
         "<-": {"learning_rate": 0.01}},
        {"type": "softmax", "->": {"output_sample_shape": 10}},
    ]

Per-layer dicts use the reference's arrow keys: ``"->"`` = forward-unit
kwargs, ``"<-"`` = backward(GD)-unit kwargs (per-layer lr/momentum/decay —
the semantics jax.grad would otherwise flatten away, SURVEY.md §1).

Wiring produced (identical to the reference's):
    start -> repeater -> loader -> fwd_0 .. fwd_n -> evaluator -> decision
    decision -> snapshotter -> gd_n .. gd_0 -> repeater
    decision.complete gates end_point; decision.gd_skip gates every gd;
    dropout/stochastic-pooling units get minibatch_class linked for their
    train/eval mode switch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Type

from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.decision import DecisionGD, DecisionMSE
from znicz_tpu.evaluator import EvaluatorMSE, EvaluatorSoftmax
from znicz_tpu.snapshotter import Snapshotter

# -- layer type registry ------------------------------------------------------


def _registry() -> Dict[str, Tuple[Type, Optional[Type]]]:
    from znicz_tpu import activation as act
    from znicz_tpu import all2all, conv, cutter, dropout, gd, gd_conv
    from znicz_tpu import gd_pooling, lrn, pooling

    reg: Dict[str, Tuple[Type, Optional[Type]]] = {
        "all2all": (all2all.All2All, gd.GradientDescent),
        "all2all_tanh": (all2all.All2AllTanh, gd.GDTanh),
        "all2all_relu": (all2all.All2AllRELU, gd.GDRELU),
        "all2all_strict_relu": (all2all.All2AllStrictRELU, gd.GDStrictRELU),
        "all2all_sigmoid": (all2all.All2AllSigmoid, gd.GDSigmoid),
        "softmax": (all2all.All2AllSoftmax, gd.GDSoftmax),
        "conv": (conv.Conv, gd_conv.GradientDescentConv),
        "conv_tanh": (conv.ConvTanh, gd_conv.GDTanhConv),
        "conv_relu": (conv.ConvRELU, gd_conv.GDRELUConv),
        "conv_strict_relu": (conv.ConvStrictRELU, gd_conv.GDStrictRELUConv),
        "max_pooling": (pooling.MaxPooling, gd_pooling.GDMaxPooling),
        "maxabs_pooling": (pooling.MaxAbsPooling, gd_pooling.GDMaxAbsPooling),
        "avg_pooling": (pooling.AvgPooling, gd_pooling.GDAvgPooling),
        "stochastic_pooling": (pooling.StochasticPooling,
                               gd_pooling.GDStochasticPooling),
        "stochastic_abs_pooling": (pooling.StochasticAbsPooling,
                                   gd_pooling.GDStochasticAbsPooling),
        "norm": (lrn.LRNormalizerForward, lrn.LRNormalizerBackward),
        "dropout": (dropout.DropoutForward, dropout.DropoutBackward),
        "cutter": (cutter.Cutter, cutter.GDCutter),
        "activation_tanh": (act.ForwardTanh, act.BackwardTanh),
        "activation_sigmoid": (act.ForwardSigmoid, act.BackwardSigmoid),
        "activation_relu": (act.ForwardRELU, act.BackwardRELU),
        "activation_str": (act.ForwardStrictRELU, act.BackwardStrictRELU),
        "activation_log": (act.ForwardLog, act.BackwardLog),
        "activation_sincos": (act.ForwardSinCos, act.BackwardSinCos),
        "activation_tanhlog": (act.ForwardTanhLog, act.BackwardTanhLog),
    }
    from znicz_tpu import attention, deconv, depooling, gd_deconv

    reg["deconv"] = (deconv.Deconv, gd_deconv.GDDeconv)
    reg["deconv_tanh"] = (deconv.DeconvTanh, gd_deconv.GDDeconvTanh)
    reg["deconv_sigmoid"] = (deconv.DeconvSigmoid, gd_deconv.GDDeconvSigmoid)
    reg["depooling"] = (depooling.Depooling, depooling.GDDepooling)
    reg["attention"] = (attention.MultiHeadAttention,
                        attention.GDMultiHeadAttention)
    try:
        from znicz_tpu import resizable_all2all

        reg["resizable_all2all"] = (resizable_all2all.ResizableAll2All,
                                    gd.GradientDescent)
    except ImportError:
        pass
    return reg


#: unit types whose train/eval behavior depends on the minibatch class
_MODE_SWITCHED = ("dropout", "stochastic_pooling", "stochastic_abs_pooling")


class StandardWorkflowBase(Workflow):
    """Holds the builder pieces; StandardWorkflow drives them in order."""

    def __init__(self, workflow=None, name=None, loader=None,
                 layers: List[dict] = (), loss_function: str = "softmax",
                 decision_config: Optional[dict] = None,
                 snapshotter_config: Optional[dict] = None,
                 lr_adjust_config: Optional[dict] = None,
                 image_saver_config: Optional[dict] = None,
                 plotters: bool = False, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        assert loader is not None, "StandardWorkflow needs a loader instance"
        self.layers_config = list(layers)
        self.loss_function = loss_function
        self.decision_config = dict(decision_config or {})
        self.snapshotter_config = dict(snapshotter_config or {})
        #: e.g. {"policy": "exp", "gamma": 0.96} (see lr_adjust.POLICIES);
        #: the reference's StandardWorkflow wired lr_adjust into the chain
        #: the same way (SURVEY §2.2)
        self.lr_adjust_config = dict(lr_adjust_config or {})
        self.lr_adjust = None
        #: SURVEY §2.2 StandardWorkflow row also auto-links plotters and
        #: image_saver; both optional here.  image_saver_config (dict,
        #: e.g. {"limit": 32}) dumps misclassified samples per epoch;
        #: plotters=True wires the error curve + first-layer Weights2D +
        #: confusion MatrixPlotter at epoch boundaries — the fused fast
        #: path runs these too (its epoch hook).  image_saver consumes
        #: per-minibatch host data the fast path never pulls, so it is
        #: unit-engine-only.
        self.image_saver_config = image_saver_config
        self.want_plotters = bool(plotters)
        self.image_saver = None
        self.plotters = []
        self.loader = loader
        self.add_unit(loader)
        self.forwards = []
        self.gds = []

    # -- builder steps --------------------------------------------------------

    def link_repeater(self):
        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

    def link_loader(self):
        self.loader.link_from(self.repeater)

    def parse_forwards_from_config(self):
        reg = _registry()
        prev, prev_attr = self.loader, "minibatch_data"
        for i, layer in enumerate(self.layers_config):
            kind = layer["type"]
            if kind not in reg:
                raise ValueError(f"unknown layer type {kind!r} "
                                 f"(known: {sorted(reg)})")
            fwd_cls, _ = reg[kind]
            fwd = fwd_cls(self, name=f"fwd_{kind}_{i}",
                          **layer.get("->", {}))
            fwd.layer_index = i
            fwd.layer_kind = kind
            fwd.link_from(prev if i == 0 else self.forwards[-1])
            fwd.link_attrs(prev, ("input", prev_attr))
            if kind in _MODE_SWITCHED:
                fwd.link_attrs(self.loader, "minibatch_class")
            self.forwards.append(fwd)
            prev, prev_attr = fwd, "output"

    def link_evaluator(self):
        last = self.forwards[-1]
        if self.loss_function == "softmax":
            self.evaluator = EvaluatorSoftmax(self, name="evaluator")
            self.evaluator.link_attrs(self.loader,
                                      ("labels", "minibatch_labels"))
        elif self.loss_function == "mse":
            self.evaluator = EvaluatorMSE(self, name="evaluator")
            self.evaluator.link_attrs(self.loader,
                                      ("target", "minibatch_targets"))
        else:
            raise ValueError(f"unknown loss {self.loss_function!r}")
        self.evaluator.link_from(last)
        self.evaluator.link_attrs(last, "output")
        self.evaluator.link_attrs(self.loader,
                                  ("batch_size", "minibatch_size"))

    def link_decision(self):
        cls = DecisionGD if self.loss_function == "softmax" else DecisionMSE
        self.decision = cls(self, name="decision", **self.decision_config)
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch", "class_ended",
            "epoch_number", "class_lengths", "minibatch_size")
        self.decision.link_attrs(self.evaluator, ("minibatch_loss", "loss"))
        if self.loss_function == "softmax":
            self.decision.link_attrs(
                self.evaluator, ("minibatch_n_err", "n_err"),
                "confusion_matrix", "max_err_output_sum")

    def link_snapshotter(self):
        self.snapshotter = Snapshotter(self, name="snapshotter",
                                       **self.snapshotter_config)
        self.snapshotter.link_from(self.decision)
        self.snapshotter.link_attrs(self.decision, "epoch_number")
        self.snapshotter.improved = self.decision.improved
        self.snapshotter.gate_skip = ~self.decision.epoch_ended

    def create_gd_units(self):
        reg = _registry()
        err_src, err_attr = self.evaluator, "err_output"
        tail = self.snapshotter
        for i in reversed(range(len(self.forwards))):
            fwd = self.forwards[i]
            layer = self.layers_config[i]
            _, gd_cls = reg[fwd.layer_kind]
            if gd_cls is None:
                raise ValueError(
                    f"layer {fwd.layer_kind!r} has no backward unit and "
                    "cannot sit inside a GD chain")
            gd = gd_cls(self, name=f"gd_{fwd.layer_kind}_{i}", forward=fwd,
                        need_err_input=(i > 0),
                        **layer.get("<-", {}))
            gd.link_from(tail)
            gd.link_attrs(err_src, ("err_output", err_attr))
            gd.gate_skip = self.decision.gd_skip
            self.gds.append(gd)
            err_src, err_attr, tail = gd, "err_input", gd

    def link_lr_adjust(self):
        """Splice a LearningRateAdjust unit after the gd chain (one policy
        instance per gd so per-unit iteration state can't alias), gated
        like the gds.  No-op without ``lr_adjust_config``."""
        if not self.lr_adjust_config or not self.gds:
            return
        from znicz_tpu.lr_adjust import LearningRateAdjust, make_policy

        cfg = dict(self.lr_adjust_config)
        policy_name = cfg.pop("policy")
        self.lr_adjust = LearningRateAdjust(self, name="lr_adjust")
        for gd in self.gds:
            self.lr_adjust.add_gd(gd, make_policy(policy_name, **cfg))
        self.lr_adjust.link_from(self.gds[-1])
        self.lr_adjust.gate_skip = self.decision.gd_skip

    def link_observers(self):
        """Optional side units (SURVEY §2.2: "plotters/image_saver")."""
        if self.image_saver_config is not None and self.loss_function == \
                "softmax":
            from znicz_tpu.image_saver import ImageSaver

            sv = ImageSaver(self, name="image_saver",
                            **self.image_saver_config)
            sv.link_from(self.evaluator)
            sv.link_attrs(self.loader, ("input", "minibatch_data"),
                          ("labels", "minibatch_labels"),
                          ("batch_size", "minibatch_size"),
                          "epoch_number", "last_minibatch")
            sv.link_attrs(self.forwards[-1], "output")
            self.image_saver = sv
        if self.want_plotters:
            from znicz_tpu.plotting_units import (AccumulatingPlotter,
                                                  MatrixPlotter, Weights2D)

            dec = self.decision

            def valid_metric():
                # validation metrics when a VALID split exists, else the
                # TRAIN epoch metrics; key depends on the decision kind
                # (DecisionGD: err_pct, DecisionMSE: mse/loss)
                m = dec.epoch_metrics[1] or dec.epoch_metrics[2] or {}
                for key in ("err_pct", "mse", "loss"):
                    if key in m:
                        return float(m[key])
                return 0.0

            err = AccumulatingPlotter(
                self, name="plot_err",
                ylabel=("valid err %" if self.loss_function == "softmax"
                        else "valid loss"),
                fetch=valid_metric)
            plots = [err]
            first_weighted = next(
                (f for f in self.forwards if f.has_weights), None)
            if first_weighted is not None:
                plots.append(Weights2D(self, name="plot_weights",
                                       source=first_weighted.weights))
            if self.loss_function == "softmax":
                import numpy as _np

                def valid_confusion():
                    conf = (dec.epoch_metrics[1] or {}).get("confusion")
                    return _np.asarray(conf if conf is not None
                                       else [[0]])

                plots.append(MatrixPlotter(self, name="plot_confusion",
                                           fetch=valid_confusion))
            prev = self.snapshotter
            for p in plots:
                p.link_from(prev)
                p.gate_skip = ~self.decision.epoch_ended   # epoch ends only
                prev = p
            self.plotters = plots

    def link_loop_and_end(self):
        loop_tail = (self.lr_adjust or (self.gds[-1] if self.gds
                                        else self.decision))
        self.repeater.link_from(loop_tail)
        self.end_point.link_from(self.decision)
        if self.plotters:
            # the final epoch's plots must render before the run stops —
            # EndPoint waits for the plot chain too (gate-skipped units
            # still propagate control on ordinary laps).  That makes the
            # stop lap reach the repeater before EndPoint pops, so block
            # the repeater once training completed — the loader must not
            # advance past the end of training
            self.end_point.link_from(self.plotters[-1])
            self.repeater.gate_block = self.decision.complete
        self.end_point.gate_block = ~self.decision.complete


class StandardWorkflow(StandardWorkflowBase):
    """One-call builder: constructs the full training graph in the reference
    order.  Subclass and override individual ``link_*`` steps to customize
    (that was the reference's extension pattern too)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.link_repeater()
        self.link_loader()
        self.parse_forwards_from_config()
        self.link_evaluator()
        self.link_decision()
        self.link_snapshotter()
        self.create_gd_units()
        self.link_lr_adjust()
        self.link_observers()
        self.link_loop_and_end()
