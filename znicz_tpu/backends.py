"""Device abstraction (rebuild of ``veles/backends.py``).

The reference discovered OpenCL/CUDA devices, owned contexts/queues and
compiled kernels.  On TPU all of that is PJRT+XLA's job, so ``Device`` shrinks
to: which jax backend ("tpu"/"cpu"), which jax device(s), and — the genuinely
new part — the **mesh** used for SPMD sharding (the rebuild's replacement for
the reference's master/slave distribution, SURVEY.md §2.4).
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Sequence, Tuple

import numpy as np

#: XLA latency-hiding-scheduler flags (ISSUE 7, lever c): reorder the TPU
#: schedule so async copies (the staged-segment H2D puts, collective
#: permutes) overlap compute instead of serializing at their use sites —
#: the compiler-side half of the ingest/compute overlap the DeviceStager
#: provides on the host side.  Published flag set (the standard pairing
#: quoted in the JAX/maxtext perf guides); TPU-only semantics, harmless
#: but useless text on CPU — the knob below gates them off by default.
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_host_transfer_overlap_limit=8",
    "--xla_latency_hiding_scheduler_rerun=2",
)


def configure_xla_flags(environ=None) -> Tuple[str, ...]:
    """Append the latency-hiding-scheduler flags to ``XLA_FLAGS`` when
    ``root.common.engine.xla_latency_hiding`` is on (default OFF — a
    labeled bench variant until the BASELINE.md r12 protocol records the
    with/without numbers).  MUST run before the first jax backend
    initialization — the launcher calls it right after config/overrides
    are applied; if a backend already exists the env change is inert, so
    this warns instead of silently lying.  Idempotent (flags already
    present are not duplicated).  Returns the flags newly appended."""
    from znicz_tpu.core.config import root

    if environ is None:
        environ = os.environ
    if not bool(root.common.engine.get("xla_latency_hiding", False)):
        return ()
    current = environ.get("XLA_FLAGS", "")
    # dedup by flag NAME, not full string: a flag the operator already
    # set (any value) is respected, never shadowed by an appended
    # duplicate (XLA parses last-wins)
    fresh = tuple(f for f in LATENCY_HIDING_XLA_FLAGS
                  if f.split("=", 1)[0] not in current)
    if not fresh:
        return ()
    jax = sys.modules.get("jax")
    # the inert-after-init refusal applies to the REAL process env only
    # (a scratch dict is a harness inspecting what WOULD be applied)
    if jax is not None and environ is os.environ:
        try:
            initialized = bool(
                jax._src.xla_bridge._backends)  # noqa: SLF001
        except Exception:               # pragma: no cover - jax internals
            initialized = False
        if initialized:
            print("warning: xla_latency_hiding set after the jax backend "
                  "initialized — XLA_FLAGS changes are inert now; set the "
                  "knob via config/CLI overrides (the launcher applies "
                  "them before building the workflow)", file=sys.stderr)
            return ()
    environ["XLA_FLAGS"] = (current + " " + " ".join(fresh)).strip()
    return fresh


class Device:
    """A compute placement: one jax device for unit-at-a-time execution plus
    an optional mesh for fused SPMD train steps."""

    def __init__(self, platform: str = "auto",
                 mesh_shape: Optional[Tuple[int, ...]] = None,
                 mesh_axes: Sequence[str] = ("data",)) -> None:
        import jax

        if platform == "auto":
            platform = jax.default_backend()
        self.platform = platform
        self.jax_devices = jax.devices(platform)
        # unit-at-a-time placement must be a device THIS process owns:
        # under jax.distributed, jax.devices()[0] is global device 0,
        # which other processes cannot address
        self.jax_device = jax.local_devices(backend=platform)[0]
        self._mesh = None
        self._mesh_shape = mesh_shape
        self._mesh_axes = tuple(mesh_axes)

    # -- constructors --------------------------------------------------------

    @classmethod
    def auto(cls) -> "Device":
        from znicz_tpu.core.config import root

        return cls(platform=root.common.engine.get("backend", "auto"))

    @classmethod
    def cpu(cls) -> "Device":
        return cls(platform="cpu")

    # -- mesh ----------------------------------------------------------------

    @property
    def mesh(self):
        """The jax Mesh for SPMD steps; defaults to all devices on one
        ``data`` axis (pure data parallelism, the reference's only mode)."""
        if self._mesh is None:
            from jax.sharding import Mesh

            shape = self._mesh_shape or (len(self.jax_devices),)
            n = int(np.prod(shape))
            devs = np.asarray(self.jax_devices[:n]).reshape(shape)
            self._mesh = Mesh(devs, self._mesh_axes)
        return self._mesh

    def set_mesh(self, shape: Tuple[int, ...], axes: Sequence[str]) -> None:
        self._mesh = None
        self._mesh_shape = tuple(shape)
        self._mesh_axes = tuple(axes)

    @property
    def n_devices(self) -> int:
        return len(self.jax_devices)

    @property
    def is_tpu(self) -> bool:
        return self.platform not in ("cpu",)

    def __repr__(self) -> str:
        return (f"Device({self.platform}, n={self.n_devices}, "
                f"mesh_axes={self._mesh_axes})")
