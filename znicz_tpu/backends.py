"""Device abstraction (rebuild of ``veles/backends.py``).

The reference discovered OpenCL/CUDA devices, owned contexts/queues and
compiled kernels.  On TPU all of that is PJRT+XLA's job, so ``Device`` shrinks
to: which jax backend ("tpu"/"cpu"), which jax device(s), and — the genuinely
new part — the **mesh** used for SPMD sharding (the rebuild's replacement for
the reference's master/slave distribution, SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class Device:
    """A compute placement: one jax device for unit-at-a-time execution plus
    an optional mesh for fused SPMD train steps."""

    def __init__(self, platform: str = "auto",
                 mesh_shape: Optional[Tuple[int, ...]] = None,
                 mesh_axes: Sequence[str] = ("data",)) -> None:
        import jax

        if platform == "auto":
            platform = jax.default_backend()
        self.platform = platform
        self.jax_devices = jax.devices(platform)
        # unit-at-a-time placement must be a device THIS process owns:
        # under jax.distributed, jax.devices()[0] is global device 0,
        # which other processes cannot address
        self.jax_device = jax.local_devices(backend=platform)[0]
        self._mesh = None
        self._mesh_shape = mesh_shape
        self._mesh_axes = tuple(mesh_axes)

    # -- constructors --------------------------------------------------------

    @classmethod
    def auto(cls) -> "Device":
        from znicz_tpu.core.config import root

        return cls(platform=root.common.engine.get("backend", "auto"))

    @classmethod
    def cpu(cls) -> "Device":
        return cls(platform="cpu")

    # -- mesh ----------------------------------------------------------------

    @property
    def mesh(self):
        """The jax Mesh for SPMD steps; defaults to all devices on one
        ``data`` axis (pure data parallelism, the reference's only mode)."""
        if self._mesh is None:
            from jax.sharding import Mesh

            shape = self._mesh_shape or (len(self.jax_devices),)
            n = int(np.prod(shape))
            devs = np.asarray(self.jax_devices[:n]).reshape(shape)
            self._mesh = Mesh(devs, self._mesh_axes)
        return self._mesh

    def set_mesh(self, shape: Tuple[int, ...], axes: Sequence[str]) -> None:
        self._mesh = None
        self._mesh_shape = tuple(shape)
        self._mesh_axes = tuple(axes)

    @property
    def n_devices(self) -> int:
        return len(self.jax_devices)

    @property
    def is_tpu(self) -> bool:
        return self.platform not in ("cpu",)

    def __repr__(self) -> str:
        return (f"Device({self.platform}, n={self.n_devices}, "
                f"mesh_axes={self._mesh_axes})")
