"""Launcher + CLI (rebuild of ``veles/launcher.py`` / ``veles/__main__.py``,
SURVEY.md §3.1).

Reference surface preserved::

    python -m znicz_tpu <workflow.py|module> [config.py]
        [root.path.key=value ...] [--snapshot FILE] [--backend cpu|tpu]
        [--workflow-graph FILE.dot] [--list]

A workflow script is any python file/module exposing ``run(snapshot=...,
device=...) -> workflow`` (all the bundled samples do); a config file is any
python file mutating ``znicz_tpu.core.config.root`` (applied before the
workflow module loads, then CLI dotted overrides on top — reference
precedence).

Distribution: the PRIMARY mode is SPMD inside the jitted step (SURVEY.md
§2.4) — no flags needed.  The reference's ``--master``/``--slave`` CLI
surface is preserved for the asynchronous parameter-server mode
(server.py/client.py): ``--master [bind]`` builds the workflow and serves
jobs instead of training locally; ``--slave endpoint`` builds the local
replica and works for that master.
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import os
import signal
import sys
from typing import Dict, List, Optional

from znicz_tpu.core.config import apply_overrides, root
from znicz_tpu.core.logger import setup_logging

SAMPLES = ("mnist", "cifar", "mnist_ae", "kohonen", "alexnet", "wine",
           "yale_faces", "kanji", "video_ae", "charlm")


def _load_module(spec: str, tag: str):
    if os.path.exists(spec):
        mod_spec = importlib.util.spec_from_file_location(tag, spec)
        mod = importlib.util.module_from_spec(mod_spec)
        sys.modules[tag] = mod
        mod_spec.loader.exec_module(mod)
        return mod
    return importlib.import_module(spec)


class Launcher:
    def __init__(self, argv: Optional[List[str]] = None):
        parser = argparse.ArgumentParser(
            prog="znicz_tpu",
            description="TPU-native VELES/Znicz workflow launcher")
        parser.add_argument("workflow", nargs="?",
                            help="workflow .py file, module path, or bundled "
                                 f"sample name ({', '.join(SAMPLES)})")
        parser.add_argument("config", nargs="?",
                            help="optional config .py file (mutates root)")
        parser.add_argument("overrides", nargs="*",
                            help="dotted overrides: root.a.b=value")
        parser.add_argument("--snapshot", default="",
                            help="resume from a snapshot file")
        parser.add_argument("--backend", default=None,
                            help="jax platform: tpu/cpu (default auto)")
        parser.add_argument("--seed", type=int, default=None)
        parser.add_argument("--workflow-graph", default="",
                            help="write the control graph as graphviz dot")
        parser.add_argument("--profile", default="",
                            help="capture a jax.profiler trace of the whole "
                                 "run into this directory")
        parser.add_argument("--profile-dir", default="", metavar="DIR",
                            help="programmatic jax profiler capture "
                                 "(start_trace/stop_trace) into DIR, with "
                                 "every fused train step wrapped in a "
                                 "jax.profiler.StepTraceAnnotation so the "
                                 "timeline shows named steps (telemetry, "
                                 "ISSUE 5; supersedes --profile when both "
                                 "are given)")
        parser.add_argument("--fused", action="store_true",
                            help="train with the fused SPMD fast path "
                                 "(one jitted scan step) instead of the "
                                 "unit-at-a-time engine")
        parser.add_argument("--master", nargs="?", const="tcp://*:5570",
                            default=None, metavar="BIND",
                            help="serve this workflow as the async "
                                 "parameter-server master instead of "
                                 "training locally (default bind "
                                 "tcp://*:5570)")
        parser.add_argument("--slave", default=None, metavar="ENDPOINT",
                            help="work for the master at ENDPOINT "
                                 "(e.g. tcp://host:5570)")
        parser.add_argument("--relay", default=None,
                            metavar="UPSTREAM[:BIND]",
                            help="run an aggregation-tree relay node "
                                 "(ISSUE 10): accept slaves/relays at "
                                 "BIND (default tcp://*:5571; a bare "
                                 "port means tcp://*:PORT), validate + "
                                 "sum-reduce their deltas and forward "
                                 "ONE combined update to UPSTREAM.  "
                                 "Needs no workflow argument")
        parser.add_argument("--tree-fanout", type=int, default=None,
                            metavar="N",
                            help="children per relay "
                                 "(root.common.engine.tree_fanout, "
                                 "default 2): the flush threshold and "
                                 "job-batch amplification factor")
        parser.add_argument("--min-slaves", type=int, default=None,
                            metavar="N",
                            help="elastic quorum gate for the master "
                                 "role (root.common.engine.min_slaves): "
                                 "below N live members (direct slaves + "
                                 "leaves reported by relays) dispatch "
                                 "pauses and readiness reports degraded")
        parser.add_argument("--staleness-bound", type=int, default=None,
                            metavar="S",
                            help="bounded-staleness apply "
                                 "(root.common.engine.staleness_bound): "
                                 "refuse-and-requeue deltas staler than "
                                 "S applies; 0 = unbounded")
        parser.add_argument("--plan-tree", type=int, default=None,
                            metavar="N_SLAVES",
                            help="print the relay-tree plan (tiers, "
                                 "endpoints, per-slave assignments) "
                                 "for N_SLAVES at --tree-fanout and "
                                 "exit")
        parser.add_argument("--serve", nargs="?", const="tcp://*:5580",
                            default=None, metavar="BIND",
                            help="serve this workflow's forward as a "
                                 "dynamic-batching inference service "
                                 "instead of training (load params with "
                                 "--snapshot; default bind tcp://*:5580; "
                                 "knobs: root.common.serving.max_batch/"
                                 "max_delay_ms/queue_bound)")
        parser.add_argument("--mesh-data", type=int, default=None,
                            metavar="N",
                            help="data-axis size of the pod-slice mesh: "
                                 "with --serve, root.common.serving."
                                 "mesh.data (each request batch splits "
                                 "into N row shards, ISSUE 13); with "
                                 "--slave, root.common.engine.mesh.data "
                                 "+ the train_shard gate (grads psum "
                                 "over ICI inside the slice, ISSUE 18). "
                                 "With --backend cpu, N x --mesh-model "
                                 "virtual devices are provisioned")
        parser.add_argument("--mesh-model", type=int, default=None,
                            metavar="N",
                            help="model-axis size of the pod-slice mesh "
                                 "(serving.mesh.model with --serve, "
                                 "engine.mesh.model with --slave) — "
                                 "wide FC layers column-shard over N "
                                 "devices")
        parser.add_argument("--generate", action="store_true",
                            help="with --serve: also speak the "
                                 "'generate' request kind — paged-KV "
                                 "autoregressive generation with "
                                 "prefix reuse, chunked prefill and "
                                 "fused sampling (root.common.serving."
                                 "generate.enabled; knobs: generate."
                                 "page_size/num_pages/prefill_chunk/"
                                 "prefix_cache/on_device_sampling)")
        parser.add_argument("--announce", default=None,
                            metavar="BALANCER",
                            help="with --serve: heartbeat this replica "
                                 "into the balancer at BALANCER "
                                 "(ISSUE 12) — readiness, queue depth "
                                 "and per-bucket p99 piggyback on "
                                 "every beat")
        parser.add_argument("--replica-id", default=None, metavar="ID",
                            help="with --serve: stable replica identity "
                                 "stamped on every reply (default: a "
                                 "fresh uuid per process)")
        parser.add_argument("--balance", nargs="?", const="tcp://*:5590",
                            default=None, metavar="BIND",
                            help="run the replica-fleet balancer "
                                 "(ISSUE 12) at BIND (default "
                                 "tcp://*:5590): health-checked "
                                 "least-loaded dispatch over the "
                                 "replicas that --announce into it, "
                                 "exactly-once failover, hedged "
                                 "retries, canary rollover with "
                                 "auto-rollback.  Needs no workflow "
                                 "argument; knobs: "
                                 "root.common.serving.balance.*")
        parser.add_argument("--replicas", default="", metavar="EP[,EP]",
                            help="with --balance: static replica "
                                 "endpoints to pre-connect (membership "
                                 "still needs their heartbeats)")
        parser.add_argument("--aot-cache", nargs="?", const="auto",
                            default=None, metavar="DIR",
                            help="with --serve: arm the AOT executable "
                                 "cache (root.common.serving.aot_cache) "
                                 "— warmed executables are serialized "
                                 "next to the snapshot (or into DIR) "
                                 "and a restarted replica LOADS its "
                                 "family instead of compiling it "
                                 "(zero-cold-start boots)")
        parser.add_argument("--autoscale-max", type=int, default=None,
                            metavar="N",
                            help="with --balance and --spawn-cmd: arm "
                                 "the autoscaler — spawn/retire replica "
                                 "processes against the load band, "
                                 "never past N replicas and never "
                                 "below --min-replicas")
        parser.add_argument("--spawn-cmd", default="", metavar="CMD",
                            help="with --autoscale-max: shell command "
                                 "that boots ONE replica announcing to "
                                 "this balancer; '{announce}' and "
                                 "'{replica_id}' are substituted (e.g. "
                                 "\"python -m znicz_tpu mnist --serve "
                                 "'tcp://127.0.0.1:*' --snapshot s.pkl.gz "
                                 "--aot-cache --announce {announce} "
                                 "--replica-id {replica_id}\")")
        parser.add_argument("--min-replicas", type=int, default=None,
                            metavar="N",
                            help="with --balance: readiness quorum "
                                 "(root.common.serving.balance."
                                 "min_replicas) — the aggregate "
                                 "/readyz 503s below N ready replicas")
        parser.add_argument("--master-resume", default="", metavar="FILE",
                            help="master crash-resume file: restore "
                                 "training state from FILE when it "
                                 "exists and keep it updated while "
                                 "serving (implies --master)")
        parser.add_argument("--fitness", action="store_true",
                            help="print a final JSON line with the run's "
                                 "fitness (genetics subprocess evaluation)")
        parser.add_argument("--list", action="store_true",
                            help="list bundled samples")
        # intermixed: dotted overrides may appear before or after flags
        # (the genetics evaluator appends chromosome overrides after the
        # caller's flags)
        self.args = parser.parse_intermixed_args(argv)

    def run(self) -> int:
        setup_logging()
        args = self.args
        if args.tree_fanout is not None:
            root.common.engine.tree_fanout = int(args.tree_fanout)
        if args.min_slaves is not None:
            root.common.engine.min_slaves = int(args.min_slaves)
        if args.staleness_bound is not None:
            root.common.engine.staleness_bound = int(args.staleness_bound)
        if args.min_replicas is not None:
            root.common.serving.balance.min_replicas = \
                int(args.min_replicas)
        if args.aot_cache is not None:
            root.common.serving.aot_cache.enabled = True
            if args.aot_cache != "auto":
                root.common.serving.aot_cache.dir = str(args.aot_cache)
        if args.generate:
            root.common.serving.generate.enabled = True
        if args.mesh_data is not None or args.mesh_model is not None:
            if args.slave is not None:
                # a pod-sliced TRAINING leaf (ISSUE 18): the mesh flags
                # target the engine tree and flip the train_shard gate
                root.common.engine.train_shard = True
                if args.mesh_data is not None:
                    root.common.engine.mesh.data = int(args.mesh_data)
                if args.mesh_model is not None:
                    root.common.engine.mesh.model = int(args.mesh_model)
            else:
                if args.mesh_data is not None:
                    root.common.serving.mesh.data = int(args.mesh_data)
                if args.mesh_model is not None:
                    root.common.serving.mesh.model = \
                        int(args.mesh_model)
        if args.plan_tree is not None:
            return self._plan_tree(args)
        if args.balance is not None:
            if args.master is not None or args.slave is not None \
                    or args.serve is not None or args.relay is not None \
                    or args.master_resume:
                print("error: --balance is mutually exclusive with the "
                      "master/slave/serve/relay roles", file=sys.stderr)
                return 2
            return self._balance(args)
        if args.relay is not None:
            if args.master is not None or args.slave is not None \
                    or args.serve is not None or args.master_resume:
                print("error: --relay is mutually exclusive with the "
                      "master/slave/serve roles", file=sys.stderr)
                return 2
            return self._relay(args)
        if args.list or not args.workflow:
            print("bundled samples:", ", ".join(SAMPLES))
            return 0
        # argparse can't distinguish "config.py" from the first dotted
        # override positionally — reclassify by the "=" marker
        if args.config and "=" in args.config:
            args.overrides.insert(0, args.config)
            args.config = None
        if args.backend:
            root.common.engine.backend = args.backend
            if args.backend == "cpu":
                # must happen BEFORE the first jax backend init; on hosts
                # with the axon plugin, env vars alone cannot unpin the
                # platform (znicz_tpu/virtdev.py).  A serving mesh on a
                # CPU host needs dp x mp VIRTUAL devices (ISSUE 13)
                from znicz_tpu.virtdev import provision_cpu_devices

                provision_cpu_devices(
                    max(1, (args.mesh_data or 1)
                        * (args.mesh_model or 1)), verify=False)
        if args.fused:
            root.common.engine.fused = True
        if args.master is not None and args.slave is not None:
            print("error: --master and --slave are mutually exclusive",
                  file=sys.stderr)
            return 2
        if args.serve is not None and (args.master is not None
                                       or args.slave is not None
                                       or args.master_resume):
            print("error: --serve is mutually exclusive with the "
                  "master/slave training roles", file=sys.stderr)
            return 2
        if args.master_resume:
            if args.slave is not None:
                print("error: --master-resume applies to the master role",
                      file=sys.stderr)
                return 2
            root.common.engine.master_resume = args.master_resume
            if args.master is None:
                args.master = "tcp://*:5570"      # implies --master
        if args.master is not None:
            root.common.engine.mode = "master"
            root.common.engine.master_bind = args.master
        elif args.slave is not None:
            root.common.engine.mode = "slave"
            root.common.engine.slave_endpoint = args.slave
        if args.seed is not None:
            from znicz_tpu.core import prng

            prng.seed_all(args.seed)
        if args.config:
            _load_module(args.config, "znicz_tpu._user_config")
        if args.overrides:
            apply_overrides(root, args.overrides)
        # a mesh may also arrive via the config file or dotted overrides
        # (not just the --mesh-* flags read above): now that both are
        # applied, re-raise the CPU virtual-device count if the
        # configured mesh needs more — still before the first jax
        # backend init, and provision only ever raises the count
        if args.backend == "cpu":
            need = 1
            if args.serve is not None:
                mc = root.common.serving.mesh
                need = int(mc.get("data", 1)) * int(mc.get("model", 1))
            elif root.common.engine.get("train_shard", False):
                # a pod-sliced training leaf (ISSUE 18)
                mc = root.common.engine.mesh
                need = int(mc.get("data", 1)) * int(mc.get("model", 1))
            if need > 1:
                from znicz_tpu.virtdev import provision_cpu_devices

                provision_cpu_devices(need, verify=False)
        # XLA scheduler flags must land in the env BEFORE the workflow
        # module's first jax backend init (ISSUE 7: the latency-hiding
        # scheduler is the compiler half of ingest/compute overlap;
        # root.common.engine.xla_latency_hiding, default off)
        from znicz_tpu.backends import configure_xla_flags

        configure_xla_flags()
        spec = args.workflow
        if spec in SAMPLES:
            spec = f"znicz_tpu.samples.{spec}"
        mod = _load_module(spec, "znicz_tpu._user_workflow")
        if args.serve is not None:
            return self._serve(mod, spec, args)
        if not hasattr(mod, "run"):
            print(f"error: {spec} does not expose run()", file=sys.stderr)
            return 2
        import inspect

        kwargs = {}
        sig = inspect.signature(mod.run)
        if "snapshot" in sig.parameters and args.snapshot:
            kwargs["snapshot"] = args.snapshot
        if args.profile_dir:
            # programmatic capture (TPU hand-off protocol, BASELINE.md):
            # unlike the --profile context manager this pairs with the
            # telemetry step annotations, so the profiler timeline shows
            # one named StepTraceAnnotation block per fused train step
            import jax

            from znicz_tpu import telemetry

            telemetry.set_profile_steps(True)
            jax.profiler.start_trace(args.profile_dir)
            try:
                wf = mod.run(**kwargs)
            finally:
                jax.profiler.stop_trace()
                print(f"profiler trace -> {args.profile_dir}/")
        elif args.profile:
            import jax

            with jax.profiler.trace(args.profile):
                wf = mod.run(**kwargs)
            print(f"profiler trace -> {args.profile}/")
        else:
            wf = mod.run(**kwargs)
        if args.workflow_graph and wf is not None:
            with open(args.workflow_graph, "w") as f:
                f.write(wf.generate_graph())
            print(f"workflow graph -> {args.workflow_graph}")
        if args.fitness:
            import json

            fit = None
            decision = getattr(wf, "decision", None)
            if decision is not None:
                fit = getattr(decision, "best_metric", None)
                if fit is None and getattr(decision, "epoch_qerror", None):
                    fit = decision.epoch_qerror[-1]
            import math

            if fit is None or not math.isfinite(float(fit)):
                # inf best_metric means no epoch ever improved — emitting
                # json 'Infinity' would be non-RFC JSON, so report no fitness.
                print("error: workflow exposes no finite fitness "
                      "(decision.best_metric / epoch_qerror)",
                      file=sys.stderr)
                return 3
            print(json.dumps({"genetics_fitness": float(fit)}), flush=True)
        return 0

    def _plan_tree(self, args) -> int:
        """``--plan-tree N``: print the relay tiers a fleet of N slaves
        needs at the configured fanout, as one JSON document — concrete
        ``--relay`` specs (top tier first, so starting them in order
        brings the tree up parents-before-children) plus the endpoint
        each slave should dial."""
        import json

        from znicz_tpu.parallel.relay import plan_tree

        master = (args.master
                  or str(root.common.engine.get("master_bind",
                                                "tcp://*:5570")))
        master = master.replace("*", "127.0.0.1")
        try:
            plan = plan_tree(
                int(args.plan_tree),
                int(root.common.engine.get("tree_fanout", 2)), master)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        plan["master"] = master
        plan["relay_args"] = [f"{r['upstream']}:{r['bind']}"
                              for r in plan["relays"]]
        print(json.dumps(plan, indent=2))
        return 0

    def _balance(self, args) -> int:
        """``--balance [BIND] --replicas ep,...``: run the replica
        balancer until interrupted (or ``root.common.serving
        .max_requests`` answers, for tests).  No workflow is built —
        the balancer moves frames, never arrays."""
        from znicz_tpu.serving import ReplicaBalancer

        # --balance needs no workflow, so dotted overrides land in the
        # workflow/config positional slots — reclassify and apply them
        # here (the main flow applies overrides after role dispatch)
        overrides = [o for o in ([args.workflow, args.config]
                                 + list(args.overrides))
                     if o and "=" in o]
        stray = [o for o in (args.workflow, args.config)
                 if o and "=" not in o]
        if stray:
            print(f"error: --balance takes no workflow argument "
                  f"(got {stray})", file=sys.stderr)
            return 2
        if overrides:
            apply_overrides(root, overrides)
        replicas = tuple(ep.strip() for ep in args.replicas.split(",")
                         if ep.strip())
        max_requests = root.common.serving.get("max_requests", None)
        balancer = ReplicaBalancer(
            bind=args.balance, replicas=replicas,
            max_requests=None if max_requests is None
            else int(max_requests))
        status = None
        web_port = root.common.serving.get("web_port", None)
        if web_port is not None:
            from znicz_tpu.web_status import WebStatus

            status = WebStatus(port=int(web_port)).start()
            status.register_balancer(balancer)
            print(f"fleet dashboard -> http://127.0.0.1:{status.port}/")
        balancer.start()
        static = (", ".join(replicas) if replicas
                  else "none — awaiting --announce heartbeats")
        print(f"balancing at {balancer.endpoint} (static replicas: "
              f"{static}; quorum {balancer.min_replicas})", flush=True)
        # autoscaler (ISSUE 17): spawn/retire replica PROCESSES via
        # --spawn-cmd against the load band; retire only reaches
        # processes this balancer spawned (the initial fleet is the
        # operator's)
        procs: Dict = {}
        if args.autoscale_max is not None and args.spawn_cmd:
            import shlex
            import subprocess
            import threading

            seq = {"n": 0}
            plock = threading.Lock()

            def _spawn() -> None:
                with plock:
                    seq["n"] += 1
                    rid = f"scale-{seq['n']}"
                cmd = args.spawn_cmd.format(announce=balancer.endpoint,
                                            replica_id=rid)
                p = subprocess.Popen(shlex.split(cmd))
                with plock:
                    procs[rid] = p
                print(f"autoscale: spawned {rid} (pid {p.pid})",
                      flush=True)

            def _retire(replica_id: str) -> None:
                with plock:
                    p = procs.pop(replica_id, None)
                if p is None:
                    print(f"autoscale: {replica_id} was not spawned "
                          f"here — draining only, not killing",
                          flush=True)
                    return
                p.terminate()
                print(f"autoscale: retired {replica_id}", flush=True)

            balancer.enable_autoscale(
                _spawn, _retire,
                autoscale_max=int(args.autoscale_max))
            print(f"autoscaling up to {int(args.autoscale_max)} "
                  f"replicas via: {args.spawn_cmd}", flush=True)
        try:
            while balancer.alive():
                if balancer.max_requests is not None and \
                        balancer.replied + balancer.refused \
                        >= balancer.max_requests:
                    break
                import time

                time.sleep(0.2)
        except KeyboardInterrupt:
            pass
        finally:
            balancer.stop()
            for p in procs.values():    # spawned replicas die with us
                p.terminate()
            if status is not None:
                status.stop()
        return 0

    def _relay(self, args) -> int:
        """``--relay UPSTREAM[:BIND]``: run one relay node until its
        upstream reports training done (or Ctrl-C).  No workflow is
        built — the relay validates children by passing the first
        handshake upstream."""
        from znicz_tpu.parallel.relay import Relay, parse_relay_spec

        try:
            upstream, bind = parse_relay_spec(args.relay)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        relay = Relay(upstream, bind)
        print(f"relay {relay.relay_id}: children at {bind} -> "
              f"upstream {upstream} (fanout {relay.fanout}, "
              f"wire {relay.wire_dtype})", flush=True)
        try:
            relay.serve()
        except KeyboardInterrupt:
            pass
        return 0

    def _serve(self, mod, spec: str, args) -> int:
        """``--serve``: build the module's workflow WITHOUT training it
        (the samples' ``run()`` trains), load ``--snapshot`` through the
        snapshotter's inference-load path, and serve the frozen forward
        as a dynamic-batching service until interrupted (or until
        ``root.common.serving.max_requests`` requests, for tests)."""
        from znicz_tpu.core.workflow import Workflow

        classes = [v for v in vars(mod).values()
                   if isinstance(v, type) and issubclass(v, Workflow)
                   and v is not Workflow
                   and v.__module__ == mod.__name__]
        if len(classes) != 1:
            print(f"error: --serve needs exactly one Workflow subclass "
                  f"in {spec}; found "
                  f"{[c.__name__ for c in classes] or 'none'}",
                  file=sys.stderr)
            return 2
        wf = classes[0]()
        wf.initialize(device=None)

        from znicz_tpu.serving import InferenceServer

        max_requests = root.common.serving.get("max_requests", None)
        server = InferenceServer(
            wf, bind=args.serve, snapshot=args.snapshot,
            max_requests=None if max_requests is None
            else int(max_requests),
            announce=args.announce, replica_id=args.replica_id)
        status = None
        web_port = root.common.serving.get("web_port", None)
        if web_port is not None:
            from znicz_tpu.web_status import WebStatus

            status = WebStatus(port=int(web_port)).start()
            status.register(wf)
            status.register_inference(server)
            print(f"status dashboard -> http://127.0.0.1:{status.port}/")
        server.start()
        print(f"serving {wf.name} at {server.endpoint} "
              f"(snapshot: {args.snapshot or 'fresh init'})", flush=True)
        # zero-downtime rollover on SIGHUP (ISSUE 6): re-load --snapshot
        # (the conventional "new weights land at the same path" flow)
        # and flip generations without dropping a request.  Signals can
        # only be wired from the main thread (tests drive main() from a
        # worker thread — they use the wire `swap` command instead).
        import threading

        if args.snapshot and hasattr(signal, "SIGHUP") \
                and threading.current_thread() is threading.main_thread():
            def _rollover(signum, frame):
                try:
                    server.swap_async(args.snapshot)
                    print(f"SIGHUP: snapshot rollover from "
                          f"{args.snapshot} started", flush=True)
                except RuntimeError as exc:    # overlapping swap
                    print(f"SIGHUP ignored: {exc}", flush=True)

            signal.signal(signal.SIGHUP, _rollover)
            print("SIGHUP triggers a zero-downtime snapshot rollover",
                  flush=True)
        try:
            server.join()
        except KeyboardInterrupt:
            pass
        finally:
            server.stop()
            if status is not None:
                status.stop()
        return 0


def main(argv: Optional[List[str]] = None) -> int:
    return Launcher(argv).run()
