"""Slave (rebuild of ``veles/client.py``): pulls jobs from the master,
computes one minibatch on the LOCAL workflow replica (the slave owns its
dataset copy like the reference's slaves did — the master only ships
minibatch indices + params), and pushes back weight deltas + metrics.
See server.py for the protocol; uses the Distributable payloads.

Fault tolerance (README "Fault tolerance"): a transport fault no longer
kills the slave.  ``run()`` is a reconnect state machine — a timed-out
REQ socket is stuck in a broken EFSM state and can NEVER be reused, so
every retry closes it and connects a FRESH one, waits a capped
exponential backoff with deterministic per-slave jitter, and re-registers
before any further job traffic.  That lets a slave ride out frame loss,
garbage replies, AND a full master restart (``--master-resume``).

Wire protocol v3 (parallel/wire.py, ISSUE 3): every message is multipart
— metadata frame + zero-copy tensor frames; weight deltas are quantized
to ``root.common.engine.wire_dtype`` (bf16/int8 with per-tensor absmax
scales) through a :class:`wire.DeltaEncoder` whose error-feedback
residuals keep convergence at f32 parity; a pending update is stored as
its ALREADY-ENCODED frames, so a resend after a reconnect re-sends bytes
instead of re-serializing the whole delta set.  A second socket on a
:class:`_JobPrefetcher` thread fetches job N+1 while the trainer
computes job N (``root.common.engine.job_prefetch``), hiding the fetch
round trip behind compute."""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from znicz_tpu.loader.base import TRAIN
# the shared ISSUE-5 compat layer; each Counter carries its own lock, so
# the prefetcher thread and the main loop increment concurrently without
# losing counts (regression-tested in tests/test_telemetry.py)
from znicz_tpu.telemetry.metrics import registered_property as \
    _client_counter
# the ONE client fault model (ISSUE 14): fresh-socket reconnect,
# capped-exp backoff with jitter, resend-same-bytes, breaker fail-fast
# and deadline budgets all live in znicz_tpu/transport/ now
from znicz_tpu.transport import (BadReply as _BadReply,  # noqa: F401
                                 CircuitBreaker, CircuitOpenError,
                                 Endpoint, PeerTimeout, RetryPolicy,
                                 local_deadline)


def scheduled_hypers_rows(base_hypers: Dict, mbs: List[dict]) -> Dict:
    """Per-step hypers rows for a fused job under a master-evaluated LR
    schedule (ISSUE 10 satellite): start from the slave's own constant
    hypers (identical to the master's bases — the workflow digest
    guarantees it) and overwrite (lr, lr_bias) — rows 0 and 1 of the
    8-wide hypers tuple — with the scheduled values the master stamped
    on each TRAIN minibatch at dispatch."""
    rows = []
    for mb in mbs:
        row = {name: np.array(t, np.float32)
               for name, t in base_hypers.items()}
        for name, pair in (mb.get("hypers") or {}).items():
            if name in row:
                row[name][0] = np.float32(pair[0])
                row[name][1] = np.float32(pair[1])
        rows.append(row)
    return {name: np.stack([r[name] for r in rows]) for name in rows[0]}


class _JobPrefetcher:
    """Pipelined job fetch (ISSUE 3): while the trainer computes job N,
    this thread requests job N+1 on its OWN REQ socket (ZMQ sockets are
    not thread-safe), so the fetch round trip — params broadcast
    included — overlaps compute instead of serializing with it.

    At most one fetch is ever outstanding; ``request()`` arms it,
    ``take()`` collects the decoded reply (or None on a miss).  A
    transport fault on THIS socket never touches the main loop's
    reconnect state machine: the prefetcher's OWN
    :class:`~znicz_tpu.transport.Endpoint` resets its (EFSM-broken)
    socket, ``prefetch_reconnects``/``prefetch_bad_replies`` are
    counted on the client, and the main socket simply fetches the job
    itself.  The prefetcher SHARES the client's circuit breaker (ISSUE
    14): once a dead master opens it, prefetch attempts fail fast
    locally instead of burning a full recv timeout per compute round.

    Semantics note: job N+1 is issued while update N is still local, so
    its params snapshot misses this slave's own last delta — delay-1
    staleness, the same kind the async protocol already exhibits
    whenever two slaves interleave (and what the seeded parity band in
    tests/test_wire.py covers).  A strictly sequential single-slave
    trajectory needs ``root.common.engine.job_prefetch = False``."""

    def __init__(self, client: "Client", make_endpoint,
                 recv_timeout: float):
        self._client = client
        self._ep: Endpoint = make_endpoint()    # own socket, SHARED breaker
        self._recv_timeout = float(recv_timeout)
        self._want = threading.Event()
        self._ready = threading.Event()
        self._slot: Optional[dict] = None
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"job-prefetch-{client.slave_id}")
        self._thread.start()

    def request(self) -> None:
        """Arm one fetch; no-op while one is pending/unconsumed."""
        if self._want.is_set() or self._ready.is_set():
            return
        self._slot = None
        self._want.set()

    def pending(self) -> bool:
        return self._want.is_set() or self._ready.is_set()

    #: how long take() is willing to wait for an in-flight fetch to land
    #: — on loopback/LAN the reply beat the compute anyway, and when it
    #: did NOT (dropped frame: the fetch thread sits out its full recv
    #: timeout) the main loop must fall back to its own healthy socket
    #: after a BOUNDED stall, not idle ~recv_timeout per fault
    TAKE_GRACE_S = 0.25

    def take(self) -> Optional[dict]:
        """The fetched job reply, or None (nothing armed, fetch failed,
        or still in flight past the grace).  A fetch that resolves
        AFTER a miss is not wasted: it stays in the slot — a real job
        assignment the next take() consumes (one compute-round of extra
        age, well inside the master's adaptive reap window)."""
        if not self.pending():
            return None
        if not self._ready.wait(min(self.TAKE_GRACE_S,
                                    self._recv_timeout)):
            return None                 # in flight: main socket takes over
        rep, self._slot = self._slot, None
        self._ready.clear()
        return rep

    def stop(self) -> None:
        self._stop = True
        self._want.set()
        self._thread.join(self._recv_timeout + 5.0)

    def _loop(self) -> None:
        from znicz_tpu.parallel import wire

        try:
            # _stop is re-checked at the TOP of every lap: stop() can
            # land while a fetch is in flight, and that fetch's finally
            # clears _want — checking _stop only after wait() would then
            # block here forever (the stop signal rides _stop, _want is
            # just the wake-up)
            while not self._stop:
                self._want.wait()
                if self._stop:
                    break
                rep = None
                try:
                    frames, _ = wire.encode_message(
                        {"cmd": "job", "prefetch": True,
                         "id": self._client.slave_id})
                    rep = self._ep.rpc(frames)
                    # receipt stamp for the deadline check (ISSUE 14):
                    # a prefetched job can sit in the slot for a whole
                    # compute round — its budget burns from HERE, not
                    # from when take() collects it
                    rep["_received_at"] = time.monotonic()
                except CircuitOpenError:
                    # master known-dead (shared breaker): fail fast
                    # with no socket, no recv-timeout burn; the main
                    # loop's breaker accounting covers it
                    pass
                except PeerTimeout:
                    # starved receive: the Endpoint already dropped the
                    # EFSM-broken socket; reconnect fresh on next fetch
                    self._client._m["prefetch_reconnects"].inc()
                except _BadReply:
                    # undecodable reply: count it (the chaos accounting
                    # holds bad-reply counters to the corrupt-frame
                    # count, so ONLY real replies may tick this) and
                    # mirror the main loop's fresh-socket policy
                    self._client._m["prefetch_bad_replies"].inc()
                    self._client._m["prefetch_reconnects"].inc()
                except Exception:
                    # connect/send fault or a genuine bug: never a
                    # "bad reply" — log it (a silently-spinning
                    # prefetcher would be undiagnosable) and refresh
                    import logging

                    logging.getLogger("znicz").warning(
                        "%s: prefetch fetch failed", self._client.slave_id,
                        exc_info=True)
                    self._client._m["prefetch_reconnects"].inc()
                    self._ep.reset()
                finally:
                    self._slot = rep
                    self._want.clear()
                    self._ready.set()
        finally:
            self._ep.close()            # closed by the owning thread


class Client:
    #: client counters registered under component="slave" (ISSUE 5):
    #: name -> HELP text
    COUNTERS = {
        "jobs_done": "jobs completed",
        "reconnects": "fresh-socket retries (main loop)",
        "bad_replies": "undecodable replies",  # shared family
        "prefetch_hits": "jobs consumed from the prefetcher",
        "prefetch_reconnects": "fresh-socket retries (prefetcher)",
        "prefetch_bad_replies": "undecodable replies (prefetcher)",
        # the unified fault model (ISSUE 14)
        "jobs_expired": "jobs dropped uncomputed: deadline budget spent",
        "breaker_opens": "circuit breaker transitions to open",
        "breaker_short_circuits": "attempts refused locally: breaker "
                                  "open (no socket, no recv timeout)",
    }

    # (historical attribute properties generated from COUNTERS after
    # the FusedClient definition at the bottom of this module)

    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 slave_id: Optional[str] = None):
        from znicz_tpu import telemetry

        self.workflow = workflow
        self.endpoint = endpoint
        self.slave_id = slave_id or uuid.uuid4().hex[:8]
        _sc = telemetry.scope("slave")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self._tracer = telemetry.tracer()
        # fleet observability (ISSUE 20): this slave's identity + span
        # exporter — completed spans and journal events piggyback on
        # update messages to the master (or relay, which forwards)
        telemetry.set_identity(f"slave-{self.slave_id}")
        self._exporter = telemetry.exporter()
        self._obs_ev_seq = 0            # journal piggyback cursor
        self.wire_dtype = "float32"     # resolved from config in run()
        self._delta_encoder = None
        #: the endpoint our relay advertised as ITS upstream (ISSUE 10):
        #: when the reconnect budget to a dead relay is spent, the slave
        #: falls back here and re-registers through the existing path —
        #: relay death costs a backoff window, not the slave.  The
        #: master advertises none, so the star behavior is unchanged.
        self._fallback_endpoint: Optional[str] = None
        #: simulated spot preemption (ISSUE 11): chaos drivers set this
        #: to make run() exit at its next loop top WITHOUT sending the
        #: pending update or finishing the in-flight job — exactly what
        #: a killed instance loses
        self._preempt = threading.Event()
        #: the shared circuit breaker (ISSUE 14), built per run() from
        #: ``slave_breaker_failures`` and shared with the prefetcher —
        #: tests read its state after run() returns
        self._breaker: Optional[CircuitBreaker] = None

    @property
    def breaker(self) -> Optional[CircuitBreaker]:
        """The run's shared transport breaker (None before run())."""
        return self._breaker

    @property
    def mesh_shape(self):
        """``{"data": dp, "model": mp}`` when this slave is a pod slice
        (FusedClient on a training mesh), else None.  Piggybacked on the
        register handshake so the master's web_status can show each
        leaf's slice shape."""
        return None

    def preempt(self) -> None:
        """Kill switch for the preemption chaos harness: the slave
        vanishes mid-whatever at its next loop iteration; the master's
        reaper recovers its in-flight job."""
        self._preempt.set()

    def _rpc(self, ep: Endpoint, msg: dict) -> dict:
        """One exchange through the shared transport Endpoint (ISSUE
        14); already-encoded resends go straight to ``ep.rpc``."""
        from znicz_tpu.parallel import wire

        msg["id"] = self.slave_id
        frames, _ = wire.encode_message(msg)
        return ep.rpc(frames)

    def _apply_params(self, params: Dict) -> None:
        for f in self.workflow.forwards:
            if f.has_weights and f.name in params:
                f.apply_data_from_master(params[f.name])

    def _deltas_since(self, before: Dict) -> Dict:
        out = {}
        for f in self.workflow.forwards:
            if not f.has_weights:
                continue
            layer = {}
            for k, arr in f.params().items():
                layer[k] = np.array(arr.map_read()) - before[f.name][k]
            out[f.name] = layer
        return out

    def _obs_payload(self) -> Dict:
        """Fleet-observability piggyback for one update message (ISSUE
        20): a bounded batch of this slave's exported spans plus fresh
        journal events, keyed by its fleet origin.  Additive keys — a
        pre-ISSUE-20 master ignores them; empty dict when there is
        nothing to ship (the common case costs two deque peeks)."""
        from znicz_tpu import telemetry

        out: Dict = {}
        spans = self._exporter.drain(telemetry.span_export_batch())
        if spans:
            out["spans"] = spans
        ev = telemetry.journal().since(
            self._obs_ev_seq, limit=telemetry.span_export_batch())
        if ev:
            self._obs_ev_seq = ev[-1]["seq"]
            out["events"] = ev
        if out:
            out["origin"] = telemetry.identity()
        return out

    def _run_minibatch(self, job: dict, train: bool):
        """One job's worth of local compute.  A SEGMENT job (master
        ``segment_steps`` > 1: {"minibatches": [...]}) loops its
        minibatches and returns a metrics list; a flat job returns one
        metrics dict (see FusedClient for the scan-dispatch version)."""
        if "minibatches" in job:
            return [self._run_one(mb, train) for mb in job["minibatches"]]
        return self._run_one(job, train)

    def _run_one(self, job: dict, train: bool) -> Dict:
        wf = self.workflow
        loader = wf.loader
        # inject the master's assignment into the local loader buffers
        idx = loader.minibatch_indices.map_invalidate()
        idx[...] = np.asarray(job["indices"], idx.dtype)
        loader.minibatch_size = job["size"]
        loader.minibatch_class = job["class"]
        loader.fill_minibatch()
        for f in wf.forwards:
            f.run()
        wf.evaluator.run()
        metrics = {"loss": float(wf.evaluator.loss)}
        if hasattr(wf.evaluator, "n_err"):
            metrics["n_err"] = int(wf.evaluator.n_err)
            metrics["confusion"] = np.array(
                wf.evaluator.confusion_matrix.map_read())
        if train:
            # LR schedules under master/slave (ISSUE 10 satellite): the
            # master evaluated its lr_adjust policies at dispatch and
            # stamped the scheduled per-layer rates on the minibatch —
            # apply them before the gds so the schedule advances
            # exactly as in local training
            sched = job.get("hypers") or {}
            for gd in wf.gds:
                pair = sched.get(gd.forward.name)
                if pair:
                    gd.learning_rate = float(pair[0])
                    gd.learning_rate_bias = float(pair[1])
            wf.decision.gd_skip.set(False)
            for gd in wf.gds:
                gd.run()
        return metrics

    def engine_name(self) -> str:
        return "unit"

    def run(self, poll_sleep: float = 0.05, recv_timeout: float = 15.0,
            max_reconnects: Optional[int] = None,
            backoff_base: Optional[float] = None,
            backoff_cap: Optional[float] = None,
            connect_retries: int = 1) -> int:
        """Work until the master reports done; returns jobs done.

        Reconnect state machine: a timeout or an undecodable reply
        closes the REQ socket (broken EFSM state — a retry on the same
        socket would raise ZMQError(EFSM)), backs off exponentially
        (``backoff_base`` doubling up to ``backoff_cap``, jittered
        deterministically per slave) and reconnects fresh, re-registering
        before any job traffic — so a master restart just looks like a
        long retry.  A pending update survives the reconnect and is
        re-sent (the master drops it as stale if the job was re-queued:
        one job, one accepted update).  Gives up cleanly after
        ``max_reconnects`` CONSECUTIVE failures (master gone for good).
        ``connect_retries`` bounds only the FIRST contact, so a slave
        pointed at a dead endpoint still fails fast with ConnectionError.
        Defaults come from root.common.engine.slave_reconnects /
        slave_backoff_base / slave_backoff_cap.

        v3 pipeline: while a job computes, a :class:`_JobPrefetcher`
        thread fetches the next one on a second socket
        (root.common.engine.job_prefetch, default on), and the pending
        update is kept as its encoded frames so a resend after a
        reconnect ships the same bytes.  Deltas go out quantized per
        root.common.engine.wire_dtype with error-feedback residuals.

        Unified fault model (ISSUE 14): the socket/backoff machinery is
        the shared :class:`~znicz_tpu.transport.Endpoint` (constants
        unchanged), PLUS the serving plane's circuit breaker
        (``root.common.engine.slave_breaker_failures`` consecutive
        transport failures open it; attempts then fail fast locally —
        no fresh socket, no recv-timeout burn — until its backoff
        admits a probe; 0 disables), and jobs whose ``deadline_ms``
        budget (stamped by the master at dispatch) is spent before
        compute are DROPPED uncomputed (``jobs_expired``) — the
        master's reaper re-queues them, so expired work is never
        computed, fleet-wide."""
        import logging

        from znicz_tpu.core.config import root
        from znicz_tpu.network_common import handshake_request
        from znicz_tpu.parallel import wire

        if max_reconnects is None:
            max_reconnects = int(
                root.common.engine.get("slave_reconnects", 8))
        if backoff_base is None:
            backoff_base = float(
                root.common.engine.get("slave_backoff_base", 0.25))
        if backoff_cap is None:
            backoff_cap = float(
                root.common.engine.get("slave_backoff_cap", 5.0))
        breaker_failures = int(
            root.common.engine.get("slave_breaker_failures", 4))
        # wire-v3 knobs: delta quantization (error-feedback residuals
        # live in the encoder, one per tensor) and the job prefetcher.
        # Literal config chains at each read site — the engine-knob lint
        # (tests/test_no_adhoc_counters.py) refuses subtree aliasing.
        self.wire_dtype = wire.canonical_wire_dtype(
            root.common.engine.get("wire_dtype", "float32"))
        self._delta_encoder = wire.DeltaEncoder(self.wire_dtype)
        prefetch_on = bool(root.common.engine.get("job_prefetch", True))
        log = logging.getLogger("znicz")
        # (LR schedules DO advance in master/slave mode since ISSUE 10:
        # the master evaluates lr_adjust policies at dispatch and ships
        # the scheduled hypers inside each TRAIN minibatch — applied in
        # _run_one / scheduled_hypers_rows for both engines.)

        # ONE breaker for both sockets: a dead master is detected once,
        # then the main loop AND the prefetcher fail fast together
        _brk_counters = {"open": self._m["breaker_opens"],
                         "short_circuit": self._m["breaker_short_circuits"]}

        def _brk_event(name: str) -> None:
            counter = _brk_counters.get(name)
            if counter is not None:
                counter.inc()

        self._breaker = CircuitBreaker(
            window=max(2 * breaker_failures, 1),
            threshold=breaker_failures, on_event=_brk_event,
            # probe windows pace on the SLAVE's own backoff constants
            # (un-jittered), not the serving plane's — per-plane
            # constants, one curve (ISSUE 14); CONSECUTIVE semantics:
            # the historical reconnect counter reset on every success,
            # so a sustained-but-survivable fault rate (chaos soaks
            # live there) keeps training and only a DEAD master opens
            # the breaker
            backoff=RetryPolicy(backoff_base, backoff_cap,
                                jitter=False),
            peer=self.endpoint, consecutive=True)

        def make_endpoint() -> Endpoint:
            return Endpoint(
                self.endpoint, recv_timeout_s=recv_timeout,
                retry=RetryPolicy.for_training_client(
                    backoff_base, backoff_cap, max_reconnects,
                    jitter_key=f"{self.slave_id}/backoff"),
                breaker=self._breaker)

        ep = make_endpoint()
        registered = False
        ever_registered = False
        failures = 0                    # CONSECUTIVE transport failures
        refusals = 0                    # CONSECUTIVE bad_frame replies
        refusal_cap = max(3, max_reconnects)
        #: the pending update as ALREADY-ENCODED v3 frames — a resend
        #: after a reconnect re-sends these bytes, it does not re-pickle
        #: or re-quantize anything (ISSUE 3 satellite)
        update_frames: Optional[list] = None
        prefetcher: Optional[_JobPrefetcher] = None

        def refused() -> bool:
            """A bad_frame reply means the master is alive but never
            decoded our frame — retry, BOUNDED: a master that refuses
            every frame we send (deterministic corruption, version skew)
            must not spin us forever.  True when the cap is spent."""
            nonlocal refusals
            refusals += 1
            if refusals <= refusal_cap:
                time.sleep(poll_sleep)
                return False
            if not ever_registered:
                raise RuntimeError(
                    f"master at {self.endpoint} refused {refusals} "
                    "consecutive frames (bad_frame) — giving up")
            log.warning("%s: master refused %d consecutive frames — "
                        "giving up", self.slave_id, refusals)
            return True

        def reconnect(exc) -> bool:
            """Fresh socket + backoff (the Endpoint already dropped the
            EFSM-broken socket); False when the budget is spent."""
            nonlocal prefetcher, registered, failures
            if isinstance(exc, _BadReply):
                self._m["bad_replies"].inc()
            failures += 1
            if not ever_registered:
                if failures >= connect_retries:
                    raise ConnectionError(
                        f"no master answered at {self.endpoint} within "
                        f"{recv_timeout:g}s — is the master running "
                        f"(launcher --master)?") from None
            elif failures > max_reconnects:
                fallback = self._fallback_endpoint
                if fallback and fallback != self.endpoint:
                    # our relay is gone for good: fall back to the
                    # upstream it advertised at register time (ISSUE
                    # 10) and ride the existing re-registration path.
                    # One hop per spent budget — the next successful
                    # register records the NEW peer's advertisement.
                    log.warning(
                        "%s: relay at %s gone after %d consecutive "
                        "reconnects — falling back to its upstream %s",
                        self.slave_id, self.endpoint, failures - 1,
                        fallback)
                    self.endpoint = fallback
                    ep.endpoint = fallback
                    self._fallback_endpoint = None
                    if prefetcher is not None:
                        # its Endpoint still dials the DEAD relay (and
                        # would keep filing timeouts into the shared
                        # breaker): retire it; re-created lazily on
                        # the next real job at the new endpoint —
                        # exactly the rehome path's discipline
                        prefetcher.stop()
                        prefetcher = None
                    failures = 1
                else:
                    log.warning(
                        "%s: giving up after %d consecutive reconnects "
                        "(master gone for good?)", self.slave_id,
                        failures - 1)
                    return False
            self._m["reconnects"].inc()
            registered = False
            ep.backoff(failures)        # capped exp + jitter, one home
            return True

        def short_circuit() -> None:
            """The breaker refused the attempt locally (ISSUE 14): no
            socket was built, no recv timeout burned.  Pace on the
            breaker's own probe window WITHOUT spending the reconnect
            budget — the budget counts REAL probe failures, so a dead
            master still yields a bounded, fail-fast give-up."""
            ep.breaker_wait(cap_s=backoff_cap)

        try:
            while True:
                if self._preempt.is_set():
                    break               # simulated spot kill (ISSUE 11)
                if not registered:
                    try:
                        rep = self._rpc(ep, handshake_request(
                            self.workflow, mesh=self.mesh_shape))
                    except CircuitOpenError:
                        short_circuit()
                        continue
                    except (PeerTimeout, _BadReply) as exc:
                        if not reconnect(exc):
                            break
                        continue
                    failures = 0        # any reply: the master is alive
                    if rep.get("bad_frame"):
                        if refused():
                            break
                        continue
                    refusals = 0
                    if not rep.get("ok"):
                        raise RuntimeError(
                            f"master refused registration: "
                            f"{rep.get('error')}")
                    # a relay advertises its upstream for dead-relay
                    # failover; the master advertises none
                    self._fallback_endpoint = rep.get("upstream")
                    registered = ever_registered = True
                    rehome = rep.get("rehome")
                    if rehome and rehome != self.endpoint:
                        # the master re-homed this orphan leaf behind a
                        # live relay (ISSUE 11 tree healing).  Keep the
                        # CURRENT endpoint as the fallback, so a rehome
                        # target that died in the meantime costs one
                        # more backoff window, never the slave.
                        log.info("%s: master re-homed us to %s",
                                 self.slave_id, rehome)
                        self._fallback_endpoint = self.endpoint
                        self.endpoint = rehome
                        registered = False
                        ep.reset()
                        ep.endpoint = rehome
                        if prefetcher is not None:
                            # its socket still points at the OLD peer —
                            # retire it; re-created lazily on the next
                            # real job
                            prefetcher.stop()
                            prefetcher = None
                    continue
                if update_frames is not None:
                    try:
                        rep = ep.rpc(update_frames)
                    except CircuitOpenError:
                        short_circuit()
                        continue
                    except (PeerTimeout, _BadReply) as exc:
                        if not reconnect(exc):
                            break
                        continue        # re-register, then RE-SEND it
                    failures = 0
                    if rep.get("bad_frame"):
                        if refused():
                            break       # master re-queues it by timeout
                        continue        # master never decoded it: resend
                    refusals = 0
                    if rep.get("unregistered"):
                        registered = False      # master restarted
                        continue
                    if rep.get("quarantined"):
                        log.warning("%s: master quarantined our delta: %s",
                                    self.slave_id, rep.get("error"))
                    if rep.get("stale_refused"):
                        # bounded staleness (ISSUE 11): the job was
                        # re-queued master-side; we just move on
                        log.info("%s: master refused our delta as "
                                 "stale: %s", self.slave_id,
                                 rep.get("error"))
                    update_frames = None
                    self._m["jobs_done"].inc()
                    continue
                # -- next job: the prefetcher's pipelined fetch first ----
                rep = None
                if prefetcher is not None:
                    rep = prefetcher.take()
                    if rep is not None:
                        failures = 0    # a reply is a reply: master alive
                        if "job" in rep:
                            self._m["prefetch_hits"].inc()
                if rep is None:
                    try:
                        rep = self._rpc(ep, {"cmd": "job"})
                        rep["_received_at"] = time.monotonic()
                    except CircuitOpenError:
                        short_circuit()
                        continue
                    except (PeerTimeout, _BadReply) as exc:
                        if not reconnect(exc):
                            break
                        continue
                    failures = 0
                if rep.get("bad_frame"):
                    if refused():
                        break
                    continue
                refusals = 0
                if rep.get("done"):
                    break
                if rep.get("unregistered"):
                    registered = False
                    continue
                if "job" not in rep:
                    time.sleep(poll_sleep)     # wait: master re-asks soon
                    continue
                job, params = rep["job"], rep["params"]
                if prefetch_on and prefetcher is None:
                    # started lazily on the FIRST real job, so a run the
                    # master refuses (or never serves) spawns no thread
                    prefetcher = _JobPrefetcher(self, make_endpoint,
                                                recv_timeout)
                if prefetcher is not None:
                    prefetcher.request()   # fetch job N+1 during compute
                # deadline propagation (ISSUE 14): the master stamps a
                # ``deadline_ms`` BUDGET on every job (its reap
                # timeout); a job that sat in the prefetch slot or a
                # relay queue past it is already re-queued master-side,
                # so computing it is pure waste — drop it UNCOMPUTED
                # and fetch fresh work (PR 6's "expired work never
                # computed", now on the training plane)
                deadline = local_deadline(rep.get("deadline_ms"),
                                          now=rep.get("_received_at"))
                if deadline is not None and time.monotonic() > deadline:
                    self._m["jobs_expired"].inc()
                    log.info("%s: job %s expired before compute "
                             "(budget %.0fms) — dropped, master "
                             "re-queues it", self.slave_id,
                             rep.get("job_id"), rep.get("deadline_ms"))
                    continue
                self._apply_params(params)
                before = {name: {k: np.asarray(v) for k, v in layer.items()}
                          for name, layer in params.items()}
                train = bool(rep.get("train"))
                # span correlated to the master's job by trace_id — the
                # cross-process join key a merged Perfetto view uses
                with self._tracer.span(
                        "slave", "job", job_id=rep.get("job_id"),
                        trace_id=rep.get("trace_id"), train=train):
                    metrics = self._run_minibatch(job, train)
                    deltas = self._deltas_since(before) if train else None
                update_frames, _ = wire.encode_message(
                    {"cmd": "update", "id": self.slave_id,
                     "job_id": rep["job_id"],
                     "trace_id": rep.get("trace_id"),
                     # the apply-counter stamp echoed back (ISSUE 11):
                     # the master reads the delta's staleness off it
                     "step": rep.get("step"),
                     "deltas": self._delta_encoder.encode(deltas),
                     "metrics": metrics,
                     **self._obs_payload()})
        finally:
            if prefetcher is not None:
                prefetcher.stop()
            ep.close()
        return self.jobs_done


class FusedClient(Client):
    """A slave that runs its jobs at FUSED-engine speed (VERDICT r4
    missing #2 / item 5): a segment job's k minibatches execute as ONE
    ``FusedTrainer`` scan dispatch on the local accelerator — one H2D of
    master params, k fused steps, one D2H for the deltas — instead of
    k unit-graph laps with a host sync per unit.  The wire protocol is
    UNCHANGED (generate_data_for_slave / apply_data_from_master payloads,
    per-minibatch metrics, delta aggregation, elastic membership): the
    master cannot tell a fused slave from a unit slave except by speed.

    Slave-local GD state (velocities) persists across jobs exactly like
    the unit slave's GD units' velocities do — the async-momentum
    semantics of the reference's parameter server are preserved.
    """

    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 slave_id: Optional[str] = None):
        super().__init__(workflow, endpoint=endpoint, slave_id=slave_id)
        from znicz_tpu.parallel.fused import (FusedStagingUnsupportedError,
                                              FusedTrainer)

        from znicz_tpu.parallel.mesh import train_mesh_from_config

        # construct EAGERLY so an unsupported graph (tied weights, ...)
        # raises FusedUnsupportedError here — where the launcher can fall
        # back to the unit Client — instead of crashing mid-fleet on the
        # first job (compilation still happens lazily, per job shape).
        # With root.common.engine.train_shard on, THIS slave is a pod
        # slice (ISSUE 18): steps jit with explicit shardings over the
        # engine mesh, grads psum over ICI inside the slice, and the
        # delta that leaves the process is already slice-summed — the
        # wire sees exactly one slave either way
        self._trainer = FusedTrainer(workflow,
                                     mesh=train_mesh_from_config())
        if self._trainer.staging:
            # dedicated type: the engine's slave fallback catches exactly
            # the known refusals, so a real config error (a bare
            # ValueError) propagates instead of silently dropping to the
            # unit-engine slave
            raise FusedStagingUnsupportedError(
                "FusedClient needs a device-resident loader "
                "(host-staged streaming slaves are not supported)")
        self._velocities = None
        self._dataset = None
        self._targets = None
        self._scan = None
        self._eval = None

    def engine_name(self) -> str:
        return "fused"

    @property
    def mesh_shape(self):
        """The trainer's slice shape (None single-device) — what the
        register handshake piggybacks."""
        return self._trainer.mesh_shape

    def _ensure_trainer(self):
        if self._scan is None:
            t = self._trainer
            # registered on the trainer under its canonical names so
            # jit_cache_sizes() (the zero-recompile cross-check) covers
            # the slave's executables too
            self._scan = t._train_scan = t.make_train_scan()
            self._eval = t._eval_step = t.make_eval_step()
            loader = self.workflow.loader
            self._dataset = t._op_value(loader.original_data)
            self._targets = t._op_value(
                loader.original_labels if t.loss_kind == "softmax"
                else loader.original_targets)
            self._velocities = t.extract_velocities()
            if t.mesh is not None:
                # place operands to match the scan's declared shardings
                # (committed single-device buffers would be refused by
                # the explicit in_shardings): dataset/targets replicate
                # once, velocities take their param placements
                from znicz_tpu.parallel.mesh import global_put, replicated

                repl = replicated(t.mesh)
                self._dataset = global_put(self._dataset, repl)
                self._targets = global_put(self._targets, repl)
                self._velocities = t.place_state(self._velocities)
        return self._trainer

    def _run_minibatch(self, job: dict, train: bool):
        t = self._ensure_trainer()
        mbs = job["minibatches"] if "minibatches" in job else [job]
        k = len(mbs)
        idx = np.stack([np.asarray(mb["indices"], np.int32) for mb in mbs])
        bs = np.array([mb["size"] for mb in mbs], np.int32)
        # master params, one H2D (synced); on a mesh the put distributes
        # each param straight to its slice placement
        params = t.place_state(t.extract_params())
        if not train:
            assert k == 1
            loss, n_err, conf = self._eval(
                params, self._dataset, self._targets, idx[0],
                np.int32(bs[0]), t._key0, False)
            metrics = {"loss": float(loss)}
            if t.loss_kind == "softmax":
                metrics["n_err"] = int(n_err)
                if t.compute_confusion:
                    metrics["confusion"] = np.asarray(conf)
            return metrics if "minibatches" not in job else [metrics]
        from znicz_tpu.core import prng

        steps = np.arange(t.steps_done, t.steps_done + k, dtype=np.int32)
        # master-scheduled hypers (ISSUE 10 satellite): the FusedTrainer
        # already takes per-step hypers rows as traced arguments (no
        # recompile) — feed it the SCHEDULED values stamped on the job
        # instead of constants when the master runs an LR schedule
        if any("hypers" in mb for mb in mbs):
            hyper_rows = scheduled_hypers_rows(t.hypers(), mbs)
        else:
            hyper_rows = t.tiled_hypers(k)
        params, self._velocities, ms, conf_sum = self._scan(
            params, self._velocities, hyper_rows, self._dataset,
            self._targets, idx, bs,
            prng.get("fused_trainer").jax_base_key(), steps)
        t.steps_done += k
        # unit Arrays adopt the post-job params so _deltas_since's
        # map_read sees them (the pre-job host copy stays the master's
        # payload — exactly the 'before' the delta subtracts)
        t.writeback(params, self._velocities)
        losses = np.asarray(ms[0])
        n_errs = np.asarray(ms[1])
        metrics = []
        for i in range(k):
            m = {"loss": float(losses[i])}
            if t.loss_kind == "softmax":
                m["n_err"] = int(n_errs[i])
                if i == 0 and t.compute_confusion:
                    # the segment's summed confusion rides the first
                    # minibatch (DecisionBase accumulates; None skipped)
                    m["confusion"] = np.asarray(conf_sum)
            metrics.append(m)
        return metrics if "minibatches" in job else metrics[0]


for _name, _help in Client.COUNTERS.items():
    setattr(Client, _name, _client_counter(_name, _help))
del _name, _help
