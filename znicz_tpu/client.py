"""Slave (rebuild of ``veles/client.py``): pulls jobs from the master,
computes one minibatch on the LOCAL workflow replica (the slave owns its
dataset copy like the reference's slaves did — the master only ships
minibatch indices + params), and pushes back weight deltas + metrics.
See server.py for the protocol; uses the Distributable payloads."""

from __future__ import annotations

import pickle
import time
import uuid
from typing import Dict, Optional

import numpy as np

from znicz_tpu.loader.base import TRAIN


class Client:
    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 slave_id: Optional[str] = None):
        self.workflow = workflow
        self.endpoint = endpoint
        self.slave_id = slave_id or uuid.uuid4().hex[:8]
        self.jobs_done = 0

    def _rpc(self, sock, msg: dict) -> dict:
        msg["id"] = self.slave_id
        sock.send(pickle.dumps(msg))
        return pickle.loads(sock.recv())

    def _apply_params(self, params: Dict) -> None:
        for f in self.workflow.forwards:
            if f.has_weights and f.name in params:
                f.apply_data_from_master(params[f.name])

    def _deltas_since(self, before: Dict) -> Dict:
        out = {}
        for f in self.workflow.forwards:
            if not f.has_weights:
                continue
            layer = {}
            for k, arr in f.params().items():
                layer[k] = np.array(arr.map_read()) - before[f.name][k]
            out[f.name] = layer
        return out

    def _run_minibatch(self, job: dict, train: bool) -> Dict:
        wf = self.workflow
        loader = wf.loader
        # inject the master's assignment into the local loader buffers
        idx = loader.minibatch_indices.map_invalidate()
        idx[...] = np.asarray(job["indices"], idx.dtype)
        loader.minibatch_size = job["size"]
        loader.minibatch_class = job["class"]
        loader.fill_minibatch()
        for f in wf.forwards:
            f.run()
        wf.evaluator.run()
        metrics = {"loss": float(wf.evaluator.loss)}
        if hasattr(wf.evaluator, "n_err"):
            metrics["n_err"] = int(wf.evaluator.n_err)
            metrics["confusion"] = np.array(
                wf.evaluator.confusion_matrix.map_read())
        if train:
            wf.decision.gd_skip.set(False)
            for gd in wf.gds:
                gd.run()
        return metrics

    def _connect(self, ctx, timeout_ms: int):
        import zmq

        sock = ctx.socket(zmq.REQ)
        sock.setsockopt(zmq.RCVTIMEO, timeout_ms)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(self.endpoint)
        return sock

    def run(self, poll_sleep: float = 0.05,
            recv_timeout: float = 15.0) -> int:
        """Work until the master reports done (or goes silent past
        ``recv_timeout`` — master-death tolerance); returns jobs done."""
        import zmq

        from znicz_tpu.network_common import handshake_request

        ctx = zmq.Context.instance()
        sock = self._connect(ctx, int(recv_timeout * 1000))
        try:
            try:
                rep = self._rpc(sock, handshake_request(self.workflow))
            except zmq.Again:
                raise ConnectionError(
                    f"no master answered at {self.endpoint} within "
                    f"{recv_timeout:g}s — is the master running "
                    f"(launcher --master)?") from None
            if not rep.get("ok"):
                raise RuntimeError(
                    f"master refused registration: {rep.get('error')}")
            while True:
                try:
                    rep = self._rpc(sock, {"cmd": "job"})
                except zmq.Again:
                    return self.jobs_done       # master gone -> stop clean
                if rep.get("done"):
                    return self.jobs_done
                if "job" not in rep:
                    time.sleep(poll_sleep)
                    continue
                job, params = rep["job"], rep["params"]
                self._apply_params(params)
                before = {name: {k: np.asarray(v) for k, v in layer.items()}
                          for name, layer in params.items()}
                train = bool(rep.get("train"))
                metrics = self._run_minibatch(job, train)
                deltas = self._deltas_since(before) if train else None
                try:
                    self._rpc(sock, {"cmd": "update",
                                     "job_id": rep["job_id"],
                                     "deltas": deltas, "metrics": metrics})
                except zmq.Again:
                    return self.jobs_done       # master gone mid-update
                self.jobs_done += 1
        finally:
            sock.close(0)
