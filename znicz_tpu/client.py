"""Slave (rebuild of ``veles/client.py``): pulls jobs from the master,
computes one minibatch on the LOCAL workflow replica (the slave owns its
dataset copy like the reference's slaves did — the master only ships
minibatch indices + params), and pushes back weight deltas + metrics.
See server.py for the protocol; uses the Distributable payloads."""

from __future__ import annotations

import pickle
import time
import uuid
from typing import Dict, Optional

import numpy as np

from znicz_tpu.loader.base import TRAIN


class Client:
    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 slave_id: Optional[str] = None):
        self.workflow = workflow
        self.endpoint = endpoint
        self.slave_id = slave_id or uuid.uuid4().hex[:8]
        self.jobs_done = 0

    def _rpc(self, sock, msg: dict) -> dict:
        msg["id"] = self.slave_id
        sock.send(pickle.dumps(msg))
        return pickle.loads(sock.recv())

    def _apply_params(self, params: Dict) -> None:
        for f in self.workflow.forwards:
            if f.has_weights and f.name in params:
                f.apply_data_from_master(params[f.name])

    def _deltas_since(self, before: Dict) -> Dict:
        out = {}
        for f in self.workflow.forwards:
            if not f.has_weights:
                continue
            layer = {}
            for k, arr in f.params().items():
                layer[k] = np.array(arr.map_read()) - before[f.name][k]
            out[f.name] = layer
        return out

    def _run_minibatch(self, job: dict, train: bool):
        """One job's worth of local compute.  A SEGMENT job (master
        ``segment_steps`` > 1: {"minibatches": [...]}) loops its
        minibatches and returns a metrics list; a flat job returns one
        metrics dict (see FusedClient for the scan-dispatch version)."""
        if "minibatches" in job:
            return [self._run_one(mb, train) for mb in job["minibatches"]]
        return self._run_one(job, train)

    def _run_one(self, job: dict, train: bool) -> Dict:
        wf = self.workflow
        loader = wf.loader
        # inject the master's assignment into the local loader buffers
        idx = loader.minibatch_indices.map_invalidate()
        idx[...] = np.asarray(job["indices"], idx.dtype)
        loader.minibatch_size = job["size"]
        loader.minibatch_class = job["class"]
        loader.fill_minibatch()
        for f in wf.forwards:
            f.run()
        wf.evaluator.run()
        metrics = {"loss": float(wf.evaluator.loss)}
        if hasattr(wf.evaluator, "n_err"):
            metrics["n_err"] = int(wf.evaluator.n_err)
            metrics["confusion"] = np.array(
                wf.evaluator.confusion_matrix.map_read())
        if train:
            wf.decision.gd_skip.set(False)
            for gd in wf.gds:
                gd.run()
        return metrics

    def _connect(self, ctx, timeout_ms: int):
        import zmq

        sock = ctx.socket(zmq.REQ)
        sock.setsockopt(zmq.RCVTIMEO, timeout_ms)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(self.endpoint)
        return sock

    def engine_name(self) -> str:
        return "unit"

    def run(self, poll_sleep: float = 0.05,
            recv_timeout: float = 15.0) -> int:
        """Work until the master reports done (or goes silent past
        ``recv_timeout`` — master-death tolerance); returns jobs done."""
        import zmq

        from znicz_tpu.network_common import handshake_request

        from znicz_tpu.lr_adjust import LearningRateAdjust

        if any(isinstance(u, LearningRateAdjust)
               for u in self.workflow.units):
            # slaves run forwards/evaluator/gds per job, never the
            # lr_adjust unit — true for BOTH engines (the fused slave's
            # constant tiled_hypers match the unit slave exactly), so an
            # LR schedule silently freezes at its initial value in the
            # async master/slave mode.  Say so instead of being subtle.
            import logging

            logging.getLogger("znicz").warning(
                "%s: LR schedules do not advance in master/slave mode "
                "(slaves run gds only); training proceeds at the "
                "current learning rate", self.slave_id)

        ctx = zmq.Context.instance()
        sock = self._connect(ctx, int(recv_timeout * 1000))
        try:
            try:
                rep = self._rpc(sock, handshake_request(self.workflow))
            except zmq.Again:
                raise ConnectionError(
                    f"no master answered at {self.endpoint} within "
                    f"{recv_timeout:g}s — is the master running "
                    f"(launcher --master)?") from None
            if not rep.get("ok"):
                raise RuntimeError(
                    f"master refused registration: {rep.get('error')}")
            while True:
                try:
                    rep = self._rpc(sock, {"cmd": "job"})
                except zmq.Again:
                    return self.jobs_done       # master gone -> stop clean
                if rep.get("done"):
                    return self.jobs_done
                if "job" not in rep:
                    time.sleep(poll_sleep)
                    continue
                job, params = rep["job"], rep["params"]
                self._apply_params(params)
                before = {name: {k: np.asarray(v) for k, v in layer.items()}
                          for name, layer in params.items()}
                train = bool(rep.get("train"))
                metrics = self._run_minibatch(job, train)
                deltas = self._deltas_since(before) if train else None
                try:
                    self._rpc(sock, {"cmd": "update",
                                     "job_id": rep["job_id"],
                                     "deltas": deltas, "metrics": metrics})
                except zmq.Again:
                    return self.jobs_done       # master gone mid-update
                self.jobs_done += 1
        finally:
            sock.close(0)


class FusedClient(Client):
    """A slave that runs its jobs at FUSED-engine speed (VERDICT r4
    missing #2 / item 5): a segment job's k minibatches execute as ONE
    ``FusedTrainer`` scan dispatch on the local accelerator — one H2D of
    master params, k fused steps, one D2H for the deltas — instead of
    k unit-graph laps with a host sync per unit.  The wire protocol is
    UNCHANGED (generate_data_for_slave / apply_data_from_master payloads,
    per-minibatch metrics, delta aggregation, elastic membership): the
    master cannot tell a fused slave from a unit slave except by speed.

    Slave-local GD state (velocities) persists across jobs exactly like
    the unit slave's GD units' velocities do — the async-momentum
    semantics of the reference's parameter server are preserved.
    """

    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 slave_id: Optional[str] = None):
        super().__init__(workflow, endpoint=endpoint, slave_id=slave_id)
        from znicz_tpu.parallel.fused import (FusedStagingUnsupportedError,
                                              FusedTrainer)

        # construct EAGERLY so an unsupported graph (tied weights, ...)
        # raises FusedUnsupportedError here — where the launcher can fall
        # back to the unit Client — instead of crashing mid-fleet on the
        # first job (compilation still happens lazily, per job shape)
        self._trainer = FusedTrainer(workflow)
        if self._trainer.staging:
            # dedicated type: the engine's slave fallback catches exactly
            # the known refusals, so a real config error (a bare
            # ValueError) propagates instead of silently dropping to the
            # unit-engine slave
            raise FusedStagingUnsupportedError(
                "FusedClient needs a device-resident loader "
                "(host-staged streaming slaves are not supported)")
        self._velocities = None
        self._dataset = None
        self._targets = None
        self._scan = None
        self._eval = None

    def engine_name(self) -> str:
        return "fused"

    def _ensure_trainer(self):
        if self._scan is None:
            t = self._trainer
            self._scan = t.make_train_scan()
            self._eval = t.make_eval_step()
            loader = self.workflow.loader
            self._dataset = t._op_value(loader.original_data)
            self._targets = t._op_value(
                loader.original_labels if t.loss_kind == "softmax"
                else loader.original_targets)
            self._velocities = t.extract_velocities()
        return self._trainer

    def _run_minibatch(self, job: dict, train: bool):
        t = self._ensure_trainer()
        mbs = job["minibatches"] if "minibatches" in job else [job]
        k = len(mbs)
        idx = np.stack([np.asarray(mb["indices"], np.int32) for mb in mbs])
        bs = np.array([mb["size"] for mb in mbs], np.int32)
        params = t.extract_params()     # master params, one H2D (synced)
        if not train:
            assert k == 1
            loss, n_err, conf = self._eval(
                params, self._dataset, self._targets, idx[0],
                np.int32(bs[0]), t._key0, False)
            metrics = {"loss": float(loss)}
            if t.loss_kind == "softmax":
                metrics["n_err"] = int(n_err)
                if t.compute_confusion:
                    metrics["confusion"] = np.asarray(conf)
            return metrics if "minibatches" not in job else [metrics]
        from znicz_tpu.core import prng

        steps = np.arange(t.steps_done, t.steps_done + k, dtype=np.int32)
        params, self._velocities, ms, conf_sum = self._scan(
            params, self._velocities, t.tiled_hypers(k), self._dataset,
            self._targets, idx, bs,
            prng.get("fused_trainer").jax_base_key(), steps)
        t.steps_done += k
        # unit Arrays adopt the post-job params so _deltas_since's
        # map_read sees them (the pre-job host copy stays the master's
        # payload — exactly the 'before' the delta subtracts)
        t.writeback(params, self._velocities)
        losses = np.asarray(ms[0])
        n_errs = np.asarray(ms[1])
        metrics = []
        for i in range(k):
            m = {"loss": float(losses[i])}
            if t.loss_kind == "softmax":
                m["n_err"] = int(n_errs[i])
                if i == 0 and t.compute_confusion:
                    # the segment's summed confusion rides the first
                    # minibatch (DecisionBase accumulates; None skipped)
                    m["confusion"] = np.asarray(conf_sum)
            metrics.append(m)
        return metrics if "minibatches" in job else metrics[0]
