"""Input normalizers (rebuild of ``veles/normalization.py``).

Strategies match the reference set: none, linear (to [-1,1] range),
mean_disp (subtract mean, divide by dispersion), exp (sigmoid-squash),
pointwise (per-feature linear).  Normalizers are fit on TRAIN data only and
their state is serialized into snapshots so inference-time inputs get the
same transform.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class NoneNormalizer:
    NAME = "none"

    def fit(self, data: np.ndarray) -> None:
        pass

    def apply_inplace(self, data: np.ndarray) -> None:
        pass

    def state(self) -> Dict:
        return {}

    def restore(self, state: Dict) -> None:
        pass


class LinearNormalizer(NoneNormalizer):
    """Scale to [interval] from the fitted min/max (reference default
    interval (-1, 1))."""

    NAME = "linear"

    def __init__(self, interval=(-1.0, 1.0)):
        self.interval = tuple(interval)
        self.vmin = None
        self.vmax = None

    def fit(self, data: np.ndarray) -> None:
        self.vmin = float(np.min(data))
        self.vmax = float(np.max(data))

    def apply_inplace(self, data: np.ndarray) -> None:
        lo, hi = self.interval
        span = (self.vmax - self.vmin) or 1.0
        data[...] = (data - self.vmin) / span * (hi - lo) + lo

    def state(self) -> Dict:
        return {"interval": self.interval, "vmin": self.vmin,
                "vmax": self.vmax}

    def restore(self, state: Dict) -> None:
        self.interval = tuple(state["interval"])
        self.vmin = state["vmin"]
        self.vmax = state["vmax"]


class MeanDispNormalizer(NoneNormalizer):
    """Subtract per-feature mean, divide by per-feature dispersion
    (max - min), the reference's image-net-style normalizer."""

    NAME = "mean_disp"

    def __init__(self):
        self.mean = None
        self.disp = None

    def fit(self, data: np.ndarray) -> None:
        flat = data.reshape(len(data), -1)
        self.mean = flat.mean(axis=0).astype(np.float32)
        disp = flat.max(axis=0) - flat.min(axis=0)
        disp[disp == 0] = 1.0
        self.disp = disp.astype(np.float32)

    def apply_inplace(self, data: np.ndarray) -> None:
        flat = data.reshape(len(data), -1)
        flat -= self.mean
        flat /= self.disp

    def state(self) -> Dict:
        return {"mean": self.mean, "disp": self.disp}

    def restore(self, state: Dict) -> None:
        self.mean = np.asarray(state["mean"], np.float32)
        self.disp = np.asarray(state["disp"], np.float32)


class ExpNormalizer(NoneNormalizer):
    """Reference's exponential squash: 2/(1+exp(-x)) - 1."""

    NAME = "exp"

    def apply_inplace(self, data: np.ndarray) -> None:
        data[...] = 2.0 / (1.0 + np.exp(-data)) - 1.0


class PointwiseNormalizer(NoneNormalizer):
    """Per-feature linear map fitted so each feature spans [-1, 1]."""

    NAME = "pointwise"

    def __init__(self):
        self.scale = None
        self.shift = None

    def fit(self, data: np.ndarray) -> None:
        flat = data.reshape(len(data), -1)
        lo, hi = flat.min(axis=0), flat.max(axis=0)
        span = hi - lo
        span[span == 0] = 1.0
        self.scale = (2.0 / span).astype(np.float32)
        self.shift = (-(lo + hi) / span).astype(np.float32)

    def apply_inplace(self, data: np.ndarray) -> None:
        flat = data.reshape(len(data), -1)
        flat *= self.scale
        flat += self.shift

    def state(self) -> Dict:
        return {"scale": self.scale, "shift": self.shift}

    def restore(self, state: Dict) -> None:
        self.scale = np.asarray(state["scale"], np.float32)
        self.shift = np.asarray(state["shift"], np.float32)


NORMALIZERS = {cls.NAME: cls for cls in
               (NoneNormalizer, LinearNormalizer, MeanDispNormalizer,
                ExpNormalizer, PointwiseNormalizer)}


def make(name: str, **kwargs) -> NoneNormalizer:
    return NORMALIZERS[name](**kwargs)
