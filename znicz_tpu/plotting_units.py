"""Plotting units (rebuild of ``veles/plotting_units.py`` +
``znicz/nn_plotting_units.py``).

The reference streamed live matplotlib figures from plot units to a separate
``GraphicsClient`` process over ZMQ pub/sub.  The rebuild keeps BOTH modes
with a single renderer per figure kind:

  - each plotter is ``snapshot()`` (gather plain data) + static
    ``draw(plt, **data)`` (pure renderer);
  - when a ``graphics.GraphicsServer`` is active, ``run`` publishes the
    snapshot — a separate ``GraphicsClient`` process re-renders it live with
    the same ``draw``;
  - otherwise ``run`` renders offline to ``<root.common.dirs.plots>/
    <name>.png`` (headless TPU-host default).

The figure set mirrors the reference: error curves (AccumulatingPlotter),
weight tiles (Weights2D), confusion matrix (MatrixPlotter), SOM hit maps
(KohonenHits), value histograms (MultiHistogram).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit

root.common.dirs.defaults({"plots": "plots"})


def _plots_dir() -> str:
    d = root.common.dirs.get("plots", "plots")
    os.makedirs(d, exist_ok=True)
    return d


class Plotter(Unit):
    """Base: gathers a plain-data ``snapshot`` and either streams it to the
    active ``GraphicsServer`` or renders it into ``<plots>/<name>.png``."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.render = kwargs.get("render", True)

    def path(self) -> str:
        return os.path.join(_plots_dir(), f"{self.name}.png")

    def snapshot(self) -> dict:
        """Plain arrays/scalars for ``draw`` — must be picklable."""
        raise NotImplementedError

    @staticmethod
    def draw(plt, **data) -> None:
        """Pure renderer; shared verbatim by offline run and live client."""
        raise NotImplementedError

    @classmethod
    def render_png(cls, data: dict, path: str) -> None:
        """THE figure scaffolding (backend, size, save options) — shared by
        the offline path and the live GraphicsClient so they cannot
        diverge."""
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        fig = plt.figure(figsize=(6, 4), dpi=96)
        try:
            cls.draw(plt, **data)
            fig.savefig(path, bbox_inches="tight")
        finally:
            plt.close(fig)

    def run(self):
        # snapshot() BEFORE the render gate: accumulating plotters keep
        # their raw series for tests/notebooks even with render=False
        data = self.snapshot()
        if not self.render:
            return
        from znicz_tpu.graphics import GraphicsServer

        server = GraphicsServer.active()
        if server is not None:
            server.publish({"kind": "figure", "cls": type(self).__name__,
                            "name": self.name, "data": data})
            return
        self.render_png(data, self.path())


class AccumulatingPlotter(Plotter):
    """Error/loss curve: appends ``fetch()`` (a float, e.g. a decision epoch
    metric) every run."""

    def __init__(self, workflow=None, name=None, fetch=None, ylabel="value",
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.fetch = fetch                 # () -> float
        self.ylabel = ylabel
        self.values: List[float] = []

    def snapshot(self) -> dict:
        if self.fetch is not None:
            self.values.append(float(self.fetch()))
        return {"values": list(self.values), "ylabel": self.ylabel}

    @staticmethod
    def draw(plt, values=(), ylabel="value"):
        plt.plot(values, marker="o", ms=3)
        plt.xlabel("epoch")
        plt.ylabel(ylabel)
        plt.grid(True, alpha=0.3)


class Weights2D(Plotter):
    """Weight tiles: first ``limit`` rows of a weight matrix reshaped to
    ``sample_shape`` and tiled into one image (the reference's
    weights-as-images plot)."""

    def __init__(self, workflow=None, name=None, source=None,
                 sample_shape=None, limit=64, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.source = source               # Array (n_out, fan_in)
        self.sample_shape = sample_shape   # e.g. (28, 28)
        self.limit = int(limit)

    def snapshot(self) -> dict:
        w = np.asarray(self.source.map_read())
        return {"weights": w.reshape(w.shape[0], -1)[:self.limit].copy(),
                "sample_shape": self.sample_shape}

    @staticmethod
    def draw(plt, weights=None, sample_shape=None):
        w = np.asarray(weights)
        shape = tuple(sample_shape) if sample_shape else (
            int(np.sqrt(w.shape[1])), int(np.sqrt(w.shape[1])))
        n = w.shape[0]
        cols = int(np.ceil(np.sqrt(n)))
        rows = int(np.ceil(n / cols))
        tile = np.zeros((rows * shape[0], cols * shape[1]), np.float32)
        for i in range(n):
            r, c = divmod(i, cols)
            img = w[i][:shape[0] * shape[1]].reshape(shape)
            tile[r * shape[0]:(r + 1) * shape[0],
                 c * shape[1]:(c + 1) * shape[1]] = img
        plt.imshow(tile, cmap="gray")
        plt.axis("off")


class MatrixPlotter(Plotter):
    """Confusion matrix heatmap."""

    def __init__(self, workflow=None, name=None, fetch=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.fetch = fetch                 # () -> 2D array

    def snapshot(self) -> dict:
        return {"matrix": np.asarray(self.fetch())}

    @staticmethod
    def draw(plt, matrix=None):
        plt.imshow(np.asarray(matrix), cmap="viridis")
        plt.colorbar()
        plt.xlabel("target")
        plt.ylabel("predicted")


class KohonenHits(Plotter):
    """SOM hit map: per-neuron winner counts on the (sy, sx) grid."""

    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.forward = forward             # KohonenForward

    def snapshot(self) -> dict:
        f = self.forward
        return {"hits": np.asarray(f.hits.map_read()).reshape(f.sy, f.sx),
                "total": int(f.total)}

    @staticmethod
    def draw(plt, hits=None, total=0):
        plt.imshow(np.asarray(hits), cmap="hot")
        plt.colorbar()
        plt.title(f"hits (total {total})")


class MultiHistogram(Plotter):
    """Histogram of a tensor's values (weights diversity diagnostics)."""

    def __init__(self, workflow=None, name=None, source=None, bins=50,
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.source = source
        self.bins = int(bins)

    def snapshot(self) -> dict:
        return {"values": np.asarray(self.source.map_read()).reshape(-1),
                "bins": self.bins}

    @staticmethod
    def draw(plt, values=None, bins=50):
        plt.hist(np.asarray(values), bins=int(bins))
        plt.grid(True, alpha=0.3)
