"""Plotting units (rebuild of ``veles/plotting_units.py`` +
``znicz/nn_plotting_units.py``).

The reference streamed live matplotlib figures from plot units to a separate
``GraphicsClient`` process over ZMQ pub/sub.  On a headless TPU host the
rebuild renders the same figures *offline*: each plotter is an ordinary unit
gated to epoch boundaries that writes a PNG under
``root.common.dirs.plots`` (plus keeps the raw series on itself for tests /
notebooks).  The figure set mirrors the reference: error curves
(AccumulatingPlotter), weight tiles (Weights2D), confusion matrix
(MatrixPlotter), SOM hit maps (KohonenHits), value histograms
(MultiHistogram).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit

root.common.dirs.defaults({"plots": "plots"})


def _plots_dir() -> str:
    d = root.common.dirs.get("plots", "plots")
    os.makedirs(d, exist_ok=True)
    return d


class Plotter(Unit):
    """Base: renders into ``<plots>/<name>.png`` via headless matplotlib."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.render = kwargs.get("render", True)

    def _figure(self):
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        return plt

    def path(self) -> str:
        return os.path.join(_plots_dir(), f"{self.name}.png")

    def redraw(self, plt) -> None:
        raise NotImplementedError

    def run(self):
        if not self.render:
            return
        plt = self._figure()
        fig = plt.figure(figsize=(6, 4), dpi=96)
        try:
            self.redraw(plt)
            fig.savefig(self.path(), bbox_inches="tight")
        finally:
            plt.close(fig)


class AccumulatingPlotter(Plotter):
    """Error/loss curve: appends ``input`` (a float, linked e.g. to a
    decision epoch metric via a fetch callable) every run."""

    def __init__(self, workflow=None, name=None, fetch=None, ylabel="value",
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.fetch = fetch                 # () -> float
        self.ylabel = ylabel
        self.values: List[float] = []

    def run(self):
        if self.fetch is not None:
            self.values.append(float(self.fetch()))
        super().run()

    def redraw(self, plt):
        plt.plot(self.values, marker="o", ms=3)
        plt.xlabel("epoch")
        plt.ylabel(self.ylabel)
        plt.grid(True, alpha=0.3)


class Weights2D(Plotter):
    """Weight tiles: first ``limit`` rows of a weight matrix reshaped to
    ``sample_shape`` and tiled into one image (the reference's
    weights-as-images plot)."""

    def __init__(self, workflow=None, name=None, source=None,
                 sample_shape=None, limit=64, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.source = source               # Array (n_out, fan_in)
        self.sample_shape = sample_shape   # e.g. (28, 28)
        self.limit = int(limit)

    def redraw(self, plt):
        w = np.asarray(self.source.map_read())
        w = w.reshape(w.shape[0], -1)[:self.limit]
        shape = self.sample_shape or (
            int(np.sqrt(w.shape[1])), int(np.sqrt(w.shape[1])))
        n = w.shape[0]
        cols = int(np.ceil(np.sqrt(n)))
        rows = int(np.ceil(n / cols))
        tile = np.zeros((rows * shape[0], cols * shape[1]), np.float32)
        for i in range(n):
            r, c = divmod(i, cols)
            img = w[i][:shape[0] * shape[1]].reshape(shape)
            tile[r * shape[0]:(r + 1) * shape[0],
                 c * shape[1]:(c + 1) * shape[1]] = img
        plt.imshow(tile, cmap="gray")
        plt.axis("off")


class MatrixPlotter(Plotter):
    """Confusion matrix heatmap."""

    def __init__(self, workflow=None, name=None, fetch=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.fetch = fetch                 # () -> 2D array

    def redraw(self, plt):
        m = np.asarray(self.fetch())
        plt.imshow(m, cmap="viridis")
        plt.colorbar()
        plt.xlabel("target")
        plt.ylabel("predicted")


class KohonenHits(Plotter):
    """SOM hit map: per-neuron winner counts on the (sy, sx) grid."""

    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.forward = forward             # KohonenForward

    def redraw(self, plt):
        f = self.forward
        hits = np.asarray(f.hits.map_read()).reshape(f.sy, f.sx)
        plt.imshow(hits, cmap="hot")
        plt.colorbar()
        plt.title(f"hits (total {f.total})")


class MultiHistogram(Plotter):
    """Histogram of a tensor's values (weights diversity diagnostics)."""

    def __init__(self, workflow=None, name=None, source=None, bins=50,
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.source = source
        self.bins = int(bins)

    def redraw(self, plt):
        vals = np.asarray(self.source.map_read()).reshape(-1)
        plt.hist(vals, bins=self.bins)
        plt.grid(True, alpha=0.3)
