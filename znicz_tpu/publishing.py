"""Post-training report generation (rebuild of ``veles/publishing/``).

The reference rendered run reports to HTML/PDF/Confluence backends.  The
rebuild keeps a backend registry with Markdown, HTML and PDF backends that
collect everything the reference's reports contained: workflow identity,
config snapshot, per-class epoch metrics, best validation numbers, unit
timing table, and any rendered plot PNGs.

Documented drop: the **confluence** backend is intentionally not rebuilt —
it was a thin HTTP client for a proprietary wiki API, unverifiable here
(reference mount empty, no network) and useless without a Confluence
server; the HTML backend output is what it would have uploaded."""

from __future__ import annotations

import html
import json
import os
import time
from typing import Dict, Optional

from znicz_tpu.core.config import root


def gather_report(workflow) -> Dict:
    from znicz_tpu.decision import CLASS_NAMES, DecisionBase

    rep: Dict = {
        "name": workflow.name,
        "time": time.strftime("%Y-%m-%d %H:%M:%S"),
        "config": root.to_dict(),
        "units": [],
        "metrics": {},
    }
    total = sum(u.run_time for u in workflow.units) or 1e-12
    for u in sorted(workflow.units, key=lambda u: -u.run_time):
        if u.run_count:
            rep["units"].append({"name": u.name, "runs": u.run_count,
                                 "time_s": round(u.run_time, 4),
                                 "pct": round(100 * u.run_time / total, 1)})
    for u in workflow.units:
        if isinstance(u, DecisionBase):
            rep["metrics"]["best_metric"] = float(u.best_metric)
            rep["metrics"]["best_epoch"] = int(u.best_epoch)
            rep["metrics"]["epochs"] = int(u.epoch_number) + 1
            for k, m in enumerate(u.epoch_metrics):
                if m is not None:
                    rep["metrics"][CLASS_NAMES[k]] = {
                        key: (float(v) if isinstance(v, (int, float))
                              else None)
                        for key, v in m.items() if key != "confusion"}
    fused = getattr(workflow, "fused_stats", None)
    if fused and fused.get("wall_s"):
        rep["metrics"]["fused_img_per_sec"] = fused["img_per_sec"]
        rep["metrics"]["fused_warm_img_per_sec"] = \
            fused.get("warm_img_per_sec", 0.0)
        rep["metrics"]["fused_train_steps"] = fused["train_steps"]
    plots_dir = root.common.dirs.get("plots")
    if plots_dir and os.path.isdir(plots_dir):
        rep["plots"] = sorted(f for f in os.listdir(plots_dir)
                              if f.endswith(".png"))
    return rep


class MarkdownBackend:
    EXT = ".md"

    def write(self, rep: Dict, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.render(rep))

    def render(self, rep: Dict) -> str:
        lines = [f"# Training report — {rep['name']}", "",
                 f"Generated: {rep['time']}", "", "## Metrics", ""]
        for key, val in rep["metrics"].items():
            lines.append(f"- **{key}**: "
                         f"{json.dumps(val) if isinstance(val, dict) else val}")
        lines += ["", "## Unit timing", "",
                  "| unit | runs | time (s) | % |", "|---|---|---|---|"]
        for u in rep["units"]:
            lines.append(f"| {u['name']} | {u['runs']} | {u['time_s']} "
                         f"| {u['pct']} |")
        for png in rep.get("plots", []):
            lines.append(f"\n![{png}]({png})")
        return "\n".join(lines) + "\n"


class HTMLBackend(MarkdownBackend):
    EXT = ".html"

    def render(self, rep: Dict) -> str:
        md = MarkdownBackend().render(rep)
        body = "".join(f"<p>{html.escape(line)}</p>\n"
                       for line in md.splitlines() if line.strip())
        return (f"<html><head><title>{html.escape(rep['name'])}</title>"
                f"</head><body>{body}</body></html>\n")


class PDFBackend:
    """PDF report via matplotlib's PdfPages (VERDICT r2 item 9): a title +
    metrics page, a unit-timing table page, then one page per rendered plot
    PNG.  The reference's Confluence backend is an explicit drop — it needs
    a Confluence server, which cannot exist here."""

    EXT = ".pdf"

    def write(self, rep: Dict, path: str) -> None:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        from matplotlib.backends.backend_pdf import PdfPages

        with PdfPages(path) as pdf:
            fig = plt.figure(figsize=(8.27, 11.69))        # A4 portrait
            fig.text(0.5, 0.92, f"Training report — {rep['name']}",
                     ha="center", size=18, weight="bold")
            fig.text(0.5, 0.88, f"Generated: {rep['time']}", ha="center",
                     size=10, color="gray")
            lines = []
            for key, val in rep["metrics"].items():
                lines.append(f"{key}: "
                             f"{json.dumps(val) if isinstance(val, dict) else val}")
            fig.text(0.1, 0.82, "\n".join(lines), va="top", size=11,
                     family="monospace")
            pdf.savefig(fig)
            plt.close(fig)

            if rep["units"]:
                fig, ax = plt.subplots(figsize=(8.27, 11.69))
                ax.axis("off")
                ax.set_title("Unit timing")
                cells = [[u["name"], u["runs"], u["time_s"], u["pct"]]
                         for u in rep["units"]]
                table = ax.table(
                    cellText=cells,
                    colLabels=["unit", "runs", "time (s)", "%"],
                    loc="upper center")
                table.auto_set_font_size(False)
                table.set_fontsize(9)
                pdf.savefig(fig)
                plt.close(fig)

            plots_dir = root.common.dirs.get("plots")
            for png in rep.get("plots", []):
                img = plt.imread(os.path.join(plots_dir, png))
                fig, ax = plt.subplots(figsize=(8.27, 11.69))
                ax.imshow(img)
                ax.axis("off")
                ax.set_title(png)
                pdf.savefig(fig)
                plt.close(fig)


BACKENDS = {"markdown": MarkdownBackend, "html": HTMLBackend,
            "pdf": PDFBackend}


def publish(workflow, backend: str = "markdown",
            directory: Optional[str] = None) -> str:
    rep = gather_report(workflow)
    be = BACKENDS[backend]()
    directory = directory or root.common.dirs.get("reports", "reports")
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{workflow.name}_report{be.EXT}")
    be.write(rep, path)
    return path
