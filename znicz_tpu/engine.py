"""Engine selection for a built workflow: the unit-at-a-time graph engine
(reference execution semantics, ``Workflow.run``) vs the fused SPMD fast
path (``znicz_tpu/parallel/fused.py``), chosen by
``root.common.engine.fused`` — the launcher's ``--fused`` flag.

The fused path requires the StandardWorkflow graph shape (forwards / gds /
loader / decision) and no tied weights; anything else (Kohonen, RBM,
hand-wired graphs) falls back to the unit engine automatically.
"""

from __future__ import annotations

from znicz_tpu.core.config import root


def wants_fused() -> bool:
    return bool(root.common.engine.get("fused", False))


def _fused_capable(workflow) -> bool:
    """--fused applies: requested AND the graph has the StandardWorkflow
    shape the fused engine needs (one predicate for the local and slave
    branches — they must never disagree)."""
    return wants_fused() and all(
        getattr(workflow, a, None) is not None
        for a in ("forwards", "gds", "loader", "decision"))


def _check_distributable(workflow, mode: str) -> None:
    missing = [a for a in ("forwards", "loader", "decision")
               if getattr(workflow, a, None) is None]
    if missing:
        raise ValueError(
            f"--{mode} needs a StandardWorkflow-shaped graph; "
            f"{workflow.name} lacks {missing}")


def train(workflow) -> None:
    """Train ``workflow`` with the configured engine/mode.

    ``root.common.engine.mode`` (the launcher's ``--master``/``--slave``)
    switches to the asynchronous parameter-server roles — the reference's
    CLI distribution surface (SURVEY §3.1/§3.4) — instead of local
    training."""
    mode = root.common.engine.get("mode", "")
    if mode == "master":
        from znicz_tpu.server import Server

        _check_distributable(workflow, mode)
        # --master-resume: restore mid-training state when the file
        # exists and keep it updated while serving (crash-resume)
        Server(workflow,
               endpoint=root.common.engine.get("master_bind",
                                               "tcp://*:5570"),
               resume_path=root.common.engine.get("master_resume",
                                                  "")).serve()
        return
    if mode == "slave":
        from znicz_tpu.client import Client, FusedClient

        _check_distributable(workflow, mode)
        endpoint = root.common.engine.get("slave_endpoint")
        client = None
        # --fused --slave: jobs run as FusedTrainer scan dispatches (one
        # compiled segment per job) instead of unit-graph laps; protocol
        # unchanged (VERDICT r4 item 5).  Graphs the fused engine cannot
        # run fall back to the unit Client, mirroring the local --fused
        # fallback below.  Catch ONLY the dedicated refusal types
        # (FusedUnsupportedError covers the tied-weights refusal and the
        # host-staged-loader subclass FusedStagingUnsupportedError) — a
        # bare ValueError is a real config error and must propagate, not
        # silently demote the slave to the slow unit engine.
        if _fused_capable(workflow):
            from znicz_tpu.parallel.fused import FusedUnsupportedError

            try:
                client = FusedClient(workflow, endpoint=endpoint)
            except FusedUnsupportedError as exc:
                import logging

                logging.getLogger("znicz").warning(
                    "fused slave unavailable (%s); falling back to the "
                    "unit-engine slave", exc)
        if client is None:
            client = Client(workflow, endpoint=endpoint)
        client.run()
        return
    if _fused_capable(workflow):
        from znicz_tpu.parallel.fused import FusedTrainer, \
            FusedUnsupportedError

        try:
            trainer = FusedTrainer(workflow)
        except FusedUnsupportedError as exc:    # e.g. tied weights
            workflow.warning(
                "--fused requested but the fused path cannot run this "
                "graph (%s); falling back to the unit engine", exc)
            workflow.run()
            return
        trainer.run()
    else:
        workflow.run()
