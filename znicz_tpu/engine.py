"""Engine selection for a built workflow: the unit-at-a-time graph engine
(reference execution semantics, ``Workflow.run``) vs the fused SPMD fast
path (``znicz_tpu/parallel/fused.py``), chosen by
``root.common.engine.fused`` — the launcher's ``--fused`` flag.

The fused path requires the StandardWorkflow graph shape (forwards / gds /
loader / decision) and no tied weights; anything else (Kohonen, RBM,
hand-wired graphs) falls back to the unit engine automatically.
"""

from __future__ import annotations

from znicz_tpu.core.config import root


def wants_fused() -> bool:
    return bool(root.common.engine.get("fused", False))


def train(workflow) -> None:
    """Train ``workflow`` with the configured engine."""
    if wants_fused() and all(
            getattr(workflow, a, None) is not None
            for a in ("forwards", "gds", "loader", "decision")):
        from znicz_tpu.parallel.fused import FusedTrainer

        try:
            trainer = FusedTrainer(workflow)
        except ValueError:          # e.g. tied weights -> unit path
            workflow.run()
            return
        trainer.run()
    else:
        workflow.run()
