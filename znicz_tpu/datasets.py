"""Deterministic procedural datasets.

This environment has no network and no MNIST/CIFAR files on disk, so the
sample workflows (SURVEY.md §6, BASELINE configs) run on procedurally
generated stand-ins with the same shapes and difficulty profile:

  - ``digits(...)``  — 28x28 grayscale "MNIST": 10 glyph classes rendered
    from a 5x7 bitmap font with random shift, scale jitter and noise.
  - ``tinyimages(...)`` — 32x32x3 "CIFAR": 10 classes of parametric textures
    (oriented gradients/blobs) with noise.

Everything derives from the seeded ``prng`` streams, so loss curves are
reproducible run-to-run — the parity property the BASELINE gates check.
Swap in real data by pointing the sample configs' ``data_path`` at .npz
files with arrays ``data``/``labels`` (same layout).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from znicz_tpu.core import prng

# 5x7 digit font (rows of 5 bits, 0..9).
_FONT = {
    0: ("01110", "10001", "10011", "10101", "11001", "10001", "01110"),
    1: ("00100", "01100", "00100", "00100", "00100", "00100", "01110"),
    2: ("01110", "10001", "00001", "00110", "01000", "10000", "11111"),
    3: ("01110", "10001", "00001", "00110", "00001", "10001", "01110"),
    4: ("00010", "00110", "01010", "10010", "11111", "00010", "00010"),
    5: ("11111", "10000", "11110", "00001", "00001", "10001", "01110"),
    6: ("01110", "10000", "11110", "10001", "10001", "10001", "01110"),
    7: ("11111", "00001", "00010", "00100", "01000", "01000", "01000"),
    8: ("01110", "10001", "10001", "01110", "10001", "10001", "01110"),
    9: ("01110", "10001", "10001", "01111", "00001", "00001", "01110"),
}


def _glyph(digit: int) -> np.ndarray:
    rows = _FONT[digit]
    return np.array([[float(c) for c in row] for row in rows], np.float32)


def digits(n: int, *, size: int = 28, noise: float = 0.15, jitter: int = 2,
           stream: str = "dataset.digits") -> Tuple[np.ndarray, np.ndarray]:
    """n samples of (size, size) float32 in [0,1] + int32 labels.
    Glyphs are roughly centered with ±jitter px shift (like real MNIST);
    full-range translation would make the task position-only and unlearnable
    for the MLP samples."""
    gen = prng.get(stream)
    rng = gen.state
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    data = np.zeros((n, size, size), np.float32)
    for i in range(n):
        g = _glyph(int(labels[i]))
        scale = int(rng.integers(2, 4))                 # 2x or 3x upscale
        big = np.kron(g, np.ones((scale, scale), np.float32))
        h, w = big.shape
        cr, cc = (size - h) // 2, (size - w) // 2
        r = int(np.clip(cr + rng.integers(-jitter, jitter + 1),
                        0, size - h))
        c = int(np.clip(cc + rng.integers(-jitter, jitter + 1),
                        0, size - w))
        img = np.zeros((size, size), np.float32)
        img[r:r + h, c:c + w] = big * float(rng.uniform(0.6, 1.0))
        img += rng.normal(0.0, noise, size=(size, size)).astype(np.float32)
        data[i] = np.clip(img, 0.0, 1.0)
    return data, labels


def tinyimages(n: int, *, size: int = 32, noise: float = 0.25,
               stream: str = "dataset.tiny") -> Tuple[np.ndarray, np.ndarray]:
    """n samples of (size, size, 3) float32 in [0,1] + int32 labels.
    Classes are parametric textures: oriented sinusoid gratings (0-4) and
    gaussian blobs at class-coded positions (5-9).

    Difficulty tier r3 (VERDICT r2 weak #2 — the old tier triple-coded
    every class in angle+frequency+color / position+channel+width, so the
    CIFAR conv net hit 0.0% valid err and regressions were invisible):
    each class now carries exactly ONE reliable cue (grating angle, blob
    position) with overlapping jitter; color, frequency, channel and blob
    width are random nuisances; every image also gets a faint random
    distractor grating plus heavier pixel noise."""
    gen = prng.get(stream)
    rng = gen.state
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    data = np.zeros((n, size, size, 3), np.float32)
    for i in range(n):
        k = int(labels[i])
        img = np.zeros((size, size, 3), np.float32)
        phase = float(rng.uniform(0, 2 * np.pi))
        if k < 5:
            # the only reliable cue: orientation (36deg apart, 7deg jitter)
            angle = k * np.pi / 5 + float(rng.normal(0, 0.10))
            freq = float(rng.uniform(3.0, 6.0))          # nuisance
            wave = 0.5 + 0.5 * np.sin(
                2 * np.pi * freq * (xx * np.cos(angle) + yy * np.sin(angle))
                + phase)
            color = rng.uniform(0.5, 1.0, 3).astype(np.float32)  # nuisance
            img = wave[..., None] * color
        else:
            # the only reliable cue: blob position (with overlap jitter)
            cx = 0.25 + 0.125 * (k - 5) + float(rng.normal(0, 0.04))
            cy = 0.35 + 0.08 * (k - 5) + float(rng.normal(0, 0.04))
            sigma = float(rng.uniform(0.08, 0.16))       # nuisance
            blob = np.exp(-(np.square(xx - cx) + np.square(yy - cy))
                          / (2 * sigma ** 2))
            chan = int(rng.integers(0, 3))               # nuisance
            img[..., chan] = blob
            img[..., (chan + 1) % 3] = 0.3 * blob
        # faint distractor grating over every image (both class families)
        dang = float(rng.uniform(0, np.pi))
        dfreq = float(rng.uniform(3.0, 6.0))
        dphase = float(rng.uniform(0, 2 * np.pi))
        dist = 0.5 + 0.5 * np.sin(
            2 * np.pi * dfreq * (xx * np.cos(dang) + yy * np.sin(dang))
            + dphase)
        img += 0.10 * dist[..., None] * \
            rng.uniform(0.3, 1.0, 3).astype(np.float32)
        img += rng.normal(0.0, noise, size=img.shape).astype(np.float32)
        data[i] = np.clip(img, 0.0, 1.0)
    return data, labels


def kanji(n: int, *, n_classes: int = 64, size: int = 24,
          noise: float = 0.1, jitter: int = 1,
          stream: str = "dataset.kanji") -> Tuple[np.ndarray, np.ndarray]:
    """n samples of (size, size) float32 + int32 labels over ``n_classes``
    glyph classes — the many-class regime of the reference's Kanji sample.
    Each class is a fixed random composition of stroke segments on a 6x6
    grid (derived deterministically from the class index + global seed);
    samples vary by sub-pixel shift, thickness and noise."""
    gen = prng.get(stream)
    rng = gen.state
    # class structure from a dedicated stream so it is stable regardless
    # of how many samples have been drawn
    cls_rng = prng.get(stream + ".classes").state
    grid = 6
    strokes = []
    for c in range(n_classes):
        segs = []
        for _ in range(int(cls_rng.integers(4, 8))):
            r0 = int(cls_rng.integers(0, grid))
            c0 = int(cls_rng.integers(0, grid))
            horiz = bool(cls_rng.integers(0, 2))
            length = int(cls_rng.integers(2, grid))
            segs.append((r0, c0, horiz, length))
        strokes.append(segs)

    scale = size // grid
    labels = rng.integers(0, n_classes, size=n).astype(np.int32)
    data = np.zeros((n, size, size), np.float32)
    for i in range(n):
        g = np.zeros((grid * scale, grid * scale), np.float32)
        thick = int(rng.integers(1, 3))
        for r0, c0, horiz, length in strokes[int(labels[i])]:
            if horiz:
                r, cs = r0 * scale + scale // 2, slice(
                    c0 * scale, min((c0 + length) * scale, grid * scale))
                g[r:r + thick, cs] = 1.0
            else:
                rs = slice(r0 * scale,
                           min((r0 + length) * scale, grid * scale))
                c = c0 * scale + scale // 2
                g[rs, c:c + thick] = 1.0
        dy = int(rng.integers(-jitter, jitter + 1))
        dx = int(rng.integers(-jitter, jitter + 1))
        img = np.zeros((size, size), np.float32)
        src = g[:size, :size]
        img[max(dy, 0):size + min(dy, 0), max(dx, 0):size + min(dx, 0)] = \
            src[max(-dy, 0):size + min(-dy, 0),
                max(-dx, 0):size + min(-dx, 0)]
        img *= float(rng.uniform(0.7, 1.0))
        img += rng.normal(0.0, noise, img.shape).astype(np.float32)
        data[i] = np.clip(img, 0.0, 1.0)
    return data, labels


def videoframes(n: int, *, size: int = 16, noise: float = 0.05,
                frames_per_clip: int = 8,
                stream: str = "dataset.video") -> Tuple[np.ndarray,
                                                        np.ndarray]:
    """n frames sampled from synthetic clips (the reference's VideoAE
    regime: an autoencoder trained on video frames).  Each clip is a blob
    moving on a linear trajectory with fixed shape/brightness; frames
    within a clip share those statics, so the frame manifold is
    low-dimensional and learnable by a small AE.  Returns (frames,
    clip_ids)."""
    gen = prng.get(stream)
    rng = gen.state
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    data = np.zeros((n, size, size), np.float32)
    clip_ids = np.zeros(n, np.int32)
    i = 0
    clip = 0
    while i < n:
        x0, y0 = rng.uniform(0.2, 0.8, 2)
        vx, vy = rng.uniform(-0.08, 0.08, 2)
        sigma = float(rng.uniform(0.08, 0.15))
        amp = float(rng.uniform(0.6, 1.0))
        for t in range(frames_per_clip):
            if i >= n:
                break
            cx, cy = x0 + vx * t, y0 + vy * t
            img = amp * np.exp(-(np.square(xx - cx) + np.square(yy - cy))
                               / (2 * sigma ** 2))
            img += rng.normal(0.0, noise, img.shape).astype(np.float32)
            data[i] = np.clip(img, 0.0, 1.0)
            clip_ids[i] = clip
            i += 1
        clip += 1
    return data, clip_ids


def load_or_generate(path: Optional[str], generator, *args, **kwargs):
    """If ``path`` exists, load arrays ``data``/``labels`` from the .npz;
    otherwise call the generator (the no-real-data fallback)."""
    if path and os.path.exists(path):
        with np.load(path) as f:
            return (np.asarray(f["data"], np.float32),
                    np.asarray(f["labels"], np.int32))
    return generator(*args, **kwargs)
