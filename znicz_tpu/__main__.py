import sys

from znicz_tpu.launcher import main

sys.exit(main())
