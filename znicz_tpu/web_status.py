"""Web status dashboard (rebuild of ``veles/web_status.py`` + ``veles/web``).

The reference ran a tornado dashboard showing running workflows and the
master/slave topology.  The rebuild serves the same information for the
SPMD world — registered workflows' progress (epoch, metrics, unit timing)
and the device mesh — over a tiny stdlib ThreadingHTTPServer:

    status = WebStatus(port=8080).start()
    status.register(workflow)
    ... train ...
    status.stop()

Endpoints: ``/`` (HTML page, auto-refresh), ``/status.json``,
``/metrics`` (Prometheus text exposition of the process-wide telemetry
registry — ISSUE 5; on a fleet coordinator the same scrape carries
every member's series too, labeled ``member=<origin>`` — ISSUE 20),
``/trace.json`` (the telemetry span ring as Chrome trace-event JSON;
``?fleet=1`` renders the coordinator's STITCHED cross-process timeline
instead, optionally narrowed with ``&trace_id=``), ``/events.json``
(the structured event journal; ``since=<seq>`` cursor, ``?fleet=1``
for the merged fleet journal with its ``mseq`` cursor), ``/slo.json``
(per-plane SLO burn rates and error-budget state), ``/fleet.json``
(the structured fleet rollup: merged metrics, stitched-trace summary,
journal origins, SLO state), and — for a registered inference service
(ISSUE 6) — ``/healthz`` (liveness: 200 while the serve loop runs, 503
once it died) and ``/readyz`` (readiness: 503 while warming a snapshot
rollover or draining — the membership signal the replica tier's health
checks key on; carries the advisory ``slo`` field, which NEVER flips
the gate).

Lock discipline (ISSUE 5 de-flake satellite): the ``/metrics`` and
``/trace.json`` handlers SNAPSHOT the registry/ring into a plain
string/bytes first and only then touch the socket — no registry or
metric lock is ever held across a socket write, so a slow or stalled
scraper cannot stall a training loop that increments counters
(regression test: tests/test_telemetry.py).
"""

from __future__ import annotations

import html
import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional


class WebStatus:
    def __init__(self, port: int = 8080, host: str = "127.0.0.1"):
        self.host = host
        self.port = int(port)
        self.workflows: List[object] = []
        self.server = None                  # optional master (topology)
        self.relays: List[object] = []      # optional relay nodes (tree)
        self.inference = None               # optional inference service
        self.inference_client = None        # optional breaker-side view
        self.balancer = None                # optional replica balancer
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def register(self, workflow) -> None:
        if workflow not in self.workflows:
            self.workflows.append(workflow)

    def register_server(self, server) -> None:
        """Show the master/slave topology (reference dashboard feature)."""
        self.server = server

    def register_relay(self, relay) -> None:
        """Show an aggregation-tree relay node (ISSUE 10): its children,
        upstream, queue/flush state and byte/refusal accounting — the
        tree-topology panel.  Register each co-located relay."""
        if relay not in self.relays:
            self.relays.append(relay)

    def register_inference(self, server) -> None:
        """Show the inference service's serving panel (ISSUE 4): qps,
        latency quantiles, batch occupancy, queue depth, per-bucket hit
        counts, shed/timed-out/bad-frame accounting — plus (ISSUE 6)
        readiness/generation and the per-client admission table; also
        arms ``/healthz`` and ``/readyz``."""
        self.inference = server

    def register_inference_client(self, client) -> None:
        """Show a local InferenceClient's view (ISSUE 6): circuit-
        breaker state, resends/give-ups, in-flight depth."""
        self.inference_client = client

    def register_balancer(self, balancer) -> None:
        """Show a replica balancer's fleet panel (ISSUE 12): per-
        replica generation/p99/in-flight/last-heartbeat-age rows, the
        exactly-once ledger, hedging and rollover state — and make
        ``/readyz`` answer the FLEET AGGREGATE (``ready_replicas`` /
        ``total``, 503 below the ``min_replicas`` quorum, mirroring
        PR 10's training quorum) instead of any single process."""
        self.balancer = balancer

    # -- snapshotting the state (host side, lock-free reads) -------------------

    def snapshot(self) -> dict:
        from znicz_tpu.decision import DecisionBase

        out = {"workflows": []}
        try:
            import jax

            out["devices"] = [str(d) for d in jax.devices()]
        except Exception as exc:       # no backend reachable: degrade visibly
            logging.getLogger("web_status").warning(
                "device enumeration failed: %r", exc)
            # STRUCTURED degradation (ISSUE 5 satellite): a consumer can
            # tell "no devices enumerable (why)" from "zero devices" —
            # the bare [] used to swallow the failure reason entirely
            out["devices"] = {"error": f"{type(exc).__name__}: {exc}",
                              "devices": []}
        for wf in self.workflows:
            info = {"name": wf.name, "stopped": bool(wf.stopped),
                    "units": [{"name": u.name, "runs": u.run_count}
                              for u in wf.units if u.run_count]}
            fused = getattr(wf, "fused_stats", None)
            if fused and fused.get("wall_s"):
                info["fused"] = dict(fused)
            for u in wf.units:
                if isinstance(u, DecisionBase):
                    info["epoch"] = int(u.epoch_number)
                    info["best_metric"] = (None if u.best_metric != u.best_metric
                                           or u.best_metric == float("inf")
                                           else float(u.best_metric))
                    info["complete"] = bool(u.complete)
            out["workflows"].append(info)
        if self.server is not None:
            import time as _time

            now = _time.time()
            srv = self.server
            # C-level copies: the serve thread mutates these concurrently
            # (evictions pop, updates append) and iterating the live
            # structures from this HTTP thread could raise mid-request
            live = dict(srv.slaves)
            dead = dict(srv.dead_slaves)
            jobs_by_slave = dict(srv.jobs_by_slave)
            from znicz_tpu.network_common import PROTOCOL_VERSION

            ratio = srv.compression_ratio()
            bpu = srv.bytes_per_update()
            out["master"] = {
                "endpoint": srv.endpoint,
                "protocol_version": PROTOCOL_VERSION,
                "jobs_done": srv.jobs_done,
                "jobs_requeued": srv.jobs_requeued,
                "stale_updates": srv.stale_updates,
                # wire-v3 traffic counters (ISSUE 3):
                "bytes_in": srv.bytes_in,
                "bytes_out": srv.bytes_out,
                "updates_received": srv.updates_received,
                "update_bytes_in": srv.update_bytes_in,
                "bytes_per_update": None if bpu is None else round(bpu, 1),
                "compression_ratio": None if ratio is None
                else round(ratio, 3),
                "prefetch_hit": srv.prefetch_hit,
                "wire_compress": srv.wire_compress,
                # robustness counters (fault model, README):
                "bad_updates": srv.bad_updates,
                "bad_frames": srv.bad_frames,
                "quarantined_updates": srv.quarantined_updates,
                "reregistrations": srv.reregistrations,
                # unified transport core (ISSUE 14): per-slave ingress
                # admission — additive key, historical names unchanged
                "rate_limited_ingress": srv.rate_limited_ingress,
                "resumed": bool(srv.resumed),
                "resume_saves": srv.resume_saves,
                "job_timeout_s": round(srv.effective_job_timeout(), 3),
                "aggregated_updates": srv.aggregated_updates,
                # elastic async training (ISSUE 11): quorum state,
                # staleness policy + per-leaf histograms, re-planner
                "elastic": {
                    "min_slaves": srv.min_slaves,
                    "members": srv.member_count(),
                    "degraded": bool(srv.degraded()),
                    "apply_step": srv.apply_step,
                    "staleness_bound": srv.staleness_bound,
                    "staleness_weight": bool(srv.staleness_weight),
                    "stale_refused": srv.stale_refused,
                    "weighted_applies": srv.weighted_applies,
                    "replans": srv.replans,
                    "preemptions_ridden": srv.preemptions_ridden,
                    "staleness_by_leaf": srv.staleness_summary(),
                    "tree_plan": srv.tree_plan,
                },
                "slaves": [
                    {"id": sid,
                     "jobs": jobs_by_slave.get(sid, 0),
                     "last_seen_s": round(now - seen, 1),
                     # tree topology (ISSUE 10): direct children that
                     # are relays, not leaf slaves
                     "relay": sid in srv.relays,
                     # pod-sliced leaves (ISSUE 18) advertise their
                     # mesh shape on register; None = single-device
                     "mesh": srv.slave_meshes.get(sid)}
                    for sid, seen in sorted(live.items())],
                # leaf slaves working BEHIND relays: attributed in
                # jobs_by_slave (contributor manifests) but never
                # direct members (iterated from the copy above — the
                # serve thread mutates the live dict concurrently)
                "leaves": [
                    {"id": sid, "jobs": n}
                    for sid, n in sorted(jobs_by_slave.items())
                    if sid not in live and sid not in dead],
                # evicted-but-remembered membership (their job history
                # survives for the final report)
                "dead_slaves": [
                    {"id": sid,
                     "jobs": jobs_by_slave.get(sid, 0),
                     "last_seen_s": round(now - seen, 1)}
                    for sid, seen in sorted(dead.items())],
            }
        if self.relays:
            # each stats() assembles under the relay's own lock — safe
            # from this HTTP thread while the relays serve
            out["relays"] = [r.stats() for r in self.relays]
        if self.inference is not None:
            # stats() assembles from plain counters — safe to call from
            # this HTTP thread while the service runs
            out["serving"] = self.inference.stats()
        if self.balancer is not None:
            # assembles under the balancer's own lock — safe from this
            # HTTP thread while the fleet serves
            out["balancer"] = self.balancer.stats()
        if self.inference_client is not None:
            c = self.inference_client
            out["serving_client"] = {
                "endpoint": c.endpoint,
                "breaker": c.breaker_state,
                "in_flight": c.in_flight,
                "resends": c.resends,
                "give_ups": c.give_ups,
                "errors": c.errors,
                "bad_replies": c.bad_replies,
                "breaker_opens": c.breaker_opens,
                "breaker_short_circuits": c.breaker_short_circuits,
                # per-endpoint windows behind a balancer (ISSUE 12)
                "replica_breakers": c.replica_breakers(),
            }
        return out

    def health(self) -> dict:
        """The ``/healthz`` body: liveness of the registered inference
        service (no service registered = the process itself answers,
        which is liveness enough)."""
        if self.balancer is not None:
            return {"ok": bool(self.balancer.alive())}
        inf = self.inference
        alive = True if inf is None else bool(inf.alive())
        return {"ok": alive}

    def readiness(self) -> dict:
        """The ``/readyz`` body: with a BALANCER registered (ISSUE 12)
        the answer is the FLEET AGGREGATE — ``ready_replicas/total``
        with 503 below the ``min_replicas`` quorum (the old per-process
        answer said nothing about whether the fleet could serve);
        otherwise ready iff a registered inference service is up,
        warmed, not mid-rollover and not draining — or, with only a
        training MASTER registered (ISSUE 11), iff its elastic quorum
        is met (503 while degraded is the membership signal an
        operator's dashboards key on during preemptions)."""
        bal = self.balancer
        if bal is not None:
            ready = bal.ready_count()
            total = bal.member_count()
            if not bal.alive():
                return {"ready": False,
                        "reason": "dead (balancer loop exited)",
                        "ready_replicas": ready, "total": total,
                        "min_replicas": bal.min_replicas}
            if bal.degraded():
                return {"ready": False,
                        "reason": f"degraded: {ready}/{total} replicas "
                                  f"ready, below the min_replicas "
                                  f"quorum ({bal.min_replicas})",
                        "ready_replicas": ready, "total": total,
                        "min_replicas": bal.min_replicas}
            return {"ready": True, "reason": "ok",
                    "ready_replicas": ready, "total": total,
                    "min_replicas": bal.min_replicas}
        inf = self.inference
        if inf is None:
            srv = self.server
            if srv is not None:
                members = srv.member_count()
                if srv.degraded():
                    return {"ready": False,
                            "reason": f"degraded: {members} members "
                                      f"below the min_slaves quorum "
                                      f"({srv.min_slaves})",
                            "members": members,
                            "min_slaves": srv.min_slaves}
                return {"ready": True, "reason": "ok",
                        "members": members,
                        "min_slaves": srv.min_slaves}
            return {"ready": False,
                    "reason": "no inference service registered"}
        if inf.ready():
            return {"ready": True, "reason": "ok",
                    "generation": inf.runner.generation}
        if not inf.alive():
            # a crashed loop must not masquerade as "starting": an
            # operator would wait out a warmup that never ends
            reason = "dead (serve loop exited — see /healthz)"
        elif inf.draining:
            reason = "draining"
        elif inf.runner.swapping:
            reason = "warming (snapshot rollover in progress)"
        else:
            reason = "starting (warmup in progress)"
        return {"ready": False, "reason": reason,
                "generation": inf.runner.generation}

    # -- server ----------------------------------------------------------------

    def _make_handler(self):
        status = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):       # silence request logging
                pass

            def _query(self):
                parsed = urllib.parse.urlsplit(self.path)
                return {k: v[-1] for k, v in
                        urllib.parse.parse_qs(parsed.query).items()}

            def do_GET(self):
                code = 200
                if self.path.startswith("/healthz"):
                    # liveness (ISSUE 6): 503 tells a supervisor to
                    # restart the process
                    health = status.health()
                    code = 200 if health["ok"] else 503
                    body = json.dumps(health).encode()
                    ctype = "application/json"
                elif self.path.startswith("/readyz"):
                    # readiness: 503 while warming/draining pulls this
                    # replica out of a load balancer WITHOUT killing it
                    from znicz_tpu import telemetry

                    ready = status.readiness()
                    # ADVISORY SLO state (ISSUE 20): surfaced for
                    # operators/dashboards, NEVER part of the gate —
                    # the 200/503 decision above this line is untouched
                    ready["slo"] = telemetry.slo_snapshot()["state"]
                    code = 200 if ready["ready"] else 503
                    body = json.dumps(ready).encode()
                    ctype = "application/json"
                elif self.path.startswith("/status.json"):
                    body = json.dumps(status.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    # Prometheus text exposition (ISSUE 5).  render
                    # returns a COMPLETE string — the socket write below
                    # happens with no registry lock held.  A coordinator
                    # holding member snapshots (ISSUE 20) renders the
                    # fleet SUPERSET: local series byte-identical, member
                    # series appended under the same families with a
                    # member=<origin> label
                    from znicz_tpu import telemetry

                    store = telemetry.fleet_metrics()
                    if store.members():
                        body = telemetry.render_fleet_prometheus(
                            telemetry.registry(), store).encode()
                    else:
                        body = telemetry.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/trace.json"):
                    # Chrome trace-event JSON of the span ring (open in
                    # Perfetto); same snapshot-then-write discipline.
                    # ?fleet=1 (ISSUE 20): the coordinator's stitched
                    # cross-process timeline instead (&trace_id= narrows
                    # to one request/job)
                    from znicz_tpu import telemetry

                    q = self._query()
                    if q.get("fleet"):
                        trace = telemetry.fleet_trace().chrome_trace(
                            trace_id=q.get("trace_id"))
                    else:
                        trace = telemetry.chrome_trace()
                    body = json.dumps(trace).encode()
                    ctype = "application/json"
                elif self.path.startswith("/events.json"):
                    # the structured event journal (ISSUE 20): bounded,
                    # seq-cursorable; ?fleet=1 serves the coordinator's
                    # merged journal on its own mseq cursor
                    from znicz_tpu import telemetry

                    q = self._query()
                    try:
                        since = int(q.get("since", 0))
                    except ValueError:
                        since = 0
                    if q.get("fleet"):
                        store = telemetry.fleet_events()
                        payload = {"fleet": True,
                                   "last_mseq": store.snapshot()["last_mseq"],
                                   "events": store.since(since)}
                    else:
                        j = telemetry.journal()
                        payload = {"origin": j.origin,
                                   "last_seq": j.last_seq,
                                   "dropped": j.dropped,
                                   "events": j.since(since)}
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                elif self.path.startswith("/slo.json"):
                    # per-plane SLO burn rates / error-budget state
                    from znicz_tpu import telemetry

                    body = json.dumps(telemetry.slo_snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/fleet.json"):
                    # the structured fleet rollup (ISSUE 20)
                    from znicz_tpu import telemetry

                    ev = telemetry.fleet_events().snapshot()
                    body = json.dumps({
                        "metrics": telemetry.fleet_metrics().rollup(),
                        "trace": telemetry.fleet_trace().snapshot(),
                        "events": {"last_mseq": ev["last_mseq"],
                                   "origins": ev["origins"]},
                        "slo": telemetry.slo_snapshot(),
                    }).encode()
                    ctype = "application/json"
                else:
                    snap = status.snapshot()
                    rows = "".join(
                        f"<tr><td>{html.escape(w['name'])}</td>"
                        f"<td>{w.get('epoch', '-')}</td>"
                        f"<td>{w.get('best_metric', '-')}</td>"
                        f"<td>{'done' if w.get('complete') else 'running'}"
                        f"</td></tr>"
                        for w in snap["workflows"])
                    master_html = ""
                    master = snap.get("master")
                    if master:
                        ela = master.get("elastic", {})
                        stale_rows = "".join(
                            f"<tr><td>{html.escape(leaf)}</td>"
                            f"<td>{st['count']}</td><td>{st['p50']}</td>"
                            f"<td>{st['max']}</td></tr>"
                            for leaf, st in sorted(
                                ela.get("staleness_by_leaf",
                                        {}).items()))
                        elastic_html = (
                            "<p>elastic: "
                            f"{'DEGRADED' if ela.get('degraded') else 'ok'}"
                            f", members {ela.get('members')}"
                            f"/{ela.get('min_slaves')} min, apply step "
                            f"{ela.get('apply_step')}, staleness bound "
                            f"{ela.get('staleness_bound')}"
                            f" (weighting "
                            f"{'on' if ela.get('staleness_weight') else 'off'}"
                            f"), stale refused {ela.get('stale_refused')}"
                            f", weighted applies "
                            f"{ela.get('weighted_applies')}, re-plans "
                            f"{ela.get('replans')}, preemptions ridden "
                            f"{ela.get('preemptions_ridden')}</p>")
                        if stale_rows:
                            elastic_html += (
                                "<table border=1><tr><th>leaf</th>"
                                "<th>staleness n</th><th>p50</th>"
                                f"<th>max</th></tr>{stale_rows}</table>")
                        srows = "".join(
                            f"<tr><td>{html.escape(s['id'])}"
                            f"{' (relay)' if s.get('relay') else ''}"
                            f"</td><td>{s['jobs']}</td>"
                            f"<td>{s['last_seen_s']}s ago</td>"
                            # pod-sliced leaves (ISSUE 18) show their
                            # slice, e.g. "data=4 x model=2"
                            f"<td>{'x'.join(f'{k}={v}' for k, v in s['mesh'].items()) if s.get('mesh') else 'single-device'}"
                            "</td></tr>"
                            for s in master["slaves"])
                        master_html = (
                            f"<h2>Master {html.escape(master['endpoint'])}"
                            f"</h2><p>jobs done: {master['jobs_done']}, "
                            f"re-queued: {master['jobs_requeued']}, stale "
                            f"updates: {master['stale_updates']}, bad "
                            f"frames: {master['bad_frames']}, quarantined: "
                            f"{master['quarantined_updates']}, reconnects: "
                            f"{master['reregistrations']}, job timeout: "
                            f"{master['job_timeout_s']}s"
                            f"{', RESUMED' if master['resumed'] else ''}"
                            "</p>"
                            f"<p>wire v{master['protocol_version']}: "
                            f"{master['bytes_in']} B in / "
                            f"{master['bytes_out']} B out, "
                            f"bytes/update: {master['bytes_per_update']}, "
                            "compression ratio: "
                            f"{master['compression_ratio']}, prefetch "
                            f"hits: {master['prefetch_hit']}</p>"
                            f"{elastic_html}"
                            "<table border=1><tr><th>slave</th><th>jobs"
                            "</th><th>last seen</th><th>mesh</th></tr>"
                            f"{srows}</table>"
                            f"<p>dead slaves: {len(master['dead_slaves'])}"
                            f", aggregated updates: "
                            f"{master.get('aggregated_updates', 0)}, "
                            "leaves behind relays: "
                            f"{len(master.get('leaves', []))}</p>")
                    relays_html = ""
                    for r in snap.get("relays", []):
                        # the tree-topology panel (ISSUE 10): one box
                        # per co-located relay, children indented under
                        # their upstream edge
                        crows = "".join(
                            f"<tr><td>{html.escape(c['id'])}</td>"
                            f"<td>{c['last_seen_s']}s ago</td></tr>"
                            for c in r["children"])
                        relays_html += (
                            f"<h2>Relay {html.escape(r['id'])}</h2>"
                            f"<p>{html.escape(r['bind'])} &rarr; "
                            f"upstream {html.escape(r['upstream'])}, "
                            f"fanout {r['fanout']}, wire "
                            f"{r['wire_dtype']}"
                            f"{', DONE' if r['complete'] else ''}</p>"
                            f"<p>flushes: {r['flushes']}, contributions: "
                            f"{r['contributions']}, refusals: "
                            f"{r['refusals']}, jobs served: "
                            f"{r['jobs_served']}, queue: "
                            f"{r['queue_depth']}, buffered: "
                            f"{r['buffered_contributions']}, bytes "
                            f"{r['bytes_in']} in / {r['bytes_out']} out, "
                            f"bad frames: {r['bad_frames']}, upstream "
                            f"reconnects: {r['upstream_reconnects']}</p>"
                            "<table border=1><tr><th>child</th>"
                            f"<th>last seen</th></tr>{crows}</table>")
                    serving_html = ""
                    serving = snap.get("serving")
                    if serving:
                        b = serving["batcher"]
                        m = serving["model"]
                        adm = b.get("admission", {})
                        pad = b.get("pad_ratio", {})

                        def _bucket_order(kv):
                            # numeric (rows, seq) order: plain int rungs
                            # (1-D) and "RxS" keys (2-D) both parse —
                            # lexicographic order shuffled 16 before 2
                            return tuple(int(p) for p in
                                         str(kv[0]).split("x"))

                        brows = "".join(
                            f"<tr><td>{r}</td><td>{n}</td>"
                            f"<td>{pad.get(r, '-')}</td></tr>"
                            for r, n in sorted(b["bucket_hits"].items(),
                                               key=_bucket_order))
                        state = ("DRAINING" if serving.get("draining")
                                 else "ready" if serving.get("ready")
                                 else "warming")
                        mesh = m.get("mesh")
                        mesh_text = ("single-device" if not mesh
                                     else "x".join(
                                         f"{k}={v}"
                                         for k, v in mesh.items())
                                     + f" ({m.get('device_count')} "
                                       "devices)")
                        crows = "".join(
                            f"<tr><td>{html.escape(cid)}</td>"
                            f"<td>{c['accepted']}</td>"
                            f"<td>{c['rate_limited']}</td>"
                            f"<td>{c['shed']}</td></tr>"
                            for cid, c in sorted(
                                adm.get("clients", {}).items()))
                        serving_html = (
                            "<h2>Serving "
                            f"{html.escape(str(serving['endpoint']))}</h2>"
                            f"<p>state: {state}, snapshot generation: "
                            f"{serving['generation']}"
                            f"{' (swapping)' if m.get('swapping') else ''}"
                            f", swaps: {m.get('swaps')}, mesh: "
                            f"{html.escape(mesh_text)}</p>"
                            f"<p>qps: {serving['qps']}, p50: "
                            f"{serving['p50_ms']} ms, p99: "
                            f"{serving['p99_ms']} ms, served: "
                            f"{serving['served']}, rejected: "
                            f"{serving['rejected']}, timed out: "
                            f"{serving['timed_out']}, expired results: "
                            f"{serving['expired_results']}, bad frames: "
                            f"{serving['bad_frames']}</p>"
                            f"<p>batcher: occupancy "
                            f"{b['mean_occupancy']}, queue depth "
                            f"{b['queue_depth']}/{b['queue_bound']} rows, "
                            f"shed {b['shed']}, max_batch "
                            f"{b['max_batch']}, max_delay "
                            f"{b['max_delay_ms']} ms, padded cells "
                            f"{b.get('padded_cells', 0)} / real "
                            f"{b.get('real_cells', 0)}"
                            + (f", seq rungs {b['seq_rungs']}"
                               if b.get('seq_rungs') else "")
                            + f"; jit compiles "
                            f"{m['compiles']} (cache "
                            f"{m['jit_cache_size']})</p>"
                            f"<p>admission: "
                            f"{'on' if adm.get('enabled') else 'off'}, "
                            f"rate limit "
                            f"{adm.get('rate_limit_rows_per_s')} rows/s, "
                            f"fair: {adm.get('fair')}, rate_limited: "
                            f"{adm.get('rate_limited')}, active clients: "
                            f"{adm.get('active_clients')}</p>"
                            "<table border=1><tr><th>client</th>"
                            "<th>accepted</th><th>rate_limited</th>"
                            f"<th>shed</th></tr>{crows}</table>"
                            "<table border=1><tr><th>bucket</th>"
                            "<th>hits</th><th>pad_ratio</th></tr>"
                            f"{brows}</table>")
                        gen = serving.get("generate")
                        if gen:
                            # the generation rows (ISSUE 16/19):
                            # continuous-batching health — decode
                            # cadence, paged-pool occupancy, prefill/
                            # decode split — plus the prefix/paging row
                            # (shared pages, COW traffic, avoided work)
                            serving_html += (
                                f"<p>generation: active {gen['active']}, "
                                f"pending {gen['pending']}, KV pages "
                                f"{gen['pages_active']}/"
                                f"{gen['num_pages']} "
                                f"(leaked {gen['pages_leaked']}), "
                                f"inter-token p50 "
                                f"{gen['inter_token_p50_ms']} ms / p99 "
                                f"{gen['inter_token_p99_ms']} ms; "
                                f"tokens {gen['generated_tokens']} "
                                f"(prefill {gen['prefill_batches']} "
                                f"chunks / {gen['prefill_tokens']} "
                                f"tokens, decode {gen['decode_batches']} "
                                f"ticks / {gen['decode_tokens']} tokens), "
                                f"finished {gen['gen_finished']}, "
                                f"truncated {gen['gen_truncated']}, "
                                f"timed out {gen['gen_timed_out']}</p>"
                                f"<p>paging: page size {gen['page_size']}"
                                f", prefill chunk {gen['prefill_chunk']}"
                                f", prefix cache "
                                f"{'on' if gen['prefix_enabled'] else 'off'}"
                                f" ({gen['prefix_pages']} pages indexed, "
                                f"{gen['pages_shared']} shared, "
                                f"{gen['prefix_hits']} hits / "
                                f"{gen['prefix_misses']} misses, "
                                f"{gen['prefix_tokens_avoided']} prompt "
                                f"tokens avoided), "
                                f"COW copies {gen['cow_copies']}, "
                                f"on-device sampling "
                                f"{'on' if gen['on_device_sampling'] else 'off'}"
                                f" ({gen['fetch_bytes']} B fetched)</p>")
                            if "ttft_p50_ms" in gen:
                                # TTFT + queue-wait vs compute split
                                # (ISSUE 20): the user-facing latency
                                # decomposition per generation request
                                serving_html += (
                                    f"<p>TTFT p50 {gen['ttft_p50_ms']} ms"
                                    f" / p99 {gen['ttft_p99_ms']} ms "
                                    f"(queue-wait p50 "
                                    f"{gen['queue_wait_p50_ms']} ms / p99 "
                                    f"{gen['queue_wait_p99_ms']} ms, "
                                    f"compute p50 "
                                    f"{gen['compute_p50_ms']} ms / p99 "
                                    f"{gen['compute_p99_ms']} ms)</p>")
                        slow = serving.get("slow_requests")
                        if slow:
                            # slow-request exemplars (ISSUE 20): the N
                            # slowest requests of the window, named —
                            # a p99 regression with req/trace ids
                            xrows = "".join(
                                f"<tr><td>{html.escape(str(x['req_id']))}"
                                f"</td>"
                                f"<td>{html.escape(str(x.get('trace_id') or '-'))}</td>"
                                f"<td>{x['latency_ms']}</td>"
                                f"<td>{html.escape(str(x.get('bucket') or '-'))}</td>"
                                f"<td>{html.escape(str(x.get('kind') or '-'))}</td>"
                                f"<td>{html.escape(json.dumps(x.get('breakdown_ms')) if x.get('breakdown_ms') else '-')}</td></tr>"
                                for x in slow)
                            serving_html += (
                                "<h3>Slowest requests (window)</h3>"
                                "<table border=1><tr><th>req</th>"
                                "<th>trace</th><th>ms</th><th>bucket</th>"
                                "<th>kind</th><th>breakdown ms</th></tr>"
                                f"{xrows}</table>")
                    bal = snap.get("balancer")
                    if bal:
                        # the fleet panel (ISSUE 12): one row per
                        # replica — gen, p99 (top bucket), in-flight,
                        # last-heartbeat age, rotation state
                        led = bal["ledger"]
                        frows = "".join(
                            f"<tr><td>{html.escape(r['replica_id'])}"
                            f"{'' if r['in_rotation'] else ' (warming)'}"
                            f"{' (retiring)' if r.get('retiring') else ''}"
                            f"{' (healing)' if r.get('healing') else ''}"
                            f"</td><td>{'ready' if r['ready'] else 'NOT'}"
                            f"</td><td>{r['gen']}</td>"
                            # the mesh column (ISSUE 13): capacity-
                            # weighted dispatch divides load by this
                            f"<td>{html.escape('x'.join(str(v) for v in r['mesh'].values()) if r.get('mesh') else '1')}"
                            f" ({r.get('device_count', 1)}d)</td>"
                            # warm provenance (ISSUE 17): where this
                            # replica's executables came from + its
                            # boot-to-ready — the elasticity columns
                            f"<td>{html.escape(str(r.get('warm_source') or '-'))}"
                            f" {r.get('warm_hits', 0)}/"
                            f"{r.get('warm_misses', 0)}"
                            f"{' (%.2fs boot)' % r['boot_s'] if isinstance(r.get('boot_s'), (int, float)) else ''}"
                            f"</td>"
                            f"<td>{max(r['p99_ms_by_bucket'].values()) if r['p99_ms_by_bucket'] else '-'}"
                            f"</td><td>{r['in_flight']}</td>"
                            f"<td>{r['last_heartbeat_s']}s ago</td></tr>"
                            for r in bal["replicas"])
                        asc = bal.get("autoscale") or {}
                        asc_html = ""
                        if asc.get("enabled"):
                            # autoscale summary (ISSUE 17): band state
                            # + lifetime action counts
                            asc_html = (
                                f"<p>autoscale: {asc['servable']} "
                                f"servable (max {asc['max']}), pending "
                                f"spawns {asc['pending_spawns']}, "
                                f"retiring {asc['retiring']}, "
                                f"scale-ups {bal.get('scale_ups', 0)}, "
                                f"scale-downs "
                                f"{bal.get('scale_downs', 0)}</p>")
                        roll = bal.get("rollover")
                        roll_html = ""
                        if roll:
                            roll_html = (
                                f"<p>rollover: phase {roll['phase']} "
                                f"-> {html.escape(str(roll['path']))}, "
                                f"canary {roll['canary']}, samples "
                                f"{roll['canary_samples']}, parity "
                                f"mismatches "
                                f"{roll['parity_mismatches']}</p>")
                        serving_html += (
                            "<h2>Replica fleet "
                            f"{html.escape(str(bal['endpoint']))}</h2>"
                            f"<p>{'DEGRADED' if bal['degraded'] else 'ok'}"
                            f": {bal['ready_replicas']}/"
                            f"{bal['total_replicas']} ready "
                            f"(quorum {bal['min_replicas']}); ledger "
                            f"accepted {led['accepted']} = replied "
                            f"{led['replied']} + refused "
                            f"{led['refused']} + in-flight "
                            f"{led['in_flight']} "
                            f"({'BALANCED' if led['balanced'] else 'LEAK'})"
                            f"</p><p>failovers: {bal['failovers']}, "
                            f"hedges: {bal['hedges']} (wins "
                            f"{bal['hedge_wins']}), dups dropped: "
                            f"{bal['dup_replies_dropped']}, heals: "
                            f"{bal['heals']}, rollovers: "
                            f"{bal['rollovers']}, rollbacks: "
                            f"{bal['rollbacks']}, hedge delay: "
                            f"{bal['hedge_delay_ms']} ms</p>"
                            f"{asc_html}"
                            f"{roll_html}"
                            "<table border=1><tr><th>replica</th>"
                            "<th>ready</th><th>gen</th><th>mesh</th>"
                            "<th>warm (hit/miss)</th>"
                            "<th>p99 ms</th>"
                            "<th>in-flight</th><th>heartbeat</th></tr>"
                            f"{frows}</table>")
                    cli = snap.get("serving_client")
                    if cli:
                        serving_html += (
                            f"<p>client breaker: {cli['breaker']}, "
                            f"in flight: {cli['in_flight']}, resends: "
                            f"{cli['resends']}, give-ups: "
                            f"{cli['give_ups']}, opens: "
                            f"{cli['breaker_opens']}, short-circuits: "
                            f"{cli['breaker_short_circuits']}</p>")
                        rb = cli.get("replica_breakers") or {}
                        if rb:
                            serving_html += "<p>per-endpoint: " + ", ".join(
                                f"{html.escape(r)}={s['state']}"
                                f"({s['failures']}/{s['window']})"
                                for r, s in sorted(rb.items())) + "</p>"
                    # fleet observability panel (ISSUE 20): SLO
                    # error-budget state + the journal tail — the
                    # "why did the fleet do X" answer, on the page
                    from znicz_tpu import telemetry

                    obs_html = ""
                    slo = telemetry.slo_snapshot()
                    if slo["planes"]:
                        orows = "".join(
                            f"<tr><td>{html.escape(plane)}</td>"
                            f"<td>{html.escape(name)}</td>"
                            f"<td>{o['target']}</td>"
                            f"<td>{'-' if o['fast_burn'] is None else round(o['fast_burn'], 3)}</td>"
                            f"<td>{'-' if o['slow_burn'] is None else round(o['slow_burn'], 3)}</td>"
                            f"<td>{round(o['budget_remaining'], 3)}</td>"
                            f"<td>{html.escape(o['state'])}</td></tr>"
                            for plane, p in sorted(slo["planes"].items())
                            for name, o in sorted(
                                p["objectives"].items()))
                        obs_html += (
                            f"<h2>SLOs ({html.escape(slo['state'])})</h2>"
                            "<table border=1><tr><th>plane</th>"
                            "<th>objective</th><th>target</th>"
                            "<th>fast burn</th><th>slow burn</th>"
                            "<th>budget left</th><th>state</th></tr>"
                            f"{orows}</table>")
                    tail = telemetry.journal().since(
                        max(0, telemetry.journal().last_seq - 10))
                    if tail:
                        erows = "".join(
                            f"<tr><td>{e['seq']}</td>"
                            f"<td>{html.escape(e['kind'])}</td>"
                            f"<td>{html.escape(e['plane'])}</td>"
                            f"<td>{html.escape(json.dumps({k: v for k, v in e.items() if k not in ('seq', 'ts', 'kind', 'plane', 'origin')}))}"
                            f"</td></tr>"
                            for e in reversed(tail))
                        obs_html += (
                            "<h2>Event journal (latest)</h2>"
                            "<table border=1><tr><th>seq</th>"
                            "<th>kind</th><th>plane</th><th>fields</th>"
                            f"</tr>{erows}</table>")
                    devs = snap["devices"]
                    dev_text = (f"unavailable — {devs['error']}"
                                if isinstance(devs, dict)
                                else ", ".join(devs))
                    body = (
                        "<html><head><meta http-equiv='refresh' content='2'>"
                        "<title>znicz-tpu status</title></head><body>"
                        f"<h2>Devices</h2><p>{html.escape(dev_text)}</p>"
                        "<h2>Workflows</h2><table border=1>"
                        "<tr><th>name</th><th>epoch</th><th>best</th>"
                        f"<th>state</th></tr>{rows}</table>"
                        f"{master_html}{relays_html}{serving_html}"
                        f"{obs_html}"
                        "<p><a href='/metrics'>/metrics</a> "
                        "<a href='/trace.json'>/trace.json</a> "
                        "<a href='/trace.json?fleet=1'>?fleet=1</a> "
                        "<a href='/events.json'>/events.json</a> "
                        "<a href='/slo.json'>/slo.json</a> "
                        "<a href='/fleet.json'>/fleet.json</a> "
                        "<a href='/status.json'>/status.json</a> "
                        "<a href='/healthz'>/healthz</a> "
                        "<a href='/readyz'>/readyz</a></p>"
                        "</body></html>").encode()
                    ctype = "text/html"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Handler

    def start(self) -> "WebStatus":
        self._server = ThreadingHTTPServer((self.host, self.port),
                                           self._make_handler())
        self.port = self._server.server_address[1]   # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
