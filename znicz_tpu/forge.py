"""Model forge (rebuild of ``veles/forge_client.py`` / ``veles/forge``).

The reference's forge was a remote model-repository service (upload/download
packaged workflows over HTTP).  This environment has no egress, so the
rebuild implements the same operations against a LOCAL registry directory
(the on-disk format is self-contained, so pointing ``registry`` at a shared
mount gives the multi-user behavior):

    forge = Forge()                      # root.common.dirs.forge
    name = forge.upload(workflow, "mnist-mlp", metadata={...})
    snap = forge.download("mnist-mlp")   # -> snapshot dict (restore() it)
    forge.list()                         # -> [{"name", "time", ...}, ...]
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import time
from typing import Dict, List, Optional

from znicz_tpu.core.config import root

root.common.dirs.defaults({"forge": "forge_registry"})


class Forge:
    def __init__(self, registry: Optional[str] = None):
        self.registry = registry or root.common.dirs.get("forge",
                                                         "forge_registry")
        os.makedirs(self.registry, exist_ok=True)

    def _pkg_dir(self, name: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)
        if not safe.strip("_"):
            raise ValueError(f"invalid package name {name!r}")
        path = os.path.join(self.registry, safe)
        # belt & braces: never resolve outside the registry
        if not os.path.realpath(path).startswith(
                os.path.realpath(self.registry) + os.sep):
            raise ValueError(f"package name {name!r} escapes the registry")
        return path

    def upload(self, workflow, name: str,
               metadata: Optional[Dict] = None) -> str:
        from znicz_tpu import snapshotter

        d = self._pkg_dir(name)
        os.makedirs(d, exist_ok=True)
        snap = snapshotter.collect(workflow)
        snap["config"] = root.to_dict()
        with gzip.open(os.path.join(d, "model.pickle.gz"), "wb") as f:
            pickle.dump(snap, f, protocol=pickle.HIGHEST_PROTOCOL)
        manifest = {"name": name, "workflow": workflow.name,
                    "time": time.time(),
                    "metadata": metadata or {}}
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        return name

    def download(self, name: str) -> Dict:
        d = self._pkg_dir(name)
        with gzip.open(os.path.join(d, "model.pickle.gz"), "rb") as f:
            return pickle.load(f)

    def manifest(self, name: str) -> Dict:
        with open(os.path.join(self._pkg_dir(name), "manifest.json")) as f:
            return json.load(f)

    def list(self) -> List[Dict]:
        out = []
        for entry in sorted(os.listdir(self.registry)):
            path = os.path.join(self.registry, entry, "manifest.json")
            if os.path.exists(path):
                with open(path) as f:
                    out.append(json.load(f))
        return out

    def delete(self, name: str) -> None:
        import shutil

        d = self._pkg_dir(name)
        if os.path.isdir(d):
            shutil.rmtree(d)
