"""Model forge (rebuild of ``veles/forge_client.py`` / ``veles/forge``).

The reference's forge was a remote model-repository service (upload/download
packaged workflows over HTTP).  The rebuild provides both halves:

  - ``Forge`` — the registry itself: a LOCAL directory of packaged models
    (self-contained on-disk format; a shared mount gives multi-user use);
  - ``ForgeServer`` — serves a registry over HTTP (stdlib
    ThreadingHTTPServer, same approach as web_status);
  - ``RemoteForge`` — the client: the same upload/download/list/delete API
    as ``Forge``, against a server URL.

    forge = Forge()                      # root.common.dirs.forge
    name = forge.upload(workflow, "mnist-mlp", metadata={...})
    snap = forge.download("mnist-mlp")   # -> snapshot dict (restore() it)
    forge.list()                         # -> [{"name", "time", ...}, ...]

    server = ForgeServer(port=8088).start()          # publish a registry
    remote = RemoteForge("http://host:8088")
    remote.upload(workflow, "mnist-mlp")
    snap = remote.download("mnist-mlp")

Trust model: packages are pickles (reference parity — its forge shipped
pickled workflows too).  Only point RemoteForge at a registry you trust;
like GraphicsClient, non-loopback URLs require ``allow_remote=True``.
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import time
from typing import Dict, List, Optional

from znicz_tpu.core.config import root

root.common.dirs.defaults({"forge": "forge_registry"})


class Forge:
    def __init__(self, registry: Optional[str] = None):
        self.registry = registry or root.common.dirs.get("forge",
                                                         "forge_registry")
        os.makedirs(self.registry, exist_ok=True)

    def _pkg_dir(self, name: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)
        if not safe.strip("_"):
            raise ValueError(f"invalid package name {name!r}")
        path = os.path.join(self.registry, safe)
        # belt & braces: never resolve outside the registry
        if not os.path.realpath(path).startswith(
                os.path.realpath(self.registry) + os.sep):
            raise ValueError(f"package name {name!r} escapes the registry")
        return path

    def upload(self, workflow, name: str,
               metadata: Optional[Dict] = None) -> str:
        blob, manifest = pack(workflow, name, metadata)
        return self.put_package(name, blob, manifest)

    def put_package(self, name: str, blob: bytes, manifest: Dict) -> str:
        """Store an already-packaged model (the server's upload path)."""
        d = self._pkg_dir(name)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "model.pickle.gz"), "wb") as f:
            f.write(blob)
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        return name

    def get_blob(self, name: str) -> bytes:
        with open(os.path.join(self._pkg_dir(name),
                               "model.pickle.gz"), "rb") as f:
            return f.read()

    def download(self, name: str) -> Dict:
        d = self._pkg_dir(name)
        with gzip.open(os.path.join(d, "model.pickle.gz"), "rb") as f:
            return pickle.load(f)

    def manifest(self, name: str) -> Dict:
        with open(os.path.join(self._pkg_dir(name), "manifest.json")) as f:
            return json.load(f)

    def list(self) -> List[Dict]:
        out = []
        for entry in sorted(os.listdir(self.registry)):
            path = os.path.join(self.registry, entry, "manifest.json")
            if os.path.exists(path):
                with open(path) as f:
                    out.append(json.load(f))
        return out

    def delete(self, name: str) -> None:
        import shutil

        d = self._pkg_dir(name)
        if os.path.isdir(d):
            shutil.rmtree(d)


def pack(workflow, name: str, metadata: Optional[Dict] = None):
    """Package a workflow -> (gzipped pickle blob, manifest dict)."""
    from znicz_tpu import snapshotter

    snap = snapshotter.collect(workflow)
    snap["config"] = root.to_dict()
    blob = gzip.compress(pickle.dumps(snap,
                                      protocol=pickle.HIGHEST_PROTOCOL))
    manifest = {"name": name, "workflow": workflow.name,
                "time": time.time(), "metadata": metadata or {}}
    return blob, manifest


class ForgeServer:
    """Serve a ``Forge`` registry over HTTP (VERDICT r2 missing #2).

    Endpoints:
      GET    /list               -> JSON list of manifests
      GET    /pkg/<name>/manifest -> manifest JSON
      GET    /pkg/<name>/model    -> gzipped-pickle package blob
      POST   /pkg/<name>          -> upload (body = blob; manifest JSON in
                                     the X-Forge-Manifest header)
      DELETE /pkg/<name>          -> remove the package
    """

    def __init__(self, registry: Optional[str] = None, port: int = 0,
                 host: str = "127.0.0.1"):
        self.forge = Forge(registry)
        self.host, self.port = host, int(port)
        self._server = None
        self._thread = None

    def _make_handler(self):
        from http.server import BaseHTTPRequestHandler

        forge = self.forge

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _pkg_name(self):
                parts = self.path.strip("/").split("/")
                return parts[1] if len(parts) >= 2 and parts[0] == "pkg" \
                    else None

            def do_GET(self):
                try:
                    if self.path == "/list":
                        return self._reply(
                            200, json.dumps(forge.list()).encode())
                    name = self._pkg_name()
                    if name and self.path.endswith("/manifest"):
                        return self._reply(
                            200, json.dumps(forge.manifest(name)).encode())
                    if name and self.path.endswith("/model"):
                        return self._reply(200, forge.get_blob(name),
                                           "application/octet-stream")
                    self._reply(404, b'{"error": "not found"}')
                except (FileNotFoundError, ValueError) as exc:
                    self._reply(404, json.dumps(
                        {"error": str(exc)}).encode())

            def do_POST(self):
                try:
                    name = self._pkg_name()
                    if not name:
                        return self._reply(404, b'{"error": "not found"}')
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    # body = manifest JSON + blob (manifest can be
                    # arbitrarily large user metadata — headers have a
                    # 64KiB line limit, the body does not)
                    mlen = int(self.headers.get("X-Forge-Manifest-Length",
                                                0))
                    manifest = json.loads(body[:mlen]) if mlen else {}
                    blob = body[mlen:]
                    manifest.setdefault("name", name)
                    forge.put_package(name, blob, manifest)
                    self._reply(200, b'{"ok": true}')
                except (ValueError, OSError) as exc:
                    self._reply(400, json.dumps(
                        {"error": str(exc)}).encode())

            def do_DELETE(self):
                try:
                    name = self._pkg_name()
                    if not name:
                        return self._reply(404, b'{"error": "not found"}')
                    forge.delete(name)
                    self._reply(200, b'{"ok": true}')
                except (ValueError, OSError) as exc:
                    self._reply(400, json.dumps(
                        {"error": str(exc)}).encode())

        return Handler

    def start(self) -> "ForgeServer":
        import threading
        from http.server import ThreadingHTTPServer

        self._server = ThreadingHTTPServer((self.host, self.port),
                                           self._make_handler())
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class RemoteForge:
    """Forge client against a ``ForgeServer`` URL — same API as ``Forge``.

    Downloads are pickles from the registry operator (reference trust
    model); non-loopback URLs therefore require ``allow_remote=True``.
    """

    def __init__(self, url: str, allow_remote: bool = False):
        from urllib.parse import urlparse

        from znicz_tpu.network_common import is_loopback_host

        self.url = url.rstrip("/")
        host = urlparse(self.url).hostname or ""
        if not allow_remote and not is_loopback_host(host):
            raise ValueError(
                f"refusing non-loopback forge {host!r}: packages are "
                f"pickled code — pass allow_remote=True only for a "
                f"registry you trust")

    def _request(self, path: str, data: Optional[bytes] = None,
                 method: Optional[str] = None, headers: Optional[Dict] = None):
        from urllib.request import Request, urlopen

        req = Request(self.url + path, data=data, method=method,
                      headers=headers or {})
        with urlopen(req, timeout=30) as resp:
            return resp.read()

    def upload(self, workflow, name: str,
               metadata: Optional[Dict] = None) -> str:
        blob, manifest = pack(workflow, name, metadata)
        mbytes = json.dumps(manifest).encode()
        self._request(
            f"/pkg/{name}", data=mbytes + blob, method="POST",
            headers={"X-Forge-Manifest-Length": str(len(mbytes)),
                     "Content-Type": "application/octet-stream"})
        return name

    def download(self, name: str) -> Dict:
        blob = self._request(f"/pkg/{name}/model")
        return pickle.loads(gzip.decompress(blob))

    def manifest(self, name: str) -> Dict:
        return json.loads(self._request(f"/pkg/{name}/manifest"))

    def list(self) -> List[Dict]:
        return json.loads(self._request("/list"))

    def delete(self, name: str) -> None:
        self._request(f"/pkg/{name}", method="DELETE")
