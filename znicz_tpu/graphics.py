"""Live plot streaming (rebuild of ``veles/graphics_server.py`` +
``graphics_client.py``, SURVEY.md §2.1 "Graphics" / L9).

The reference published matplotlib figures from plot units over ZMQ pub/sub
to a separate client process that rendered them live.  The rebuild streams
each plotter's *data snapshot* (not a pickled figure): the client
reconstructs the figure with the very same ``Plotter.draw`` renderer the
offline path uses, so there is exactly one renderer per figure kind.

  - ``GraphicsServer``: process-wide XPUB publisher.  XPUB (not PUB) so
    ``wait_for_subscribers`` can see subscription handshakes and tests/
    launchers can avoid the classic pub/sub slow-joiner message loss.
  - ``GraphicsClient``: SUB loop rendering payloads to PNGs in an output
    directory; run as ``python -m znicz_tpu.graphics <endpoint> <outdir>``.
  - Plot units publish automatically whenever a server is active (see
    ``plotting_units.Plotter.run``), degrading gracefully to offline PNG
    rendering when none is.

Payloads are pickled dicts ``{"kind": "figure", "cls": <Plotter subclass
name>, "name": <unit name>, "data": {plain arrays/scalars}}`` plus a
``{"kind": "end"}`` sentinel.  Transport is trusted-local (pickle over a
loopback/ICI-side socket), matching the reference's model.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

_server: Optional["GraphicsServer"] = None


class GraphicsServer:
    """XPUB publisher for plotter snapshots.  ``start()`` installs the
    process-wide instance that ``plotting_units.Plotter`` publishes to."""

    def __init__(self, endpoint: str = "tcp://127.0.0.1:*"):
        import zmq

        from znicz_tpu.network_common import bind_with_retry

        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.XPUB)
        bind_with_retry(self._sock, endpoint)
        self.endpoint = self._sock.getsockopt_string(zmq.LAST_ENDPOINT)
        self._subscribers = 0

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def start(cls, endpoint: str = "tcp://127.0.0.1:*") -> "GraphicsServer":
        global _server
        if _server is None:
            _server = cls(endpoint)
        return _server

    @classmethod
    def active(cls) -> Optional["GraphicsServer"]:
        return _server

    @classmethod
    def stop(cls) -> None:
        global _server
        if _server is not None:
            _server.publish({"kind": "end"})
            _server.close()
            _server = None

    def close(self) -> None:
        self._sock.close(linger=500)

    # -- pub side ------------------------------------------------------------

    def _pump_subscriptions(self, timeout_ms: int = 0) -> None:
        import zmq

        while self._sock.poll(timeout_ms, zmq.POLLIN):
            msg = self._sock.recv()
            if msg[:1] == b"\x01":
                self._subscribers += 1
            elif msg[:1] == b"\x00":
                self._subscribers -= 1
            timeout_ms = 0

    def wait_for_subscribers(self, n: int = 1, timeout: float = 10.0) -> bool:
        """Block until >= n subscribers have joined (slow-joiner guard)."""
        deadline = time.monotonic() + timeout
        while self._subscribers < n:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            self._pump_subscriptions(int(left * 1000))
        return True

    def publish(self, payload: dict) -> None:
        self._pump_subscriptions()
        self._sock.send(pickle.dumps(payload, pickle.HIGHEST_PROTOCOL))


def _is_loopback(endpoint: str) -> bool:
    """True for ipc:// / inproc:// endpoints and tcp:// on a loopback host
    (host policy shared with RemoteForge via network_common)."""
    from znicz_tpu.network_common import is_loopback_host

    if endpoint.startswith(("ipc://", "inproc://")):
        return True
    if endpoint.startswith("tcp://"):
        return is_loopback_host(
            endpoint[len("tcp://"):].rsplit(":", 1)[0].strip("[]"))
    return False


class GraphicsClient:
    """Receives plotter snapshots and renders PNGs via the plotter classes'
    own ``draw`` renderers."""

    def __init__(self, endpoint: str, out_dir: str,
                 allow_remote: bool = False):
        import zmq

        # Payloads are unpickled (same-host trusted IPC, like the reference's
        # twisted pickle streams).  Unpickling data from a non-loopback peer
        # would be arbitrary code execution, so refuse unless explicitly
        # overridden.
        if not allow_remote and not _is_loopback(endpoint):
            raise ValueError(
                f"GraphicsClient endpoint {endpoint!r} is not loopback; "
                "payloads are pickled (code-execution risk from untrusted "
                "publishers). Pass allow_remote=True only for trusted hosts.")
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.SUB)
        self._sock.connect(endpoint)
        self._sock.setsockopt(zmq.SUBSCRIBE, b"")
        self.received = 0

    def render(self, payload: dict) -> Optional[str]:
        from znicz_tpu import plotting_units

        cls = getattr(plotting_units, payload["cls"], None)
        if cls is None or not issubclass(cls, plotting_units.Plotter):
            return None
        path = os.path.join(self.out_dir, f"{payload['name']}.png")
        cls.render_png(payload["data"], path)
        return path

    def run(self, max_figures: int = 0, timeout: float = 0.0,
            idle_timeout: Optional[float] = None) -> int:
        """Render until the ``end`` sentinel (or limits); returns count.
        ``idle_timeout`` bounds every recv so the client always exits even
        when the publisher dies without sending the sentinel (SUB sockets
        wait for reconnection forever otherwise).  Default: 600s when no
        overall ``timeout`` is given, else disabled — an explicit timeout
        must never be silently capped by the idle guard."""
        import zmq

        if idle_timeout is None:
            idle_timeout = 0.0 if timeout else 600.0
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            wait = idle_timeout if idle_timeout else None
            if deadline is not None:
                left = deadline - time.monotonic()
                wait = left if wait is None else min(left, wait)
            if wait is not None and (
                    wait <= 0
                    or not self._sock.poll(int(wait * 1000), zmq.POLLIN)):
                break
            payload = pickle.loads(self._sock.recv())
            if payload.get("kind") == "end":
                break
            if payload.get("kind") == "figure":
                if self.render(payload) is not None:
                    self.received += 1
                    if max_figures and self.received >= max_figures:
                        break
        return self.received

    def close(self) -> None:
        self._sock.close(linger=0)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="znicz-tpu live graphics client")
    parser.add_argument("endpoint")
    parser.add_argument("out_dir")
    parser.add_argument("--max-figures", type=int, default=0)
    parser.add_argument("--timeout", type=float, default=0.0)
    parser.add_argument("--idle-timeout", type=float, default=None,
                        help="exit after this long with no messages "
                             "(default: 600 when no --timeout, else off; "
                             "0 = never)")
    args = parser.parse_args(argv)
    client = GraphicsClient(args.endpoint, args.out_dir)
    try:
        count = client.run(max_figures=args.max_figures,
                           timeout=args.timeout,
                           idle_timeout=args.idle_timeout)
    finally:
        client.close()
    print(f"rendered {count} figures -> {args.out_dir}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
