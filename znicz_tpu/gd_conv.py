"""Convolution backward units (rebuild of ``znicz/gd_conv.py``).

The reference hand-wrote transposed-correlation kernels for err_input and
patch-matmul kernels for dW; here both are exactly what ``jax.vjp`` of the
forward conv emits (XLA's conv-transpose forms), so these classes only fix
the naming/type surface.  ``GDConvSoftmax`` does not exist in the reference
(conv is never the top layer feeding CE directly).
"""

from __future__ import annotations

from znicz_tpu.nn_units import GradientDescentBase


class GradientDescentConv(GradientDescentBase):
    pass


class GDTanhConv(GradientDescentConv):
    pass


class GDRELUConv(GradientDescentConv):
    pass


class GDStrictRELUConv(GradientDescentConv):
    pass


GD_BY_FORWARD_CONV = {
    "Conv": GradientDescentConv,
    "ConvTanh": GDTanhConv,
    "ConvRELU": GDRELUConv,
    "ConvStrictRELU": GDStrictRELUConv,
}
