"""Array: the host/device paired tensor (rebuild of ``veles/memory.py``).

The reference's ``Array`` pairs a numpy host buffer with an OpenCL/CUDA device
buffer and a lazy map/unmap sync protocol.  On TPU the device buffer is a jax
array in HBM and transfers go through PJRT, so the protocol collapses to a
tiny state machine:

  - ``map_read()``       — make the host view current (device→host if needed)
  - ``map_write()``      — host view current + mark host dirty
  - ``map_invalidate()`` — mark host dirty without device→host copy
  - ``unmap()``          — make the device copy current (host→device if dirty)

Units keep their tensors as ``Array``s; inside fused jitted train steps the
same storage is accessed as ``.devmem`` (a jax array), and the map protocol
guards stale-host reads exactly like the reference's asserts did (SURVEY.md
§5 "race detection").
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# Sync states.
_SYNCED = 0        # host == device (or device never materialized)
_HOST_DIRTY = 1    # host newer than device
_DEV_DIRTY = 2     # device newer than host


def roundup(value: int, multiple: int) -> int:
    """Round ``value`` up to a multiple (the reference used this to pad
    buffers to kernel tile sizes; we keep it for MXU-friendly padding)."""
    rem = value % multiple
    return value if rem == 0 else value + multiple - rem


class Array:
    """Host numpy buffer + lazy jax device buffer."""

    def __init__(self, data: Optional[np.ndarray] = None) -> None:
        self._mem: Optional[np.ndarray] = None
        self._devmem = None          # jax.Array or None
        self._state = _SYNCED
        self._device = None          # znicz_tpu.backends.Device
        #: jax.device_put on the CPU backend ZERO-COPIES sufficiently large
        #: aligned numpy arrays — the jax array aliases ``_mem``'s buffer.
        #: Mutating the host buffer afterwards would silently corrupt the
        #: "immutable" device value (async consumers may still be reading
        #: it), so writes break the aliasing first (map_write/
        #: map_invalidate).  True while ``_devmem`` may share ``_mem``.
        self._aliased = False
        if data is not None:
            self.reset(data)

    # -- allocation ----------------------------------------------------------

    def reset(self, data: Optional[np.ndarray]) -> None:
        """(Re)bind the host buffer; drops any device copy."""
        if data is not None and not isinstance(data, np.ndarray):
            data = np.asarray(data)
        self._mem = data
        self._devmem = None
        self._aliased = False
        self._state = _HOST_DIRTY if data is not None else _SYNCED

    @property
    def mem(self) -> Optional[np.ndarray]:
        """Raw host buffer (no sync) — write via map_write/map_invalidate."""
        return self._mem

    @mem.setter
    def mem(self, data: Optional[np.ndarray]) -> None:
        self.reset(data)

    def __bool__(self) -> bool:
        return self._mem is not None or self._devmem is not None

    @property
    def host_dirty(self) -> bool:
        """True when the host buffer holds writes not yet synced to the
        device copy.  Raw-state peek (no sync) — consumers that hand the
        device buffer onward (e.g. the fused trainer's cross-host-sharded
        operand path, which CANNOT reshard implicitly) use this to refuse
        stale reads instead of training on outdated state."""
        return self._state == _HOST_DIRTY

    @property
    def cross_host_sharded(self) -> bool:
        """True when the backing device buffer is a global array actually
        SHARDED across processes (not fully addressable, not fully
        replicated) — host collection (map_read / np.array) cannot
        materialize it.  Raw-attribute peek: the devmem property would
        SYNC (device_put) a host-dirty Array just to be inspected."""
        dm = self._devmem
        return (dm is not None
                and not getattr(dm, "is_fully_addressable", True)
                and not getattr(dm, "is_fully_replicated", False))

    # -- shape helpers -------------------------------------------------------

    @property
    def shape(self) -> Tuple[int, ...]:
        if self._mem is not None:
            return self._mem.shape
        if self._devmem is not None:
            return tuple(self._devmem.shape)
        return ()

    @property
    def dtype(self):
        if self._mem is not None:
            return self._mem.dtype
        if self._devmem is not None:
            return np.dtype(self._devmem.dtype)
        return None

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 0

    def __len__(self) -> int:
        return self.shape[0] if self.shape else 0

    @property
    def sample_size(self) -> int:
        """Elements per leading-dim sample (reference: size/shape[0])."""
        return self.size // max(1, len(self))

    @property
    def plain(self) -> np.ndarray:
        """Flattened host view, mapped for read."""
        self.map_read()
        return self._mem.reshape(-1)

    # -- the map/unmap protocol ----------------------------------------------

    def initialize(self, device) -> None:
        """Attach to a device (the reference allocated the device buffer
        here; we stay lazy — first unmap materializes it)."""
        self._device = device

    def map_read(self) -> np.ndarray:
        if self._state == _DEV_DIRTY and self._devmem_deleted():
            raise RuntimeError(
                "Array: device buffer was donated away before its value "
                "was read back — the data is gone.  Writeback or "
                "map_read before handing devmem to a donating consumer.")
        if self._state == _DEV_DIRTY:
            # np.array (not asarray): asarray of a jax CPU buffer is a
            # zero-copy READ-ONLY view, which would make map_write hand out
            # an unwritable buffer.
            self._mem = np.array(self._devmem)
            self._aliased = False
            self._state = _SYNCED
        if self._mem is None:
            raise RuntimeError("Array.map_read on empty Array")
        return self._mem

    def map_write(self) -> np.ndarray:
        mem = self.map_read()
        if self._aliased:
            # the live device value may share this buffer (zero-copy
            # device_put) — writes must land in a fresh one
            self._mem = mem = np.array(mem)
            self._aliased = False
        self._state = _HOST_DIRTY
        return mem

    def map_invalidate(self) -> np.ndarray:
        """Host will be fully overwritten: skip the device→host copy."""
        if self._mem is None and self._devmem is not None:
            self._mem = np.empty(self._devmem.shape,
                                 np.dtype(self._devmem.dtype))
        if self._mem is None:
            raise RuntimeError("Array.map_invalidate on empty Array")
        if self._aliased:
            # see map_write; no copy — the caller overwrites everything
            self._mem = np.empty_like(self._mem)
            self._aliased = False
        self._state = _HOST_DIRTY
        return self._mem

    def _devmem_deleted(self) -> bool:
        """True when a DONATING consumer invalidated the device buffer
        (jit with donate_argnums may consume an array that, on the CPU
        backend, aliased this Array's devmem — e.g. a second FusedTrainer
        built over the same workflow)."""
        try:
            return (self._devmem is not None
                    and self._devmem.is_deleted())
        except Exception:
            return False

    def unmap(self):
        """Make the device copy current; returns the jax array.  A
        donated-away device buffer is recovered from the host copy when
        the host is not stale; otherwise the data is genuinely gone and
        this raises instead of returning a dead array."""
        if self._devmem_deleted():
            if self._state == _DEV_DIRTY or self._mem is None:
                raise RuntimeError(
                    "Array: device buffer was donated away and no "
                    "current host copy exists (device value was newer). "
                    "Writeback or map_read the Array before handing its "
                    "devmem to a donating consumer.")
            self._devmem = None
            self._state = _HOST_DIRTY
        if self._state == _HOST_DIRTY or self._devmem is None:
            if self._mem is None:
                raise RuntimeError("Array.unmap on empty Array")
            import jax

            if self._device is not None:
                self._devmem = jax.device_put(self._mem,
                                              self._device.jax_device)
            elif jax.process_count() > 1:
                # multi-controller: the bare put's default placement is
                # GLOBAL device 0, which other processes do not own — the
                # result would span non-addressable devices.  Host arrays
                # belong on a local device (global_put reshards later).
                self._devmem = jax.device_put(self._mem,
                                              jax.local_devices()[0])
            else:
                self._devmem = jax.device_put(self._mem)
            self._state = _SYNCED
            # only the CPU backend zero-copies; TPU/GPU puts always copy
            # to device memory, so marking those aliased would just force
            # pointless host-buffer reallocation on every map_write
            dev = next(iter(self._devmem.devices()), None)
            self._aliased = (dev is not None and dev.platform == "cpu")
        return self._devmem

    @property
    def devmem(self):
        """Current device buffer (syncing host→device if dirty)."""
        return self.unmap()

    @devmem.setter
    def devmem(self, value) -> None:
        """Adopt a freshly computed jax array as the authoritative value."""
        self._devmem = value
        self._aliased = False        # computed value, not a view of _mem
        self._state = _DEV_DIRTY

    # -- numpy conveniences --------------------------------------------------

    def __array__(self, dtype=None):
        mem = self.map_read()
        return mem.astype(dtype) if dtype is not None else mem

    def __getitem__(self, idx):
        return self.map_read()[idx]

    def __setitem__(self, idx, value):
        self.map_write()[idx] = value

    def __repr__(self) -> str:
        state = {_SYNCED: "synced", _HOST_DIRTY: "host-dirty",
                 _DEV_DIRTY: "dev-dirty"}[self._state]
        return f"Array(shape={self.shape}, dtype={self.dtype}, {state})"


# The reference aliased Array as Vector.
Vector = Array
