"""NN unit bases (rebuild of ``znicz/nn_units.py``, SURVEY.md §2.2 "NN base").

Two base classes:

  - ``ForwardBase`` — a unit with ``input -> output`` plus learnable params
    (weights/bias), weight init policies (uniform/gaussian ``weights_stddev``),
    ``weights_transposed``, and a pure ``apply(params, x)`` the whole stack
    reuses (unit-at-a-time run, fused train step, numpy oracle tests).

  - ``GradientDescentBase`` — the reference's hand-written backward ("GD")
    units become a facade over ``jax.vjp`` of the paired forward's pure
    function.  What is preserved is the *semantics* the reference exposed:
    per-unit learning_rate / learning_rate_bias / weights_decay / l1_vs_l2 /
    gradient_moment (momentum) / gradient clipping, err_output -> err_input
    chaining in reverse unit order, and updates applied only on TRAIN
    minibatches.  What is gone: hand-derived derivative kernels (vjp cannot
    drift from the forward math).

TPU notes: each unit jits one step function with static shapes; parameters
and hyperparameters are traced arguments so per-epoch lr adjustment never
recompiles.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.units import Unit
from znicz_tpu.distributable import Distributable
from znicz_tpu.memory import Array


class ForwardBase(Unit, Distributable):
    """Base of every forward compute unit.

    Config kwargs (reference names):
      - ``weights_stddev``: init scale; default ``1/sqrt(fan_in)``-style.
      - ``weights_filling``: "uniform" | "gaussian" | "constant".
      - ``bias_stddev`` / ``bias_filling``: same for bias.
      - ``include_bias``: bias term on/off.
      - ``weights_transposed``: store W as (in, out) instead of (out, in).
    """

    #: subclasses with no learnable params set this False (pooling, dropout…)
    has_weights = True

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.input: Optional[Array] = None
        self.output = Array()
        self.weights = Array()
        self.bias = Array()
        self.weights_stddev = kwargs.get("weights_stddev")
        self.weights_filling = kwargs.get("weights_filling", "uniform")
        self.bias_stddev = kwargs.get("bias_stddev")
        self.bias_filling = kwargs.get("bias_filling", "constant")
        self.include_bias = kwargs.get("include_bias", True)
        self.weights_transposed = kwargs.get("weights_transposed", False)
        self._compiled = None

    # -- weight init ---------------------------------------------------------

    def _fill(self, arr: np.ndarray, filling: str, stddev: float) -> None:
        gen = prng.get(self.name)
        if filling == "uniform":
            # The reference's uniform filling spans ±stddev·sqrt(3) so that
            # the std matches the gaussian filling.
            lim = stddev * np.sqrt(3.0)
            gen.fill_uniform(arr, -lim, lim)
        elif filling == "gaussian":
            gen.fill_normal(arr, stddev)
        elif filling == "constant":
            arr[...] = stddev
        else:
            raise ValueError(f"unknown filling {filling!r}")

    def init_weights(self, w_shape: Tuple[int, ...],
                     b_shape: Tuple[int, ...]) -> None:
        fan_in = int(np.prod(w_shape[1:])) or 1
        stddev = self.weights_stddev or 1.0 / np.sqrt(fan_in)
        w = np.zeros(w_shape, np.float32)
        self._fill(w, self.weights_filling, stddev)
        if self.weights_transposed:
            w = np.ascontiguousarray(w.T)
        self.weights.mem = w
        if self.include_bias:
            b = np.zeros(b_shape, np.float32)
            self._fill(b, self.bias_filling, self.bias_stddev or 0.0)
            self.bias.mem = b

    # -- pure compute --------------------------------------------------------

    def params(self) -> Dict[str, Array]:
        """name -> Array of learnable params (used by GD twin, snapshotter,
        fused trainer)."""
        if not self.has_weights:
            return {}
        out = {"weights": self.weights}
        if self.include_bias:
            out["bias"] = self.bias
        return out

    def apply(self, params: Dict, x):
        """Pure forward: params dict of jax arrays + input -> output.
        Subclasses MUST override.  No side effects, jit-safe."""
        raise NotImplementedError

    def output_shape_for(self, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        """Static output shape given input shape; subclasses override."""
        raise NotImplementedError

    # -- unit lifecycle ------------------------------------------------------

    def create_output(self) -> None:
        shape = self.output_shape_for(tuple(self.input.shape))
        if self.output.mem is None or tuple(self.output.shape) != shape:
            self.output.mem = np.zeros(shape, np.float32)

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        for arr in (self.weights, self.bias, self.output):
            arr.initialize(device)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self.apply)
        p = {k: a.devmem for k, a in self.params().items()}
        self.output.devmem = self._compiled(p, self.input.devmem)


def _decay_grad(w, weights_decay, l1_vs_l2):
    """Regularization gradient, reference formula: a weighted mix of L2 (w)
    and L1 (sign w) — ``factor_l1·sign(w)/2 + factor_l2·w`` with
    ``l1_vs_l2`` interpolating."""
    import jax.numpy as jnp

    return weights_decay * (l1_vs_l2 * 0.5 * jnp.sign(w)
                            + (1.0 - l1_vs_l2) * w)


def _state_dtype():
    """Storage dtype for optimizer accumulators (velocities):
    ``root.common.engine.state_dtype = "bfloat16"`` halves their HBM
    traffic — the profiled cost of the fc update fusions is pure
    weight+velocity memory bandwidth (r4 profile: fc6 dW+update at
    11 TFLOP/s, HBM-bound).  Update MATH stays float32 regardless
    (sgd_update); only the stored accumulator is rounded.  Semantics:
    the velocity is quantized to bf16 (8-bit mantissa) once per step;
    master weights are always float32."""
    from znicz_tpu.core.config import root

    name = root.common.engine.get("state_dtype", "float32")
    if name == "float32":
        return np.dtype("float32")
    if name == "bfloat16":
        return "bfloat16"
    raise ValueError(
        f"root.common.engine.state_dtype={name!r}: must be 'float32' or "
        "'bfloat16' (silently accepting a typo would silently change "
        "training-state precision)")


def sgd_update(w, g, v, *, lr, weights_decay, l1_vs_l2, momentum, clip):
    """The reference's weight-update kernel as one pure function — the
    SINGLE home of the update rule, used by both the unit-at-a-time GD units
    and the fused SPMD trainer (they must never drift).

    ``v`` may be stored in a reduced dtype (see ``_state_dtype``); the
    arithmetic runs in the weights' dtype (f32) and the new velocity is
    stored back in v's own dtype.  Returns (w_new, v_new)."""
    import jax.numpy as jnp

    g = jnp.where(clip > 0.0, jnp.clip(g, -clip, clip), g)
    g = g + _decay_grad(w, weights_decay, l1_vs_l2)
    v_new = momentum * v.astype(w.dtype) - lr * g
    return w + v_new, v_new.astype(v.dtype)


class GradientDescentBase(Unit, Distributable):
    """Backward twin of a ``ForwardBase``: consumes ``err_output``, produces
    ``err_input`` and updates the forward's params in place (on device).

    Hyperparameters (reference names / defaults):
      learning_rate (0.01), learning_rate_bias (= learning_rate),
      weights_decay (0.0), weights_decay_bias (0.0), l1_vs_l2 (0.0 = pure L2),
      gradient_moment (0.0), gradient_moment_bias (= gradient_moment),
      gradient_clip (0 = off; max-abs clip of raw gradients).

    Update rule: SGD with momentum + L1/L2 + clip — the policy every
    BASELINE config uses.  SURVEY §2.3 flags possible adagrad/adadelta
    accumulator variants in the reference's weight-update kernels as
    "verify against the mount"; the mount is empty, so those remain an
    explicit, documented drop until a reference to verify against exists.
    """

    def __init__(self, workflow=None, name=None, forward: ForwardBase = None,
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.forward = forward
        self.err_output: Optional[Array] = None     # linked from downstream
        self.err_input = Array()                     # produced for upstream
        self.learning_rate = kwargs.get("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.get("learning_rate_bias",
                                             self.learning_rate)
        self.weights_decay = kwargs.get("weights_decay", 0.0)
        self.weights_decay_bias = kwargs.get("weights_decay_bias", 0.0)
        self.l1_vs_l2 = kwargs.get("l1_vs_l2", 0.0)
        self.gradient_moment = kwargs.get("gradient_moment", 0.0)
        self.gradient_moment_bias = kwargs.get("gradient_moment_bias",
                                               self.gradient_moment)
        self.gradient_clip = kwargs.get("gradient_clip", 0.0)
        #: when False, compute err_input but skip the param update (the
        #: reference's ``apply_gradient`` switch; also off for frozen layers)
        self.apply_gradient = kwargs.get("apply_gradient", True)
        #: first GD in the chain doesn't need err_input (reference's
        #: ``need_err_input``)
        self.need_err_input = kwargs.get("need_err_input", True)
        #: hypers as configured, frozen at first initialize() — the values a
        #: freshly built replica of this graph would carry.  The network
        #: digest hashes THESE, not the live fields, so a peer whose
        #: LearningRateAdjust schedule has advanced (slave re-registering
        #: mid-training) still matches the master's graph (ADVICE r3).
        self.initial_hypers = None
        self._velocities: Dict[str, Array] = {}
        self._compiled = None

    # -- pure compute --------------------------------------------------------

    def backward_apply(self, params: Dict, x):
        """The function whose vjp defines this unit's backward.  Defaults to
        the forward's ``apply``; softmax GD overrides (CE+softmax combo makes
        err_output already the logits cotangent)."""
        return self.forward.apply(params, x)

    def _step(self, params, velocities, x, err_output, hypers):
        """Pure: one backward+update step.  Returns (err_input, new_params,
        new_velocities)."""
        import jax

        (lr, lr_bias, wd, wd_bias, l1l2, mom, mom_bias, clip) = hypers
        _, vjp = jax.vjp(self.backward_apply, params, x)
        grads, err_input = vjp(err_output)
        new_params, new_vel = {}, {}
        for k, g in grads.items():
            is_bias = (k == "bias")
            new_params[k], new_vel[k] = sgd_update(
                params[k], g, velocities[k],
                lr=(lr_bias if is_bias else lr),
                weights_decay=(wd_bias if is_bias else wd),
                l1_vs_l2=l1l2,
                momentum=(mom_bias if is_bias else mom),
                clip=clip)
        return err_input, new_params, new_vel

    # -- unit lifecycle ------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        assert self.forward is not None, f"{self.name}: no forward twin"
        if self.initial_hypers is None:
            self.initial_hypers = tuple(float(v) for v in self._hypers())
        for k, arr in self.forward.params().items():
            vel = Array(np.zeros(arr.shape, _state_dtype()))
            vel.initialize(device)
            self._velocities[k] = vel
        self.err_input.initialize(device)

    # -- Distributable: a GD unit's serializable state is its optimizer
    # -- accumulators (the forward owns the weights) --------------------------

    def _param_arrays(self):
        return {k: np.array(a.map_read())
                for k, a in self._velocities.items()}

    def apply_data_from_master(self, data):
        if data:
            for k, arr in self._velocities.items():
                if k in data:
                    arr.mem = np.asarray(data[k]).copy()

    apply_data_from_slave = apply_data_from_master

    def _hypers(self):
        import numpy as np

        return tuple(np.float32(v) for v in (
            self.learning_rate, self.learning_rate_bias, self.weights_decay,
            self.weights_decay_bias, self.l1_vs_l2, self.gradient_moment,
            self.gradient_moment_bias, self.gradient_clip))

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self._step)
        params = {k: a.devmem for k, a in self.forward.params().items()}
        vels = {k: a.devmem for k, a in self._velocities.items()}
        err_in, new_params, new_vels = self._compiled(
            params, vels, self.forward.input.devmem, self.err_output.devmem,
            self._hypers())
        if self.need_err_input:
            self.err_input.devmem = err_in
        if self.apply_gradient:
            for k, arr in self.forward.params().items():
                arr.devmem = new_params[k]
            for k, arr in self._velocities.items():
                arr.devmem = new_vels[k]
