"""Sequence-model units (beyond-reference capability; see
ops/attention.py for why).  Follows the framework's unit contract: pure
``apply(params, x)``, a GD twin via vjp with the standard per-layer
hyperparameters, registry type ``"attention"`` for StandardWorkflow.

Input/output: (batch, seq, embed).  For sequence-parallel training, the
fused path can swap the core for ``ops.attention.ring_attention`` inside a
shard_map over the sequence axis — either explicitly (``sp_axis`` kwarg,
for callers already inside a shard_map) or via the
``root.common.engine.seq_parallel`` knob (ISSUE 15): with ``seq_parallel
= N > 1`` the unit builds an ``("sp",)`` mesh of N devices at initialize
and ``apply`` shard_maps the attention core over it — ring attention
leaves the dryrun on the EXISTING mesh plumbing, CPU-testable with
virtual devices exactly like ``bench.py --shard`` (default 0 = off, the
single-device path, bit-exact; BASELINE.md r20 records the TPU
engagement protocol).

The variable-length serving/training units live here too (ISSUE 15):

  - :class:`CharEmbedding` — (batch, seq) integer ids -> token + position
    embeddings; the id dtype crossing the wire/HBM is u8 (vocab <= 256),
    decoded in-graph like every u8 dataset;
  - :class:`SeqAll2All` family — POSITION-WISE dense layers (the
    transformer FFN / logits head): same (out, in) weight layout and
    activation surface as All2All, applied at every sequence position
    instead of over the flattened sample (All2All's flatten is exactly
    what a variable-length input cannot have).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase, GradientDescentBase
from znicz_tpu.ops import activations
from znicz_tpu.ops.attention import (attention, cache_append,
                                     decode_attention, ring_attention)


def seq_parallel_size() -> int:
    """The ``root.common.engine.seq_parallel`` knob: sequence-parallel
    mesh size for MultiHeadAttention (0/1 = off — the single-device
    path).  Gated OFF by default; engage per BASELINE.md r20."""
    from znicz_tpu.core.config import root

    return int(root.common.engine.get("seq_parallel", 0))


class MultiHeadAttention(ForwardBase):
    def __init__(self, workflow=None, name=None, heads=4, head_dim=None,
                 causal=False, sp_axis=None, residual=False, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.heads = int(heads)
        self.head_dim = head_dim           # default: embed // heads
        self.causal = bool(causal)
        self.sp_axis = sp_axis             # set inside shard_map for SP
        #: y = x + attn(x): the transformer block's skip connection,
        #: inside the unit so the strictly-sequential forward chain
        #: (unit engine AND fused path) needs no graph surgery
        self.residual = bool(residual)
        #: ("sp",) mesh when root.common.engine.seq_parallel is on
        #: (built at initialize; apply shard_maps the core over it) — or
        #: a TRAINER mesh via bind_sequence_mesh (ISSUE 18)
        self._sp_mesh = None
        #: (batch axis or None, sequence axis) the shard_map splits over
        self._sp_spec = (None, "sp")
        self.proj = {k: Array() for k in ("wq", "wk", "wv", "wo")}

    def bind_sequence_mesh(self, mesh, batch_axis="data",
                           seq_axis="model") -> bool:
        """Ring attention on a TRAINER/SERVING mesh (ISSUE 18): instead
        of a private ("sp",) mesh, shard_map the attention core over the
        slice's own axes — batch over ``batch_axis``, sequence blocks
        ring-rotating over ``seq_axis`` — so charlm training reuses the
        very mesh its train steps are jitted over (no second device
        grid, no resharding at the attention boundary).  Sticky:
        ``initialize`` skips its private mesh once bound.  Returns False
        (unbound) when the mesh lacks a >1 sequence axis."""
        if mesh is None or seq_axis not in mesh.axis_names \
                or int(mesh.shape[seq_axis]) < 2:
            return False
        self._sp_mesh = mesh
        self._sp_spec = (batch_axis if batch_axis in mesh.axis_names
                         else None, seq_axis)
        return True

    def params(self) -> Dict[str, Array]:
        return dict(self.proj)

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def _core(self, q, k, v, axis_name=None):
        if axis_name:
            return ring_attention(q, k, v, axis_name, causal=self.causal)
        return attention(q, k, v, causal=self.causal)

    def apply(self, params, x):
        b, t, e = x.shape
        h, d = self.heads, self.head_dim
        q = (x @ params["wq"]).reshape(b, t, h, d)
        k = (x @ params["wk"]).reshape(b, t, h, d)
        v = (x @ params["wv"]).reshape(b, t, h, d)
        bax, sax = self._sp_spec
        if self.sp_axis:
            o = self._core(q, k, v, self.sp_axis)
        elif (self._sp_mesh is not None
                and t % self._sp_mesh.shape[sax] == 0
                and (bax is None or b % self._sp_mesh.shape[bax] == 0)):
            # ring attention over the bound mesh — q/k/v split along the
            # sequence axis (and the batch axis when bound to a trainer
            # mesh), k/v blocks rotate by ppermute, grads flow through
            # the shard_map (tests/test_attention.py proves exactness +
            # grad parity).  A shape the mesh cannot split (a short
            # serving bucket) falls back to the dense core — same math.
            from jax.sharding import PartitionSpec as P

            from znicz_tpu.parallel.mesh import shard_map

            spec = P(bax, sax)
            o = shard_map(
                lambda q, k, v: self._core(q, k, v, sax),
                mesh=self._sp_mesh,
                in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
        else:
            o = self._core(q, k, v)
        y = o.reshape(b, t, h * d) @ params["wo"]
        return x + y if self.residual else y

    def apply_prefill(self, params, x):
        """Full-sequence forward that ALSO returns the per-position k/v
        it computed, so the serving plane can seed a decode cache from
        the prompt in one pass (ISSUE 16).  Dense core only — a prefill
        bucket is one device's worth of sequence.  Returns
        (y, k, v) with k/v shaped (batch, seq, heads, head_dim)."""
        b, t, e = x.shape
        h, d = self.heads, self.head_dim
        q = (x @ params["wq"]).reshape(b, t, h, d)
        k = (x @ params["wk"]).reshape(b, t, h, d)
        v = (x @ params["wv"]).reshape(b, t, h, d)
        o = attention(q, k, v, causal=self.causal)
        y = o.reshape(b, t, h * d) @ params["wo"]
        return (x + y if self.residual else y), k, v

    def apply_prefill_chunk(self, params, x, k_view, v_view, t0):
        """Chunked prefill (ISSUE 19): ``x`` is one chunk's
        (batch, chunk, embed) hiddens whose row ``i`` sits at GLOBAL
        positions ``t0[i] .. t0[i] + chunk - 1``; ``k_view``/``v_view``
        are (batch, ctx, heads, head_dim) gathered paged-cache views
        already holding each row's prefix ``[0 .. t0)``.  Writes the
        chunk's k/v at its global positions (positions past the view
        drop), attends causally with per-row offsets — positions past
        each query (a reused page's stale tail, pad tokens' keys) are
        causally dead — and returns ``(y, k_rows, v_rows)`` for the
        caller to persist into the paged pool."""
        import jax.numpy as jnp

        b, c, e = x.shape
        h, d = self.heads, self.head_dim
        q = (x @ params["wq"]).reshape(b, c, h, d)
        k_rows = (x @ params["wk"]).reshape(b, c, h, d)
        v_rows = (x @ params["wv"]).reshape(b, c, h, d)
        idx = t0[:, None] + jnp.arange(c)
        rows_b = jnp.arange(b)[:, None]
        k_cache = k_view.at[rows_b, idx].set(k_rows, mode="drop")
        v_cache = v_view.at[rows_b, idx].set(v_rows, mode="drop")
        o = attention(q, k_cache, v_cache, causal=True, q_offset=t0)
        y = o.reshape(b, c, h * d) @ params["wo"]
        return (x + y if self.residual else y), k_rows, v_rows

    def apply_decode(self, params, x_t, k_cache, v_cache, t):
        """One autoregressive step: ``x_t`` is this step's hidden row
        (batch, 1, embed) at per-row global position ``t`` ((batch,)
        int32); caches are (batch, cache_len, heads, head_dim).  Appends
        this step's k/v at position ``t`` (so the query always sees at
        least itself), attends over the prefix ``[0..t]``, and returns
        ``(y_t, k_row, v_row)`` — the new rows, for the caller to
        persist (the serving pool scatters just the row, not the whole
        gathered cache).  The returned ``k_cache``/``v_cache`` are the
        appended versions used for THIS step's attention."""
        b, _, e = x_t.shape
        h, d = self.heads, self.head_dim
        q = (x_t @ params["wq"]).reshape(b, 1, h, d)
        k_row = (x_t @ params["wk"]).reshape(b, h, d)
        v_row = (x_t @ params["wv"]).reshape(b, h, d)
        k_cache = cache_append(k_cache, k_row, t)
        v_cache = cache_append(v_cache, v_row, t)
        o = decode_attention(q, k_cache, v_cache, t)
        y = o.reshape(b, 1, h * d) @ params["wo"]
        return (x_t + y if self.residual else y), k_row, v_row

    def initialize(self, device=None, **kwargs):
        b, t, e = self.input.shape
        if self.head_dim is None:
            assert e % self.heads == 0, \
                f"{self.name}: embed {e} not divisible by heads {self.heads}"
            self.head_dim = int(e) // self.heads
        sp = seq_parallel_size()
        if sp > 1 and self.sp_axis is None and self._sp_mesh is None:
            if int(t) % sp:
                raise ValueError(
                    f"{self.name}: root.common.engine.seq_parallel={sp} "
                    f"cannot split sequence length {t}; pick a seq "
                    f"length divisible by the sp mesh size")
            from znicz_tpu.parallel.mesh import make_mesh

            self._sp_mesh = make_mesh((sp,), ("sp",))
        hd = self.heads * self.head_dim
        if self.proj["wq"].mem is None:
            for key, shape in (("wq", (int(e), hd)), ("wk", (int(e), hd)),
                               ("wv", (int(e), hd)), ("wo", (hd, int(e)))):
                w = np.zeros(shape, np.float32)
                self._fill(w, self.weights_filling,
                           self.weights_stddev or 1.0 / np.sqrt(shape[0]))
                self.proj[key].mem = w
        self.create_output()
        for arr in self.proj.values():
            arr.initialize(device)
        super().initialize(device=device, **kwargs)


class GDMultiHeadAttention(GradientDescentBase):
    """vjp of the attention forward; per-layer lr/momentum/decay as usual."""


class CharEmbedding(ForwardBase):
    """Token + positional embedding: (batch, seq) integer ids ->
    (batch, seq, embed).  Ids may arrive as floats (the u8 storage
    decode widens in-graph like every u8 dataset) — they are cast back
    to int32 for the table lookup, so the SAME pure function serves the
    trainer's gathered rows and the serving plane's staged buckets.
    Positions index from 0: a request padded on the RIGHT keeps its real
    tokens' positions unchanged, which is what the masked-parity
    contract needs."""

    def __init__(self, workflow=None, name=None, vocab=256, embed=64,
                 max_len=128, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.vocab = int(vocab)
        self.embed = int(embed)
        self.max_len = int(max_len)
        self.tables = {"embed": Array(), "pos": Array()}

    def params(self) -> Dict[str, Array]:
        return dict(self.tables)

    def output_shape_for(self, in_shape):
        return (in_shape[0], in_shape[1], self.embed)

    def apply(self, params, x):
        import jax.numpy as jnp

        ids = jnp.clip(x.astype(jnp.int32), 0, self.vocab - 1)
        t = x.shape[1]
        return jnp.take(params["embed"], ids, axis=0) \
            + params["pos"][:t][None]

    def apply_offset(self, params, x, t0):
        """A chunk's embedding at per-row global offsets (ISSUE 19's
        chunked prefill): ``x`` is (batch, chunk) ids whose row ``i``
        sits at positions ``t0[i] .. t0[i] + chunk - 1``.  Same tables,
        same clip as :meth:`apply`; positions are gathered per row (and
        clip at the table top like apply_decode — pad tokens past the
        window read a valid row whose output is discarded)."""
        import jax.numpy as jnp

        ids = jnp.clip(x.astype(jnp.int32), 0, self.vocab - 1)
        pos = jnp.clip(t0[:, None] + jnp.arange(x.shape[1]), 0,
                       self.max_len - 1)
        return jnp.take(params["embed"], ids, axis=0) \
            + jnp.take(params["pos"], pos, axis=0)

    def apply_decode(self, params, tokens, t):
        """One decode step's embedding: ``tokens`` is (batch,) — this
        step's input id per row — at per-row global position ``t``
        ((batch,) int32).  Returns (batch, 1, embed).  Same tables, same
        clip, but the position is gathered per ROW instead of sliced
        from 0 (each co-batched generation sits at its own depth)."""
        import jax.numpy as jnp

        ids = jnp.clip(tokens.astype(jnp.int32), 0, self.vocab - 1)
        pos = jnp.clip(t, 0, self.max_len - 1)
        return (jnp.take(params["embed"], ids, axis=0)
                + jnp.take(params["pos"], pos, axis=0))[:, None, :]

    def initialize(self, device=None, **kwargs):
        b, t = self.input.shape[:2]
        if int(t) > self.max_len:
            raise ValueError(
                f"{self.name}: input seq length {t} exceeds max_len="
                f"{self.max_len} (the positional table's size)")
        if self.tables["embed"].mem is None:
            for key, shape in (("embed", (self.vocab, self.embed)),
                               ("pos", (self.max_len, self.embed))):
                w = np.zeros(shape, np.float32)
                self._fill(w, self.weights_filling,
                           self.weights_stddev or 1.0 / np.sqrt(self.embed))
                self.tables[key].mem = w
        self.create_output()
        for arr in self.tables.values():
            arr.initialize(device)
        super().initialize(device=device, **kwargs)


class GDCharEmbedding(GradientDescentBase):
    """vjp of the embedding lookup (scatter-add into the tables); the id
    input is integral, so no err_input flows upstream (none exists)."""


class SeqAll2All(ForwardBase):
    """Position-wise dense layer: ``y = act(x @ W^T + b)`` at every
    sequence position — (batch, seq, in) -> (batch, seq, width).  Same
    (out, in) weight layout, activation surface and GD semantics as
    All2All; what differs is exactly the flatten All2All performs (a
    variable-length input must keep its seq axis)."""

    ACTIVATION = staticmethod(activations.identity)

    def __init__(self, workflow=None, name=None, output_sample_shape=(),
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.width = int(np.prod(tuple(output_sample_shape))) \
            if output_sample_shape else 0

    def output_shape_for(self, in_shape):
        return (in_shape[0], in_shape[1], self.width)

    @property
    def output_samples_number(self) -> int:
        """Per-position width (the All2All-compat name the fused
        trainer's confusion sizing reads)."""
        return self.width

    def apply(self, params, x):
        from znicz_tpu.ops.linear import seq_linear

        return type(self).ACTIVATION(
            seq_linear(x, params["weights"], params.get("bias"),
                       weights_transposed=self.weights_transposed))

    def initialize(self, device=None, **kwargs):
        in_size = int(self.input.shape[-1])
        if not self.width:
            self.width = in_size
        if self.weights.mem is None:
            self.init_weights((self.width, in_size), (self.width,))
        self.create_output()
        super().initialize(device=device, **kwargs)


class SeqAll2AllTanh(SeqAll2All):
    ACTIVATION = staticmethod(activations.tanh_scaled)


class SeqAll2AllStrictRELU(SeqAll2All):
    ACTIVATION = staticmethod(activations.strict_relu)


class SeqAll2AllSoftmax(SeqAll2All):
    """Per-position softmax head (the LM's next-token distribution); the
    paired GD twin treats err_output as the logits cotangent, and the
    fused trainer emits LOGITS from this head exactly as it does for
    All2AllSoftmax."""

    ACTIVATION = staticmethod(activations.softmax)


class GDSeqAll2All(GradientDescentBase):
    """Backward for any SeqAll2All* via vjp of forward.apply."""


class GDSeqSoftmax(GDSeqAll2All):
    """err_output is d(CE)/d(logits): bypass the softmax in the vjp
    (the same fused softmax+CE-backward convention as gd.GDSoftmax)."""

    def backward_apply(self, params, x):
        from znicz_tpu.ops.linear import seq_linear

        return seq_linear(x, params["weights"], params.get("bias"),
                          weights_transposed=self.forward.weights_transposed)
