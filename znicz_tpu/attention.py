"""Multi-head attention units (beyond-reference capability; see
ops/attention.py for why).  Follows the framework's unit contract: pure
``apply(params, x)``, a GD twin via vjp with the standard per-layer
hyperparameters, registry type ``"attention"`` for StandardWorkflow.

Input/output: (batch, seq, embed).  For sequence-parallel training, the
fused path can swap the core for ``ops.attention.ring_attention`` inside a
shard_map over the sequence axis (``sp_axis`` kwarg).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase, GradientDescentBase
from znicz_tpu.ops.attention import attention, ring_attention


class MultiHeadAttention(ForwardBase):
    def __init__(self, workflow=None, name=None, heads=4, head_dim=None,
                 causal=False, sp_axis=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.heads = int(heads)
        self.head_dim = head_dim           # default: embed // heads
        self.causal = bool(causal)
        self.sp_axis = sp_axis             # set inside shard_map for SP
        self.proj = {k: Array() for k in ("wq", "wk", "wv", "wo")}

    def params(self) -> Dict[str, Array]:
        return dict(self.proj)

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, x):
        b, t, e = x.shape
        h, d = self.heads, self.head_dim
        q = (x @ params["wq"]).reshape(b, t, h, d)
        k = (x @ params["wk"]).reshape(b, t, h, d)
        v = (x @ params["wv"]).reshape(b, t, h, d)
        if self.sp_axis:
            o = ring_attention(q, k, v, self.sp_axis, causal=self.causal)
        else:
            o = attention(q, k, v, causal=self.causal)
        return o.reshape(b, t, h * d) @ params["wo"]

    def initialize(self, device=None, **kwargs):
        b, t, e = self.input.shape
        if self.head_dim is None:
            assert e % self.heads == 0, \
                f"{self.name}: embed {e} not divisible by heads {self.heads}"
            self.head_dim = int(e) // self.heads
        hd = self.heads * self.head_dim
        if self.proj["wq"].mem is None:
            for key, shape in (("wq", (int(e), hd)), ("wk", (int(e), hd)),
                               ("wv", (int(e), hd)), ("wo", (hd, int(e)))):
                w = np.zeros(shape, np.float32)
                self._fill(w, self.weights_filling,
                           self.weights_stddev or 1.0 / np.sqrt(shape[0]))
                self.proj[key].mem = w
        self.create_output()
        for arr in self.proj.values():
            arr.initialize(device)
        super().initialize(device=device, **kwargs)


class GDMultiHeadAttention(GradientDescentBase):
    """vjp of the attention forward; per-layer lr/momentum/decay as usual."""
