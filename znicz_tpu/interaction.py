"""Interactive shell unit (rebuild of ``veles/interaction.py``).

The reference's ``Shell`` unit dropped into an IPython session inside the
running workflow (gated, e.g., to epoch ends) with the workflow in scope.
Same here; when IPython is unavailable (or ``interactive=False``) it falls
back to ``code.interact`` / no-op so headless runs never block."""

from __future__ import annotations

from znicz_tpu.core.units import Unit


class Shell(Unit):
    def __init__(self, workflow=None, name=None, interactive=True, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.interactive = bool(interactive)
        self.invocations = 0

    def run(self):
        self.invocations += 1
        if not self.interactive:
            return
        ns = {"workflow": self.workflow, "unit": self}
        banner = (f"znicz-tpu shell (workflow={self.workflow.name!r}); "
                  "objects: workflow, unit; Ctrl-D to continue training")
        try:
            from IPython import embed

            embed(banner1=banner, user_ns=ns, colors="neutral")
        except ImportError:
            import code

            code.interact(banner=banner, local=ns)
