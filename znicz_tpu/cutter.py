"""Cutter: crop a region of the input plane, fwd+bwd (rebuild of
``znicz/cutter.py``).  Padding kwargs follow the reference: the crop keeps
``[top:H-bottom, left:W-right]`` of an NHWC tensor; the backward pads
err_output back with zeros (vjp of a static slice)."""

from __future__ import annotations

from znicz_tpu.nn_units import ForwardBase, GradientDescentBase


class Cutter(ForwardBase):
    has_weights = False

    def __init__(self, workflow=None, name=None, padding=(0, 0, 0, 0),
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.padding = tuple(padding)       # (left, top, right, bottom)

    def output_shape_for(self, in_shape):
        b, h, w, c = in_shape
        left, top, right, bottom = self.padding
        return (b, h - top - bottom, w - left - right, c)

    def apply(self, params, x):
        left, top, right, bottom = self.padding
        h, w = x.shape[1], x.shape[2]
        return x[:, top:h - bottom, left:w - right, :]

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)


class GDCutter(GradientDescentBase):
    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)
