"""Master (rebuild of ``veles/server.py``).

The TPU rebuild's PRIMARY distribution is SPMD psum inside the fused step
(znicz_tpu/parallel) — zero-copy, synchronous, ICI-speed.  This module
preserves the reference's OTHER mode for capability parity: an
**asynchronous master/slave parameter server over ZeroMQ** (veles' only
strategy, SURVEY.md §2.4) for heterogeneous/elastic fleets that cannot join
a mesh:

  - slaves REQ jobs; the master REPs minibatch index assignments plus
    current params (``generate_data_for_slave`` on each trainable unit);
  - slaves push back weight DELTAS + evaluator metrics; the master applies
    them as they arrive — no barrier (the reference's async semantics);
  - slave join/leave is inherently elastic: a lost job is re-queued after
    ``job_timeout``.

Transport is pyzmq REP with pickle payloads, mirroring the reference's
pickle-over-ZMQ (trusted-cluster assumption documented there too).
"""

from __future__ import annotations

import pickle
import time
from typing import Dict, List, Optional

from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TRAIN


class Server:
    """Drive with ``serve()`` (blocks until the decision completes).

    workflow requirements: ``loader``, ``forwards``, ``decision`` — the
    graph built by StandardWorkflow or the samples.
    """

    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 job_timeout: float = 30.0):
        self.workflow = workflow
        self.endpoint = endpoint
        self.job_timeout = float(job_timeout)
        self.loader = workflow.loader
        self.decision = workflow.decision
        self.slaves: Dict[str, float] = {}          # id -> last seen
        self.registered: set = set()                # handshake-passed ids
        self.jobs_done = 0
        self.jobs_requeued = 0
        self.stale_updates = 0
        self.jobs_by_slave: Dict[str, int] = {}
        self._pending: List[dict] = []              # re-queued lost jobs
        self._inflight: Dict[int, tuple] = {}       # job_id -> (job, t, sid)
        self._job_seq = 0
        self._socket = None

    # -- params <-> payloads ---------------------------------------------------

    def _trainables(self):
        return [f for f in self.workflow.forwards if f.has_weights]

    def snapshot_params(self) -> Dict:
        return {f.name: f.generate_data_for_slave()
                for f in self._trainables()}

    def apply_deltas(self, deltas: Dict) -> None:
        for f in self._trainables():
            d = deltas.get(f.name)
            if not d:
                continue
            for k, arr in f.params().items():
                if k in d:
                    mem = arr.map_write()
                    mem += d[k]

    # -- job management --------------------------------------------------------

    def _reap_lost_jobs(self) -> None:
        now = time.time()
        lost = [jid for jid, (_, t, _) in self._inflight.items()
                if now - t > self.job_timeout]
        for jid in lost:
            job, _, sid = self._inflight.pop(jid)
            self._pending.append(job)
            self.jobs_requeued += 1

    def _next_job(self) -> Optional[dict]:
        self._reap_lost_jobs()
        if self._pending:
            return self._pending.pop(0)
        if bool(self.decision.complete):
            return None
        self.loader.run()
        import numpy as np

        return {
            "indices": np.array(self.loader.minibatch_indices.mem).copy(),
            "class": int(self.loader.minibatch_class),
            "size": int(self.loader.minibatch_size),
            "last_minibatch": bool(self.loader.last_minibatch),
            "class_ended": bool(self.loader.class_ended),
            "epoch_number": int(self.loader.epoch_number),
        }

    def _feed_decision(self, job: dict, metrics: dict) -> None:
        d = self.decision
        d.minibatch_class = job["class"]
        d.last_minibatch = job["last_minibatch"]
        d.class_ended = job["class_ended"]
        d.epoch_number = job["epoch_number"]
        d.class_lengths = self.loader.class_lengths
        d.minibatch_size = job["size"]
        d.minibatch_loss = float(metrics.get("loss", 0.0))
        if hasattr(d, "minibatch_n_err"):
            d.minibatch_n_err = int(metrics.get("n_err", 0))
            d.confusion_matrix = metrics.get("confusion")
        d.run()

    # -- the REP loop ----------------------------------------------------------

    def serve(self, linger: float = 3.0) -> None:
        """Blocks until the decision completes, then keeps draining for
        ``linger`` seconds so every slave's outstanding request gets a
        ``done`` reply (a request sent the instant training finished must
        not be orphaned — the slave would block in recv forever)."""
        import zmq

        ctx = zmq.Context.instance()
        self._socket = ctx.socket(zmq.REP)
        self._socket.bind(self.endpoint)
        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)
        deadline = None
        try:
            while True:
                if bool(self.decision.complete):
                    # jobs still out with crashed slaves will never be
                    # re-served — reap on timeout and drop, else serve()
                    # would poll forever waiting on a dead peer
                    self._reap_lost_jobs()
                    self._pending.clear()
                finished = (bool(self.decision.complete)
                            and not self._inflight and not self._pending)
                if finished and deadline is None:
                    deadline = time.time() + linger
                if deadline is not None and time.time() > deadline:
                    break
                if poller.poll(100):
                    req = pickle.loads(self._socket.recv())
                    self._socket.send(pickle.dumps(self._handle(req)))
        finally:
            self._socket.close(0)
            self._socket = None

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        sid = req.get("id", "?")
        if sid in self.registered:          # membership stamp gated on
            self.slaves[sid] = time.time()  # the handshake, like jobs
        if cmd == "register":
            from znicz_tpu.network_common import (PROTOCOL_VERSION,
                                                  check_handshake)

            refusal = check_handshake(req, self.workflow)
            if refusal:
                self.slaves.pop(sid, None)      # refused != member
                self.registered.discard(sid)
                return {"ok": False, "error": refusal}
            self.registered.add(sid)
            self.slaves[sid] = time.time()
            return {"ok": True, "version": PROTOCOL_VERSION,
                    "class_lengths": list(self.loader.class_lengths)}
        if cmd in ("job", "update") and sid not in self.registered:
            # the handshake is a gate, not advice: a refused (or never
            # registered) peer gets no params and applies no deltas
            return {"ok": False, "done": True,
                    "error": f"slave {sid!r} is not registered"}
        if cmd == "job":
            if bool(self.decision.complete):
                return {"done": True}
            job = self._next_job()
            if job is None:
                return {"done": True}
            self._job_seq += 1
            jid = self._job_seq
            self._inflight[jid] = (job, time.time(), sid)
            return {"job_id": jid, "job": job,
                    "params": self.snapshot_params(),
                    "train": job["class"] == TRAIN}
        if cmd == "update":
            jid = req.get("job_id")
            entry = self._inflight.pop(jid, None)
            if entry is None:
                # job already reaped/re-queued (slow slave) or finished —
                # the update must be DROPPED, not applied (async staleness
                # bound: one job, one accepted update)
                self.stale_updates += 1
                return {"ok": False, "stale": True}
            job, _, _ = entry
            if req.get("deltas"):
                self.apply_deltas(req["deltas"])
            # async arrivals after completion must not rewind decision state
            if not bool(self.decision.complete):
                self._feed_decision(job, req.get("metrics", {}))
            self.jobs_done += 1
            self.jobs_by_slave[sid] = self.jobs_by_slave.get(sid, 0) + 1
            return {"ok": True, "complete": bool(self.decision.complete)}
        return {"error": f"unknown cmd {cmd!r}"}
