"""Master (rebuild of ``veles/server.py``).

The TPU rebuild's PRIMARY distribution is SPMD psum inside the fused step
(znicz_tpu/parallel) — zero-copy, synchronous, ICI-speed.  This module
preserves the reference's OTHER mode for capability parity: an
**asynchronous master/slave parameter server over ZeroMQ** (veles' only
strategy, SURVEY.md §2.4) for heterogeneous/elastic fleets that cannot join
a mesh:

  - slaves REQ jobs; the master REPs minibatch index assignments plus
    current params (``generate_data_for_slave`` on each trainable unit);
  - slaves push back weight DELTAS + evaluator metrics; the master applies
    them as they arrive — no barrier (the reference's async semantics);
  - slave join/leave is inherently elastic: a lost job is re-queued after
    ``job_timeout``.

Transport is pyzmq REP with pickle payloads, mirroring the reference's
pickle-over-ZMQ (trusted-cluster assumption documented there too).
"""

from __future__ import annotations

import pickle
import time
from typing import Dict, List, Optional

from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TRAIN


class Server:
    """Drive with ``serve()`` (blocks until the decision completes).

    workflow requirements: ``loader``, ``forwards``, ``decision`` — the
    graph built by StandardWorkflow or the samples.
    """

    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 job_timeout: float = 30.0, segment_steps: int = None):
        from znicz_tpu.core.config import root

        self.workflow = workflow
        self.endpoint = endpoint
        self.job_timeout = float(job_timeout)
        #: >1 makes a TRAIN job a SEGMENT of up to this many consecutive
        #: non-tail minibatches (VERDICT r4 item 5 — fused-speed slaves:
        #: the slave runs the whole segment as one FusedTrainer scan
        #: dispatch and ships one aggregated delta; eval and epoch-tail
        #: jobs stay singletons so Decision control flow is unchanged).
        #: Config: root.common.engine.job_segment.  Unit-engine slaves
        #: handle segment jobs too (they loop the minibatches), so mixed
        #: fleets keep working.
        self.segment_steps = int(
            root.common.engine.get("job_segment", 1)
            if segment_steps is None else segment_steps)
        self.loader = workflow.loader
        self.decision = workflow.decision
        self.slaves: Dict[str, float] = {}          # id -> last seen
        self.registered: set = set()                # handshake-passed ids
        self.jobs_done = 0
        self.jobs_requeued = 0
        self.stale_updates = 0
        self.bad_updates = 0            # malformed replies refused+requeued
        self.jobs_by_slave: Dict[str, int] = {}
        self._pending: List[dict] = []              # re-queued lost jobs
        self._inflight: Dict[int, tuple] = {}       # job_id -> (job, t, sid)
        self._job_seq = 0
        self._hold = None                           # segment-overshoot mb
        self._socket = None

    # -- params <-> payloads ---------------------------------------------------

    def _trainables(self):
        return [f for f in self.workflow.forwards if f.has_weights]

    def snapshot_params(self) -> Dict:
        return {f.name: f.generate_data_for_slave()
                for f in self._trainables()}

    def apply_deltas(self, deltas: Dict) -> None:
        for f in self._trainables():
            d = deltas.get(f.name)
            if not d:
                continue
            for k, arr in f.params().items():
                if k in d:
                    mem = arr.map_write()
                    mem += d[k]

    # -- job management --------------------------------------------------------

    def _reap_lost_jobs(self) -> None:
        now = time.time()
        lost = [jid for jid, (_, t, _) in self._inflight.items()
                if now - t > self.job_timeout]
        for jid in lost:
            job, _, sid = self._inflight.pop(jid)
            self._pending.append(job)
            self.jobs_requeued += 1

    def _advance_mb(self) -> dict:
        if self._hold is not None:
            mb, self._hold = self._hold, None
            return mb
        self.loader.run()
        import numpy as np

        return {
            "indices": np.array(self.loader.minibatch_indices.mem).copy(),
            "class": int(self.loader.minibatch_class),
            "size": int(self.loader.minibatch_size),
            "last_minibatch": bool(self.loader.last_minibatch),
            "class_ended": bool(self.loader.class_ended),
            "epoch_number": int(self.loader.epoch_number),
        }

    def _outstanding(self):
        return [j for j, _, _ in self._inflight.values()] + self._pending

    def _tail_outstanding(self) -> bool:
        return any(j.get("last_minibatch") for j in self._outstanding())

    #: malformed replies tolerated per segment job before it is dropped
    #: instead of re-queued (bounds the refuse/refetch livelock a
    #: deterministically-broken slave would otherwise spin)
    MAX_BAD_REPLIES = 3

    #: reply sentinel: no job RIGHT NOW (epoch-boundary ordering), ask
    #: again — distinct from None (training done)
    _WAIT = {"wait": True}

    def _next_job(self) -> Optional[dict]:
        """Next job, with the async flow ORDERED at epoch boundaries
        (r5): minibatches within an epoch run fully asynchronously
        (reference semantics — updates overtake each other freely), but
        the epoch TAIL is issued only once every other job of its epoch
        has returned, and the next epoch starts only after the tail's
        update is in.  Without this, a segment job still in flight when
        the tail returns feeds the Decision across the epoch boundary —
        improvement/stop bookkeeping and the epoch metrics get
        misattributed, and the next epoch's eval jobs measure params
        missing the previous epoch's last updates.  The cost is one
        drained pipeline per epoch (the reference paid host syncs far
        more often than that)."""
        self._reap_lost_jobs()
        if self._pending:
            return self._pending.pop(0)
        if bool(self.decision.complete):
            return None
        if self._tail_outstanding():
            return self._WAIT               # epoch boundary: wait it out
        mb = self._advance_mb()
        if mb["last_minibatch"] and self._outstanding():
            self._hold = mb                 # tail waits for stragglers
            return self._WAIT
        if self.segment_steps <= 1 or mb["class"] != TRAIN or \
                mb["last_minibatch"]:
            return mb
        # collect consecutive non-tail TRAIN minibatches into ONE job —
        # the fused slave runs them as a single scan dispatch (non-tail
        # TRAIN feeds cannot flip Decision control flow, same invariant
        # the fused trainer's own segmented loop relies on)
        seg = [mb]
        while len(seg) < self.segment_steps:
            nxt = self._advance_mb()
            if nxt["class"] == TRAIN and not nxt["last_minibatch"]:
                seg.append(nxt)
            else:
                self._hold = nxt
                break
        if len(seg) == 1:
            return mb
        return {"kind": "segment", "minibatches": seg,
                "class": TRAIN, "size": sum(m["size"] for m in seg)}

    def _feed_decision(self, job: dict, metrics: dict) -> None:
        d = self.decision
        d.minibatch_class = job["class"]
        d.last_minibatch = job["last_minibatch"]
        d.class_ended = job["class_ended"]
        d.epoch_number = job["epoch_number"]
        d.class_lengths = self.loader.class_lengths
        d.minibatch_size = job["size"]
        d.minibatch_loss = float(metrics.get("loss", 0.0))
        if hasattr(d, "minibatch_n_err"):
            d.minibatch_n_err = int(metrics.get("n_err", 0))
            d.confusion_matrix = metrics.get("confusion")
        d.run()

    # -- the REP loop ----------------------------------------------------------

    def serve(self, linger: float = 3.0) -> None:
        """Blocks until the decision completes, then keeps draining for
        ``linger`` seconds so every slave's outstanding request gets a
        ``done`` reply (a request sent the instant training finished must
        not be orphaned — the slave would block in recv forever)."""
        import zmq

        ctx = zmq.Context.instance()
        self._socket = ctx.socket(zmq.REP)
        self._socket.bind(self.endpoint)
        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)
        deadline = None
        try:
            while True:
                if bool(self.decision.complete):
                    # jobs still out with crashed slaves will never be
                    # re-served — reap on timeout and drop, else serve()
                    # would poll forever waiting on a dead peer
                    self._reap_lost_jobs()
                    self._pending.clear()
                finished = (bool(self.decision.complete)
                            and not self._inflight and not self._pending)
                if finished and deadline is None:
                    deadline = time.time() + linger
                if deadline is not None and time.time() > deadline:
                    break
                if poller.poll(100):
                    req = pickle.loads(self._socket.recv())
                    self._socket.send(pickle.dumps(self._handle(req)))
        finally:
            self._socket.close(0)
            self._socket = None

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        sid = req.get("id", "?")
        if sid in self.registered:          # membership stamp gated on
            self.slaves[sid] = time.time()  # the handshake, like jobs
        if cmd == "register":
            from znicz_tpu.network_common import (PROTOCOL_VERSION,
                                                  check_handshake)

            refusal = check_handshake(req, self.workflow)
            if refusal:
                self.slaves.pop(sid, None)      # refused != member
                self.registered.discard(sid)
                return {"ok": False, "error": refusal}
            self.registered.add(sid)
            self.slaves[sid] = time.time()
            return {"ok": True, "version": PROTOCOL_VERSION,
                    "class_lengths": list(self.loader.class_lengths)}
        if cmd in ("job", "update") and sid not in self.registered:
            # the handshake is a gate, not advice: a refused (or never
            # registered) peer gets no params and applies no deltas
            return {"ok": False, "done": True,
                    "error": f"slave {sid!r} is not registered"}
        if cmd == "job":
            if bool(self.decision.complete):
                return {"done": True}
            job = self._next_job()
            if job is None:
                return {"done": True}
            if job is self._WAIT:
                return {"wait": True}       # client sleeps and re-asks
            self._job_seq += 1
            jid = self._job_seq
            self._inflight[jid] = (job, time.time(), sid)
            return {"job_id": jid, "job": job,
                    "params": self.snapshot_params(),
                    "train": job["class"] == TRAIN}
        if cmd == "update":
            jid = req.get("job_id")
            entry = self._inflight.pop(jid, None)
            if entry is None:
                # job already reaped/re-queued (slow slave) or finished —
                # the update must be DROPPED, not applied (async staleness
                # bound: one job, one accepted update)
                self.stale_updates += 1
                return {"ok": False, "stale": True}
            job, _, _ = entry
            if "minibatches" in job:
                # a segment reply must carry one metrics dict PER
                # minibatch — a short (or long) list means the slave ran
                # a different job than assigned, and zip() would silently
                # truncate the feed; refuse the whole update (deltas
                # included — they came from the same broken run) and
                # re-queue the job so the work is not lost.  Bounded: a
                # deterministically-broken slave (version skew) would
                # otherwise refetch and re-fail the same job forever —
                # after MAX_BAD_REPLIES the non-tail segment is dropped
                # (its metrics are lost like a stale update's; Decision
                # control flow never depends on non-tail feeds).
                ms = req.get("metrics") or []
                if len(ms) != len(job["minibatches"]):
                    import logging

                    self.bad_updates += 1
                    job["_bad_replies"] = job.get("_bad_replies", 0) + 1
                    requeue = job["_bad_replies"] < self.MAX_BAD_REPLIES
                    logging.getLogger("znicz").warning(
                        "slave %s: segment update carries %d metrics for "
                        "%d minibatches — refusing the update and %s",
                        sid, len(ms), len(job["minibatches"]),
                        "re-queueing the job" if requeue else
                        "DROPPING the job (repeated malformed replies)")
                    if requeue:
                        self._pending.append(job)
                    return {"ok": False,
                            "error": f"segment metrics length {len(ms)} "
                                     f"!= {len(job['minibatches'])}"}
            if req.get("deltas"):
                self.apply_deltas(req["deltas"])
            # async arrivals after completion must not rewind decision state
            if not bool(self.decision.complete):
                if "minibatches" in job:
                    # segment job: per-minibatch metrics, fed in order
                    ms = req.get("metrics") or []
                    for mb, m in zip(job["minibatches"], ms):
                        self._feed_decision(mb, m or {})
                else:
                    self._feed_decision(job, req.get("metrics", {}))
            self.jobs_done += 1
            self.jobs_by_slave[sid] = self.jobs_by_slave.get(sid, 0) + 1
            return {"ok": True, "complete": bool(self.decision.complete)}
        return {"error": f"unknown cmd {cmd!r}"}
