"""Master (rebuild of ``veles/server.py``).

The TPU rebuild's PRIMARY distribution is SPMD psum inside the fused step
(znicz_tpu/parallel) — zero-copy, synchronous, ICI-speed.  This module
preserves the reference's OTHER mode for capability parity: an
**asynchronous master/slave parameter server over ZeroMQ** (veles' only
strategy, SURVEY.md §2.4) for heterogeneous/elastic fleets that cannot join
a mesh:

  - slaves REQ jobs; the master REPs minibatch index assignments plus
    current params (``generate_data_for_slave`` on each trainable unit);
  - slaves push back weight DELTAS + evaluator metrics; the master applies
    them as they arrive — no barrier (the reference's async semantics);
  - slave join/leave is inherently elastic: a lost job is re-queued after
    ``job_timeout``.

Transport is pyzmq REP speaking wire protocol v3 (parallel/wire.py):
multipart messages — one small pickled metadata frame plus one raw
zero-copy buffer frame per tensor, with optional bf16/int8 delta
quantization (decoded transparently here, so quarantine inspects REAL
deltas) and optional zlib/lz4 compression of the params broadcast
(``root.common.engine.wire_compress``).  Only the metadata frame is
pickle (trusted-cluster assumption, like the reference's wire).  A peer
still framing v2 (one pickled blob) gets its reply — including the
protocol-version refusal — in v2 framing so it can read the reason.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Dict, List, Optional

import numpy as np

from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TRAIN
# the shared ISSUE-5 compat layer: registry counters readable/writable
# under their historical attribute names (web_status, resume snapshots)
from znicz_tpu.telemetry.metrics import registered_property as \
    _server_counter


def _codec_counter(name: str, doc: str) -> property:
    """A Server attribute that lives on its wire.Codec — readable AND
    writable under the historical name (restore_resume setattr's them)."""

    def fget(self):
        return getattr(self.codec, name)

    def fset(self, value):
        setattr(self.codec, name, value)

    return property(fget, fset, doc=doc)



class Server:
    """Drive with ``serve()`` (blocks until the decision completes).

    workflow requirements: ``loader``, ``forwards``, ``decision`` — the
    graph built by StandardWorkflow or the samples.

    Fault model (see README "Fault tolerance"): undecodable/malformed
    frames are refused and counted (``bad_frames``), never fatal; deltas
    with non-finite values or an exploded norm are quarantined (refused +
    re-queued under the bounded ``MAX_BAD_REPLIES`` policy) so one
    diverging slave cannot poison the global params; the reap timeout
    adapts to observed job durations; silent slaves are evicted from the
    membership table; and with ``resume_path`` set the master
    periodically snapshots its full training state so a crashed master
    restarts mid-training (``--master-resume``).
    """

    def __init__(self, workflow, endpoint: str = "tcp://127.0.0.1:5570",
                 job_timeout: float = 30.0, segment_steps: int = None,
                 resume_path: str = "", snapshot_every_s: float = None,
                 slave_ttl: float = None, min_slaves: int = None,
                 staleness_bound: int = None, staleness_weight: bool = None,
                 elastic_rehome: bool = None):
        from znicz_tpu.core.config import root

        self.workflow = workflow
        self.endpoint = endpoint
        self.job_timeout = float(job_timeout)
        #: >1 makes a TRAIN job a SEGMENT of up to this many consecutive
        #: non-tail minibatches (VERDICT r4 item 5 — fused-speed slaves:
        #: the slave runs the whole segment as one FusedTrainer scan
        #: dispatch and ships one aggregated delta; eval and epoch-tail
        #: jobs stay singletons so Decision control flow is unchanged).
        #: Config: root.common.engine.job_segment.  Unit-engine slaves
        #: handle segment jobs too (they loop the minibatches), so mixed
        #: fleets keep working.
        self.segment_steps = int(
            root.common.engine.get("job_segment", 1)
            if segment_steps is None else segment_steps)
        self.loader = workflow.loader
        self.decision = workflow.decision
        self.slaves: Dict[str, float] = {}          # id -> last seen
        self.registered: set = set()                # handshake-passed ids
        self.dead_slaves: Dict[str, float] = {}     # evicted id -> last seen
        self._ever_registered: set = set()
        #: ids that registered with ``relay=True`` (ISSUE 10): direct
        #: children that are aggregation-tree relays, not leaf slaves —
        #: the web_status topology panel marks them
        self.relays: set = set()
        #: pod-sliced slaves (ISSUE 18): id -> {"data": dp, "model": mp}
        #: piggybacked on the register handshake; single-device slaves
        #: are absent — web_status shows each leaf's slice shape
        self.slave_meshes: Dict[str, dict] = {}
        # -- telemetry (ISSUE 5): every master counter lives in the
        # process-wide registry (exported on /metrics) under
        # component="master"; the class-level _server_counter properties
        # keep the historical attribute names readable/writable for
        # web_status, resume snapshots and tests
        from znicz_tpu import telemetry

        _sc = telemetry.scope("master")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self._tracer = telemetry.tracer()
        # -- fleet observability (ISSUE 20): the master is the training
        # plane's coordinator — slave/relay updates piggyback spans and
        # journal events that land in the fleet stores behind
        # /trace.json?fleet=1 and /events.json
        telemetry.set_identity("master")
        self._quorum_degraded = False   # quorum journal episode latch
        self._t_obs_drain = 0.0         # self-ingest rate limiter (s)
        #: training-plane SLO (advisory burn rates on /slo.json; never
        #: a readiness gate): apply progress — accepted delta applies
        #: vs refused/stale/quarantined updates
        self.slo = telemetry.register_slo(telemetry.SloTracker(
            "training",
            window_fast_s=float(root.common.engine.get(
                "obs_slo_fast_window_s", 60.0)),
            window_slow_s=float(root.common.engine.get(
                "obs_slo_slow_window_s", 600.0))))
        self.slo.add_objective("apply_progress", target=float(
            root.common.engine.get("obs_slo_apply_progress", 0.99)))
        import uuid

        #: per-Server tag prefixing job trace_ids, so two masters'
        #: (or a restarted master's) trace_ids never collide when
        #: traces are merged across processes
        self._run_tag = uuid.uuid4().hex[:6]
        #: cold-path compression of the params broadcast ("none"/"zlib"/
        #: "lz4"); deltas are quantized by the CLIENT (engine.wire_dtype)
        self.wire_compress = str(
            root.common.engine.get("wire_compress", "none"))
        # -- wire-v3 traffic accounting (ISSUE 3 / ISSUE 4): one shared
        # Codec holds bytes_in/out, the per-direction tensor byte pairs
        # and bad_frames; the class-level properties below keep the
        # counters readable/writable under their historical names
        # (web_status, resume snapshots, tests)
        from znicz_tpu.parallel import wire

        self.codec = wire.Codec(compress=self.wire_compress,
                                owner="master")
        self.jobs_by_slave: Dict[str, int] = {}
        self._pending: List[dict] = []              # re-queued lost jobs
        self._inflight: Dict[int, tuple] = {}       # job_id -> (job, t, sid)
        self._job_seq = 0
        self._hold = None                           # segment-overshoot mb
        self._socket = None
        self._transport = None          # the serve()-time TransportLoop
        #: optional FaultSchedule for the transport loop's BUILT-IN
        #: ingress fault hook (ISSUE 14) — the cross-plane chaos soak
        #: installs ONE seeded schedule on every plane through this
        self.transport_chaos = None
        self._stop = False
        #: silent-slave eviction window, seconds (<= 0 disables); evicted
        #: ids keep their jobs_by_slave history for the final report
        self.slave_ttl = float(
            root.common.engine.get("slave_ttl", 60.0)
            if slave_ttl is None else slave_ttl)
        #: observed job round-trip durations; with >= 5 samples the reap
        #: timeout becomes adaptive (see effective_job_timeout)
        self._durations: collections.deque = collections.deque(maxlen=64)
        self.job_timeout_mult = float(
            root.common.engine.get("job_timeout_mult", 8.0))
        #: recent accepted-delta L2 norms; a new delta whose norm exceeds
        #: quarantine_norm_mult x the running median is refused
        self._delta_norms: collections.deque = collections.deque(maxlen=64)
        self.quarantine_norm_mult = float(
            root.common.engine.get("quarantine_norm_mult", 25.0))
        self._param_shapes = None       # lazy {layer: {param: shape}}
        # -- elastic async training (ISSUE 11) --------------------------
        #: quorum gate: below this many live members (direct leaf slaves
        #: + the subtree leaf counts live relays report on their job
        #: requests) dispatch pauses (job requests get ``wait``) and the
        #: dashboard/readiness report degraded.  0 disables the gate.
        self.min_slaves = int(
            root.common.engine.get("min_slaves", 0)
            if min_slaves is None else min_slaves)
        #: bounded staleness: a delta whose job's params stamp is more
        #: than this many applies behind the current apply counter is
        #: refused and its job re-queued (``stale_refused``) — a
        #: straggler's gradient from the distant past must not land on
        #: params it has never seen.  0 = unbounded (accept anything).
        self.staleness_bound = int(
            root.common.engine.get("staleness_bound", 0)
            if staleness_bound is None else staleness_bound)
        #: staleness-weighted apply: scale a delta by 1/(1+s) before it
        #: lands, so a thousand-slave pod rides through stragglers
        #: instead of letting their stale gradients fight fresh ones at
        #: full weight.  Fresh deltas (s == 0) are untouched.
        self.staleness_weight = bool(
            root.common.engine.get("staleness_weight", False)
            if staleness_weight is None else staleness_weight)
        #: runtime tree healing: when on, a LEAF slave registering
        #: directly at the master while live relays exist is handed a
        #: ``rehome`` endpoint (a recently-seen relay) in its register
        #: reply — an orphan that fell back after its relay died is
        #: steered back under the tree instead of staying a star child
        self.elastic_rehome = bool(
            root.common.engine.get("elastic_rehome", False)
            if elastic_rehome is None else elastic_rehome)
        #: the apply counter — the staleness clock: one tick per
        #: accepted delta apply (job replies are stamped with it; the
        #: slave echoes the stamp back with its update)
        self._apply_step = 0
        # -- unified transport core (ISSUE 14) --------------------------
        #: per-slave ingress admission — the serving plane's TokenBucket
        #: lifted to the master (transport/admission.py): a slave
        #: flooding JOB requests past ``ingress_rate_limit``/s is
        #: answered ``wait`` (counted ``rate_limited_ingress``, never
        #: fatal, never a membership strike) instead of monopolizing
        #: the REP loop.  UPDATES are always admitted: they carry
        #: finished work, and refusing one would trash the compute
        #: behind it.  0 disables (the default — a cooperative fleet).
        from znicz_tpu.transport import AdmissionTable
        self._ingress = AdmissionTable(
            rate=float(root.common.engine.get("ingress_rate_limit", 0.0)),
            burst=float(root.common.engine.get("ingress_rate_burst", 0.0)))
        #: training-job deadline propagation (ISSUE 14): every job is
        #: stamped with a ``deadline_ms`` BUDGET (= the live reap
        #: timeout — past it the master re-queues the job anyway, so
        #: computing it is pure waste); slaves and relays drop expired
        #: jobs uncomputed.  PR 6's serving contract, fleet-wide.
        self.job_deadline = bool(
            root.common.engine.get("job_deadline", True))
        #: per-relay subtree leaf counts, reported by relays on their
        #: job requests (``leaves``) — the quorum's view through trees
        self._relay_leaves: Dict[str, int] = {}
        #: relay id -> the bind it serves children at (from its register
        #: message) — the re-planner's and rehome's address book
        self.relay_binds: Dict[str, str] = {}
        self._tree_plan: Optional[dict] = None
        self._rehome_rr = 0
        #: per-leaf staleness histograms (telemetry family
        #: ``update_staleness`` labeled by leaf), created lazily
        self._stale_hist: Dict[str, object] = {}
        from znicz_tpu.telemetry.metrics import weak_fn
        _sc.gauge("quorum_members", "live training members (quorum view)",
                  fn=weak_fn(self, lambda s: s.member_count()))
        _sc.gauge("quorum_degraded", "1 while below the min_slaves gate",
                  fn=weak_fn(self, lambda s: 1.0 if s.degraded() else 0.0))
        # -- LR schedules under master/slave (ISSUE 10 satellite): the
        # master owns the train-iteration clock.  Any LearningRateAdjust
        # unit's policy bindings are evaluated HERE at dispatch and the
        # scheduled per-layer (lr, lr_bias) ships inside each TRAIN
        # minibatch's payload — slaves apply them per job, so schedules
        # advance exactly as in local training (modulo the async
        # reordering the protocol already has)
        from znicz_tpu.lr_adjust import LearningRateAdjust

        self._lr_bindings = []
        for u in workflow.units:
            if isinstance(u, LearningRateAdjust):
                self._lr_bindings.extend(u._bindings)
        self._lr_iteration = 0          # TRAIN minibatches dispatched
        #: crash-resume: when set, serve() writes the master's full
        #: training state here every snapshot_every_s seconds, and a
        #: Server constructed while the file exists restores from it
        #: (the launcher's --master-resume)
        self.resume_path = str(resume_path or "")
        self.snapshot_every_s = float(
            root.common.engine.get("master_snapshot_s", 10.0)
            if snapshot_every_s is None else snapshot_every_s)
        self._last_resume_save = 0.0
        self.resumed = False
        if self.resume_path and os.path.exists(self.resume_path):
            self.restore_resume(self.resume_path)

    # -- params <-> payloads ---------------------------------------------------

    def _trainables(self):
        return [f for f in self.workflow.forwards if f.has_weights]

    def snapshot_params(self) -> Dict:
        return {f.name: f.generate_data_for_slave()
                for f in self._trainables()}

    def apply_deltas(self, deltas: Dict, scale: float = 1.0) -> None:
        """Land a delta set on the global params and advance the apply
        counter (the staleness clock).  ``scale`` < 1 is the
        staleness-weighted apply (ISSUE 11): a late gradient still
        contributes direction, at discounted magnitude."""
        for f in self._trainables():
            d = deltas.get(f.name)
            if not d:
                continue
            for k, arr in f.params().items():
                if k in d:
                    mem = arr.map_write()
                    if scale == 1.0:
                        mem += d[k]
                    else:
                        mem += np.asarray(d[k], mem.dtype) * mem.dtype.type(
                            scale)
        self._apply_step += 1

    # -- counters (one home: the telemetry registry) ---------------------------

    #: master counters registered under component="master" (ISSUE 5):
    #: name -> HELP text (also each property's docstring)
    COUNTERS = {
        "jobs_done": "jobs completed",   # shared family w/ slave
        "jobs_requeued": "lost/refused jobs re-queued",
        "stale_updates": "updates dropped: job already reaped/finished",
        "bad_updates": "malformed replies refused+requeued",
        "quarantined_updates": "non-finite / norm-exploded deltas refused",
        "reregistrations": "re-registers (slave reconnects)",
        "resume_saves": "crash-resume snapshots written",
        "updates_received": "update messages seen (any outcome)",
        "update_bytes_in": "wire bytes of update messages",
        "prefetch_hit": "jobs served to prefetch requests",
        "aggregated_updates": "pre-aggregated relay updates accepted",
        # elastic async training (ISSUE 11)
        "stale_refused": "deltas refused: staleness beyond the bound",
        "weighted_applies": "applies scaled down by staleness",
        "replans": "runtime tree re-plans (relay membership changes)",
        "preemptions_ridden": "members lost mid-run and ridden out",
        # unified transport core (ISSUE 14)
        "rate_limited_ingress": "job requests answered wait: per-slave "
                                "ingress rate limit",
    }

    # (the historical attribute properties are generated from COUNTERS
    # right after the class body — one source of truth per counter)

    # -- wire accounting (one home: the Codec) ---------------------------------

    bytes_in = _codec_counter(
        "bytes_in", "wire bytes received (all frames)")
    bytes_out = _codec_counter(
        "bytes_out", "wire bytes sent (all frames)")
    bad_frames = _codec_counter(
        "bad_frames", "undecodable/garbage frames refused")
    #: f32-equivalent vs actual tensor bytes, per direction: ``in`` is
    #: dominated by (possibly quantized) deltas, ``out`` by the
    #: (possibly compressed) params broadcast
    tensor_bytes_raw_in = _codec_counter(
        "tensor_bytes_raw_in", "f32-equivalent tensor bytes received")
    tensor_bytes_wire_in = _codec_counter(
        "tensor_bytes_wire_in", "actual tensor bytes received")
    tensor_bytes_raw_out = _codec_counter(
        "tensor_bytes_raw_out", "f32-equivalent tensor bytes sent")
    tensor_bytes_wire_out = _codec_counter(
        "tensor_bytes_wire_out", "actual tensor bytes sent")

    # -- job management --------------------------------------------------------

    def compression_ratio(self, direction: str = "both"
                          ) -> Optional[float]:
        """f32-equivalent tensor bytes / tensor bytes actually on the
        wire — ``"in"`` (quantized deltas), ``"out"`` (optionally
        compressed params broadcast) or ``"both"``; None before any
        tensor traffic in that direction."""
        return self.codec.compression_ratio(direction)

    def bytes_per_update(self) -> Optional[float]:
        """Mean wire bytes of one slave->master update message — the
        acceptance metric the int8 wire must beat the f32/pickle wire on
        (ISSUE 3); None before the first update."""
        if not self.updates_received:
            return None
        return self.update_bytes_in / self.updates_received

    def effective_job_timeout(self) -> float:
        """The reap timeout, adapted from observed job durations: the
        configured ``job_timeout`` is the ceiling (dead-slave safety
        net), but once >= 5 round trips have been observed a straggler is
        re-dispatched after ``job_timeout_mult`` x the median duration
        (+1s slack) — fast fleets recover lost jobs in seconds without
        punishing slow-but-alive (e.g. unit-engine) slaves, whose own
        durations raise the median."""
        durations = list(self._durations)   # copy: read from other threads
        if len(durations) < 5:
            return self.job_timeout
        adaptive = self.job_timeout_mult * float(np.median(durations)) + 1.0
        return min(self.job_timeout, max(adaptive, 0.5))

    def _reap_lost_jobs(self) -> None:
        now = time.time()
        timeout = self.effective_job_timeout()
        lost = [jid for jid, (_, t, _) in self._inflight.items()
                if now - t > timeout]
        for jid in lost:
            job, _, sid = self._inflight.pop(jid)
            self._pending.append(job)
            self._m["jobs_requeued"].inc()

    def _evict_dead_slaves(self) -> None:
        """Membership hygiene: a slave silent past ``slave_ttl`` is moved
        to ``dead_slaves`` (its jobs_by_slave history survives for the
        report) and must re-register to work again; its in-flight jobs
        come back via the normal reaper."""
        if self.slave_ttl <= 0:
            return
        now = time.time()
        for sid in [s for s, seen in self.slaves.items()
                    if now - seen > self.slave_ttl]:
            import logging

            self.dead_slaves[sid] = self.slaves.pop(sid)
            self.registered.discard(sid)
            self.slave_meshes.pop(sid, None)
            if not bool(self.decision.complete):
                # a member lost while training continues: a preemption
                # the elastic mode rode out (ISSUE 11)
                self._m["preemptions_ridden"].inc()
                from znicz_tpu import telemetry

                telemetry.emit("preemption", "training", slave=sid,
                               ttl_s=self.slave_ttl,
                               members=self.member_count())
            if sid in self.relays:
                # a relay eviction changes the TREE, not just the
                # membership: re-plan so rehome targets and the
                # topology view drop the dead subtree immediately
                self._relay_leaves.pop(sid, None)
                self._replan(f"relay {sid} evicted")
            logging.getLogger("znicz").info(
                "slave %s evicted (silent for %.0fs)", sid, self.slave_ttl)

    def _quarantine_reason(self, deltas: Dict,
                           n_contrib: int = 1) -> Optional[str]:
        """Refusal reason for a delta payload that must never touch the
        global params: a leaf whose shape does not match the target param
        (apply_deltas would raise mid-apply, tearing the update), any
        non-finite value, or a global L2 norm beyond
        ``quarantine_norm_mult`` x the running median of accepted-update
        norms (>= 5 samples).  Accepted norms feed the history;
        quarantined ones do not (a diverging slave must not drag the
        median up to its own level).  ``n_contrib`` > 1 (a relay's
        pre-aggregated sum of that many child deltas, ISSUE 10)
        normalizes the norm per contributor, so the history and the
        threshold stay comparable between star and tree topologies.
        NEVER raises — a payload too broken to inspect is itself the
        quarantine reason (by the time this runs the job has left
        _inflight, so an exception would lose it)."""
        try:
            if self._param_shapes is None:   # fixed after initialize()
                self._param_shapes = {
                    f.name: {k: tuple(a.shape)
                             for k, a in f.params().items()}
                    for f in self._trainables()}
            shapes = self._param_shapes
            total = 0.0
            for name, layer in deltas.items():
                for k, arr in (layer or {}).items():
                    a = np.asarray(arr, np.float64)
                    want = shapes.get(name, {}).get(k)
                    if want is not None and tuple(a.shape) != want:
                        return (f"shape {tuple(a.shape)} != {want} "
                                f"for {name}.{k}")
                    if not np.all(np.isfinite(a)):
                        return "non-finite values"
                    total += float(np.dot(a.ravel(), a.ravel()))
        except Exception as exc:
            return f"undecodable delta payload: {exc!r}"
        norm = float(np.sqrt(total)) / max(1, int(n_contrib))
        if len(self._delta_norms) >= 5:
            med = float(np.median(self._delta_norms))
            if med > 0.0 and norm > self.quarantine_norm_mult * med:
                return (f"norm {norm:.3g} > {self.quarantine_norm_mult:g} "
                        f"x median {med:.3g}")
        self._delta_norms.append(norm)
        return None

    # -- elastic async training (ISSUE 11) -------------------------------------

    @property
    def apply_step(self) -> int:
        """The apply counter — the staleness clock job stamps count in."""
        return self._apply_step

    def _staleness(self, step, sid: str) -> int:
        """Applies elapsed since the job's params stamp (0 for an old
        peer that echoes no stamp), observed into the per-leaf
        ``update_staleness`` histogram family.  NEVER raises: it runs
        AFTER the job left ``_inflight``, so a garbage stamp from a
        broken peer must degrade to "fresh", not lose the job."""
        if step is None:
            return 0
        try:
            s = max(0, self._apply_step - int(step))
        except (TypeError, ValueError):
            return 0
        hist = self._stale_hist.get(sid)
        if hist is None:
            from znicz_tpu import telemetry

            hist = telemetry.scope("master").histogram(
                "update_staleness",
                "delta staleness in applies, at arrival", size=256,
                leaf=str(sid))
            self._stale_hist[sid] = hist
        hist.observe(s)
        return s

    def _stale_scale(self, s) -> float:
        """The staleness-weighted apply factor ``1/(1+s)`` (ISSUE 11);
        1.0 when weighting is off or the delta is fresh."""
        if not self.staleness_weight:
            return 1.0
        w = 1.0 / (1.0 + max(0.0, float(s)))
        if w < 1.0:
            self._m["weighted_applies"].inc()
        return w

    def _refuse_stale(self, job: dict, sid: str, s) -> dict:
        """Bounded staleness: beyond ``staleness_bound`` the delta must
        never land — refused, counted, and the job re-queued WITHOUT a
        bad-reply strike (staleness is the fleet's timing, not a
        malformed reply; a straggler's job must be re-dispatched, not
        dropped).  Bounded on its OWN budget though: a peer whose stamp
        echo is deterministically broken (every delta beyond the bound
        forever) must not livelock the refuse/refetch cycle — after
        ``MAX_BAD_REPLIES`` stale refusals a non-tail job is dropped
        like a repeatedly-malformed one (a TAIL job is always re-queued:
        the epoch cannot close without its feed)."""
        import logging

        self._m["stale_refused"].inc()
        self.slo.record("apply_progress", False)
        job["_stale_refusals"] = job.get("_stale_refusals", 0) + 1
        requeue = (bool(job.get("last_minibatch"))
                   or job["_stale_refusals"] < self.MAX_BAD_REPLIES)
        logging.getLogger("znicz").info(
            "slave %s: delta staleness %s > bound %d — refused and %s",
            sid, s, self.staleness_bound,
            "re-queued" if requeue else
            "DROPPED (repeated stale refusals)")
        if requeue:
            self._pending.append(job)
        return {"ok": False, "stale_refused": True, "staleness": int(s),
                "error": f"delta staleness {s} exceeds the "
                         f"{self.staleness_bound}-apply bound"}

    def staleness_summary(self) -> Dict[str, dict]:
        """Per-leaf staleness digest for the web_status panel:
        observation count, p50 and max over the recent window."""
        out = {}
        for sid, h in sorted(dict(self._stale_hist).items()):
            data = h.window()
            if data.size:
                out[sid] = {"count": int(h.count),
                            "p50": float(np.median(data)),
                            "max": int(data.max())}
        return out

    def member_count(self) -> int:
        """Live training membership, the quorum's denominator: direct
        non-relay slaves plus the subtree leaf counts live relays report
        on their job requests (``leaves``) — so a preempted subtree
        shrinks the count as soon as its relay stops polling or reports
        fewer children."""
        slaves = dict(self.slaves)      # copy: read from the web thread
        n = sum(1 for sid in slaves if sid not in self.relays)
        n += sum(int(self._relay_leaves.get(sid, 0))
                 for sid in slaves if sid in self.relays)
        return n

    def quorum_met(self) -> bool:
        return self.min_slaves <= 0 or self.member_count() >= \
            self.min_slaves

    def degraded(self) -> bool:
        """True while the fleet sits below the quorum gate mid-run —
        the /readyz-style membership signal (web_status.readiness)."""
        return not self.quorum_met() and not bool(self.decision.complete)

    def _note_quorum(self) -> None:
        """Journal the quorum-gate TRANSITIONS (ISSUE 20): degraded
        once when membership falls below ``min_slaves`` mid-run,
        restored once when it recovers — an episode latch, not a
        per-tick emit."""
        if self.min_slaves <= 0:
            return
        deg = self.degraded()
        if deg == self._quorum_degraded:
            return
        from znicz_tpu import telemetry

        telemetry.emit("quorum_degraded" if deg else "quorum_restored",
                       "training", members=self.member_count(),
                       min_slaves=self.min_slaves)
        self._quorum_degraded = deg

    def _replan(self, why: str) -> None:
        """``plan_tree`` promoted to a RUNTIME re-planner (ISSUE 11):
        whenever live-relay membership changes (a relay joins, or TTL
        eviction removes one) the master recomputes its view of the
        tree — the live relays, their binds and reported subtree sizes
        — which is what ``rehome`` assignment and the topology panel
        dispatch against.  Orphaned children re-home through the
        existing re-registration path and lost jobs come back through
        the existing reaper, so a re-plan never loses or double-applies
        work."""
        import logging

        slaves = dict(self.slaves)
        live = [{"id": sid, "bind": self.relay_binds.get(sid),
                 "leaves": int(self._relay_leaves.get(sid, 0))}
                for sid in sorted(slaves) if sid in self.relays]
        self._tree_plan = {"relays": live, "reason": why,
                           "members": self.member_count()}
        self._m["replans"].inc()
        from znicz_tpu import telemetry

        telemetry.emit("replan", "training", why=why,
                       relays=len(live),
                       members=self._tree_plan["members"])
        logging.getLogger("znicz").info(
            "tree re-planned (%s): %d live relays, %d members", why,
            len(live), self._tree_plan["members"])

    @property
    def tree_plan(self) -> Optional[dict]:
        plan = self._tree_plan
        return None if plan is None else dict(plan)

    def _rehome_target(self) -> Optional[str]:
        """A live relay bind for an orphaned leaf to re-home behind —
        round-robin over relays seen RECENTLY (well inside slave_ttl:
        a healthy relay polls sub-second, so a relay silent for
        several seconds is not a safe rehome target even before its
        TTL eviction)."""
        now = time.time()
        window = min(self.slave_ttl, 10.0) if self.slave_ttl > 0 else 10.0
        targets = [self.relay_binds[sid]
                   for sid, seen in sorted(dict(self.slaves).items())
                   if sid in self.relays and sid in self.relay_binds
                   and now - seen <= window]
        if not targets:
            return None
        self._rehome_rr = (self._rehome_rr + 1) % (1 << 30)
        return targets[self._rehome_rr % len(targets)]

    def jobs_ledger(self) -> Dict:
        """The no-silent-loss / no-double-apply cross-check (ISSUE 11
        acceptance): every dispatched job id ends in EXACTLY one bucket
        — done, reaper/sibling re-queue, refused (malformed /
        quarantined / stale-beyond-bound; the re-queued copy
        re-dispatches under a NEW id), or still in flight.  ``balanced``
        is the invariant; it holds for any master that never restored a
        resume snapshot (restore jumps the job-id sequence by design,
        so pre-crash ids can never collide)."""
        out = {
            "dispatched": int(self._job_seq),
            "jobs_done": int(self.jobs_done),
            "jobs_requeued": int(self.jobs_requeued),
            "bad_updates": int(self.bad_updates),
            "quarantined_updates": int(self.quarantined_updates),
            "stale_refused": int(self.stale_refused),
            "in_flight": len(self._inflight),
        }
        out["balanced"] = out["dispatched"] == (
            out["jobs_done"] + out["jobs_requeued"] + out["bad_updates"]
            + out["quarantined_updates"] + out["stale_refused"]
            + out["in_flight"])
        return out

    def _scheduled_hypers(self) -> Optional[Dict]:
        """The per-layer (lr, lr_bias) a TRAIN minibatch dispatched at
        the CURRENT train iteration should use, per the workflow's
        LearningRateAdjust bindings — the unit-path clock exactly:
        minibatch k trains at the rate lr_adjust wrote after minibatch
        k-1 (``pol(base, k-1)``; minibatch 0 at the configured base)."""
        if not self._lr_bindings:
            return None
        it = self._lr_iteration
        out = {}
        for gd, base, base_bias, pol, bias_pol in self._lr_bindings:
            if it == 0:
                lr, lr_bias = base, base_bias
            else:
                lr, lr_bias = pol(base, it - 1), bias_pol(base_bias,
                                                          it - 1)
            out[gd.forward.name] = (float(lr), float(lr_bias))
        return out

    def _advance_mb(self) -> dict:
        if self._hold is not None:
            mb, self._hold = self._hold, None
            return mb
        self.loader.run()
        import numpy as np

        mb = {
            "indices": np.array(self.loader.minibatch_indices.mem).copy(),
            "class": int(self.loader.minibatch_class),
            "size": int(self.loader.minibatch_size),
            "last_minibatch": bool(self.loader.last_minibatch),
            "class_ended": bool(self.loader.class_ended),
            "epoch_number": int(self.loader.epoch_number),
        }
        if mb["class"] == TRAIN:
            # scheduled hypers ride the minibatch payload (a re-queued
            # job keeps its stamp: the schedule is per-minibatch, not
            # per-delivery); relays forward job payloads opaquely
            hypers = self._scheduled_hypers()
            if hypers:
                mb["hypers"] = hypers
            self._lr_iteration += 1
        return mb

    def _outstanding(self):
        return [j for j, _, _ in self._inflight.values()] + self._pending

    def _tail_outstanding(self) -> bool:
        return any(j.get("last_minibatch") for j in self._outstanding())

    #: malformed replies tolerated per segment job before it is dropped
    #: instead of re-queued (bounds the refuse/refetch livelock a
    #: deterministically-broken slave would otherwise spin)
    MAX_BAD_REPLIES = 3

    #: reply sentinel: no job RIGHT NOW (epoch-boundary ordering), ask
    #: again — distinct from None (training done)
    _WAIT = {"wait": True}

    def _next_job(self) -> Optional[dict]:
        """Next job, with the async flow ORDERED at epoch boundaries
        (r5): minibatches within an epoch run fully asynchronously
        (reference semantics — updates overtake each other freely), but
        the epoch TAIL is issued only once every other job of its epoch
        has returned, and the next epoch starts only after the tail's
        update is in.  Without this, a segment job still in flight when
        the tail returns feeds the Decision across the epoch boundary —
        improvement/stop bookkeeping and the epoch metrics get
        misattributed, and the next epoch's eval jobs measure params
        missing the previous epoch's last updates.  The cost is one
        drained pipeline per epoch (the reference paid host syncs far
        more often than that)."""
        self._reap_lost_jobs()
        if self._pending:
            return self._pending.pop(0)
        if bool(self.decision.complete):
            return None
        if self._tail_outstanding():
            return self._WAIT               # epoch boundary: wait it out
        mb = self._advance_mb()
        if mb["last_minibatch"] and self._outstanding():
            self._hold = mb                 # tail waits for stragglers
            return self._WAIT
        if self.segment_steps <= 1 or mb["class"] != TRAIN or \
                mb["last_minibatch"]:
            return mb
        # collect consecutive non-tail TRAIN minibatches into ONE job —
        # the fused slave runs them as a single scan dispatch (non-tail
        # TRAIN feeds cannot flip Decision control flow, same invariant
        # the fused trainer's own segmented loop relies on)
        seg = [mb]
        while len(seg) < self.segment_steps:
            nxt = self._advance_mb()
            if nxt["class"] == TRAIN and not nxt["last_minibatch"]:
                seg.append(nxt)
            else:
                self._hold = nxt
                break
        if len(seg) == 1:
            return mb
        return {"kind": "segment", "minibatches": seg,
                "class": TRAIN, "size": sum(m["size"] for m in seg)}

    def _refuse_update(self, job: dict, sid: str, why: str,
                       counter: str = "bad_updates",
                       quarantined: bool = False) -> dict:
        """The ONE home for the refuse/requeue/drop policy on a bad
        update (malformed payloads and quarantined deltas alike):
        counted under ``counter``, logged, and the job (already popped
        from _inflight) re-queued under the bounded MAX_BAD_REPLIES
        policy — except a TAIL job, which is always re-queued because
        the epoch cannot close without its feed."""
        import logging

        self._m[counter].inc()
        self.slo.record("apply_progress", False)
        job["_bad_replies"] = job.get("_bad_replies", 0) + 1
        requeue = (bool(job.get("last_minibatch"))
                   or job["_bad_replies"] < self.MAX_BAD_REPLIES)
        logging.getLogger("znicz").warning(
            "slave %s: %s — refusing the update and %s", sid, why,
            "re-queueing the job" if requeue else
            "DROPPING the job (repeated bad replies)")
        if requeue:
            self._pending.append(job)
        rep = {"ok": False, "error": why}
        if quarantined:
            rep["quarantined"] = True
        return rep

    def _feed_decision(self, job: dict, metrics: dict) -> None:
        d = self.decision
        d.minibatch_class = job["class"]
        d.last_minibatch = job["last_minibatch"]
        d.class_ended = job["class_ended"]
        d.epoch_number = job["epoch_number"]
        d.class_lengths = self.loader.class_lengths
        d.minibatch_size = job["size"]
        d.minibatch_loss = float(metrics.get("loss", 0.0))
        if hasattr(d, "minibatch_n_err"):
            d.minibatch_n_err = int(metrics.get("n_err", 0))
            d.confusion_matrix = metrics.get("confusion")
        d.run()

    # -- crash-resume ----------------------------------------------------------

    def save_resume(self, path: str) -> None:
        """Write the master's full training state: params/velocities and
        loader/decision/prng cursors via the snapshotter, plus the
        server-side extras a restart needs — the loader's intra-epoch
        position, every outstanding job (in flight + pending: the
        minibatches a crash would otherwise silently lose), the job-id
        sequence (so pre-crash updates stay stale instead of colliding),
        the mid-epoch decision accumulators, and the robustness
        counters/history."""
        from znicz_tpu import snapshotter

        snap = snapshotter.collect(self.workflow)
        d = self.decision
        acc = {"loss": list(d._acc_loss), "batches": list(d._acc_batches)}
        if hasattr(d, "_acc_n_err"):
            acc["n_err"] = list(d._acc_n_err)
            acc["samples"] = list(d._acc_samples)
            acc["confusion"] = [None if c is None else np.asarray(c)
                                for c in d._acc_confusion]
        snap["master"] = {
            "loader_pos": int(self.loader._pos),
            "hold": self._hold,
            "outstanding": [
                {k: v for k, v in j.items()
                 if k not in ("_bad_replies", "_stale_refusals")}
                for j in self._outstanding()],
            "job_seq": self._job_seq,
            "jobs_by_slave": dict(self.jobs_by_slave),
            "lr_iteration": self._lr_iteration,
            "apply_step": self._apply_step,
            "decision_acc": acc,
            "durations": list(self._durations),
            "delta_norms": list(self._delta_norms),
            "counters": {
                "jobs_done": self.jobs_done,
                "jobs_requeued": self.jobs_requeued,
                "stale_updates": self.stale_updates,
                "bad_updates": self.bad_updates,
                "bad_frames": self.bad_frames,
                "quarantined_updates": self.quarantined_updates,
                "reregistrations": self.reregistrations,
                "bytes_in": self.bytes_in,
                "bytes_out": self.bytes_out,
                "updates_received": self.updates_received,
                "update_bytes_in": self.update_bytes_in,
                "prefetch_hit": self.prefetch_hit,
                "aggregated_updates": self.aggregated_updates,
                # elastic accounting (ISSUE 11): a master crash
                # mid-degraded-mode must restore EXACT books
                "stale_refused": self.stale_refused,
                "weighted_applies": self.weighted_applies,
                "replans": self.replans,
                "preemptions_ridden": self.preemptions_ridden,
                "rate_limited_ingress": self.rate_limited_ingress,
                "tensor_bytes_raw_in": self.tensor_bytes_raw_in,
                "tensor_bytes_wire_in": self.tensor_bytes_wire_in,
                "tensor_bytes_raw_out": self.tensor_bytes_raw_out,
                "tensor_bytes_wire_out": self.tensor_bytes_wire_out,
            },
        }
        # compression keyed to the extension: Snapshotter.load picks its
        # opener by suffix, so a gzipped file under a non-.gz name would
        # be unreadable at restart — the one moment it must not be
        snapshotter.write_host_pickle(
            path, snap, "gz" if path.endswith(".gz") else "none")
        self._m["resume_saves"].inc()

    def restore_resume(self, path: str) -> None:
        """Restore from a ``save_resume`` file onto the (initialized)
        workflow: training continues from the snapshot point — jobs that
        were outstanding at save time are re-queued, updates issued after
        it are re-done (the stream replays; nothing is silently lost),
        and slaves simply re-register and keep working."""
        import logging

        from znicz_tpu import snapshotter

        snap = snapshotter.Snapshotter.load(path)
        snapshotter.restore(self.workflow, snap)
        m = snap.get("master", {})
        self.loader._pos = int(m.get("loader_pos", 0))
        self._hold = m.get("hold")
        self._pending = list(m.get("outstanding", []))
        self._inflight.clear()
        # jobs issued AFTER the snapshot reused ids the snapshot never
        # saw — restart far past them so a surviving slave's re-sent
        # pre-crash update can only ever be stale, never collide with a
        # freshly-issued id (it would be applied against the wrong job)
        self._job_seq = int(m.get("job_seq", 0)) + 100_000
        self.jobs_by_slave = dict(m.get("jobs_by_slave", {}))
        self._lr_iteration = int(m.get("lr_iteration", 0))
        self._apply_step = int(m.get("apply_step", 0))
        self._durations = collections.deque(m.get("durations", []),
                                            maxlen=64)
        self._delta_norms = collections.deque(m.get("delta_norms", []),
                                              maxlen=64)
        for name, value in m.get("counters", {}).items():
            setattr(self, name, int(value))
        acc = m.get("decision_acc", {})
        d = self.decision
        if "loss" in acc:
            d._acc_loss = list(acc["loss"])
            d._acc_batches = list(acc["batches"])
        if "n_err" in acc and hasattr(d, "_acc_n_err"):
            d._acc_n_err = list(acc["n_err"])
            d._acc_samples = list(acc["samples"])
            d._acc_confusion = list(acc["confusion"])
        self.resumed = True
        logging.getLogger("znicz").info(
            "master resumed from %s: epoch %d, %d jobs done, "
            "%d outstanding jobs re-queued", path,
            int(self.loader.epoch_number), self.jobs_done,
            len(self._pending))

    def _maybe_save_resume(self) -> None:
        if not self.resume_path or self.snapshot_every_s <= 0:
            return
        if bool(self.decision.complete):
            return
        now = time.time()
        if now - self._last_resume_save < self.snapshot_every_s:
            return
        self._last_resume_save = now
        self.save_resume(self.resume_path)

    # -- the REP loop ----------------------------------------------------------

    def stop(self) -> None:
        """Ask serve() to exit at its next poll tick WITHOUT the
        end-of-run drain — the chaos harness's simulated master crash
        (state survives only in the periodic resume snapshot)."""
        self._stop = True

    def serve(self, linger: float = 3.0) -> None:
        """Blocks until the decision completes, then keeps draining for
        ``linger`` seconds so every slave's outstanding request gets a
        ``done`` reply (a request sent the instant training finished must
        not be orphaned — the slave would block in recv forever).

        Rides the unified :class:`~znicz_tpu.transport.TransportLoop`
        (ISSUE 14): REP lockstep dispatch of :meth:`_reply_frames`
        (copy=False — reply tensor frames are memoryviews of
        snapshot_params' fresh copies, never mutated later) plus one
        idle tick for the reap/evict/resume/drain-linger work."""
        from znicz_tpu.transport import TransportLoop

        self._stop = False
        loop = self._transport = TransportLoop("master",
                                       instance=self.endpoint)
        state = {"deadline": None}

        def tick() -> None:
            if self._stop:
                loop.stop()
                return
            if bool(self.decision.complete):
                # jobs still out with crashed slaves will never be
                # re-served — reap on timeout and drop, else serve()
                # would poll forever waiting on a dead peer
                self._reap_lost_jobs()
                self._pending.clear()
            finished = (bool(self.decision.complete)
                        and not self._inflight and not self._pending)
            if finished and state["deadline"] is None:
                state["deadline"] = time.time() + linger
            if state["deadline"] is not None \
                    and time.time() > state["deadline"]:
                loop.stop()
                return
            self._evict_dead_slaves()
            self._note_quorum()
            t = time.time()
            if t - self._t_obs_drain > 0.25:
                # the master's own spans/events join the fleet stores
                # it coordinates (ISSUE 20; rate-limited)
                self._t_obs_drain = t
                from znicz_tpu import telemetry

                telemetry.drain_own_spans()
                telemetry.drain_own_events()
            self._maybe_save_resume()

        try:
            self._socket = loop.bind_rep(self.endpoint)
            loop.register(self._socket, self._reply_frames, reply=True)
            if self.transport_chaos is not None:
                loop.inject_faults(self.transport_chaos)
            loop.add_tick(tick)
            tick()                      # pre-poll pass (resume cadence)
            loop.run(poll_ms=100)
        finally:
            loop.close()
            self._socket = None
            # _transport intentionally KEEPS the closed loop: the
            # cross-plane soak reads its message/fault accounting
            # post-run
            if (self.resume_path and not self._stop
                    and bool(self.decision.complete)
                    and os.path.exists(self.resume_path)):
                # training finished: the crash-resume file has done its
                # job — left behind, a RERUN of the same --master-resume
                # command would silently restore stale mid-training state
                os.remove(self.resume_path)

    def _reply_frames(self, frames: List[bytes]) -> List:
        """Decode + dispatch one multipart message, returning the reply
        FRAMES.  NEVER raises: a truncated or garbage message from a
        broken peer — a corrupted metadata frame, a tensor frame whose
        length disagrees with its manifest, or a request that decodes but
        trips _handle — is refused with an error reply and counted,
        instead of raising out of the REP loop and killing the master.
        Legacy (v2-framed) requests — and undecodable ones, whose peer
        format is unknown — are answered in legacy single-pickle framing
        so even an out-of-date slave can read its refusal."""
        import logging

        from znicz_tpu.parallel import wire

        try:
            req, info = self.codec.decode(frames)
            if not isinstance(req, dict):
                raise wire.WireError(
                    f"decodes to {type(req).__name__}, not a request dict")
        except Exception as exc:
            rep_frames = self.codec.refusal(exc)
            logging.getLogger("znicz").warning(
                "refused undecodable message (%d frames, %d bytes): %s "
                "— bad_frames=%d", len(frames),
                sum(len(f) for f in frames), exc, self.bad_frames)
            return rep_frames
        legacy = bool(info.get("legacy"))
        if req.get("cmd") == "update":
            self._m["updates_received"].inc()
            self._m["update_bytes_in"].inc(info["message_bytes"])
        try:
            # span around REP handling, correlated by the job's trace_id
            # (the request echoes the id the job reply carried — ISSUE 5
            # satellite: wire-v3 metadata carries trace_id end-to-end)
            with self._tracer.span(
                    "master", f"handle:{req.get('cmd')}",
                    job_id=req.get("job_id"),
                    trace_id=req.get("trace_id"), slave=req.get("id")):
                rep = self._handle(req)
        except Exception as exc:
            self.codec.count_bad_frame()
            logging.getLogger("znicz").exception(
                "refused malformed request %r", req.get("cmd"))
            rep = {"ok": False, "bad_frame": True,
                   "error": f"malformed request: {exc!r}"}
        return self.codec.encode(rep, legacy=legacy)

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        sid = req.get("id", "?")
        if sid in self.registered:          # membership stamp gated on
            self.slaves[sid] = time.time()  # the handshake, like jobs
        if cmd == "register":
            from znicz_tpu.network_common import (PROTOCOL_VERSION,
                                                  check_handshake)

            refusal = check_handshake(req, self.workflow)
            if refusal:
                self.slaves.pop(sid, None)      # refused != member
                self.registered.discard(sid)
                return {"ok": False, "error": refusal}
            self.dead_slaves.pop(sid, None)     # back from the dead
            if sid in self._ever_registered or sid in self.jobs_by_slave:
                # a repeat register = a slave reconnect (backoff retry or
                # a peer re-joining a crash-resumed master, whose job
                # history came back with the snapshot)
                self._m["reregistrations"].inc()
            self._ever_registered.add(sid)
            self.registered.add(sid)
            newly_live = sid not in self.slaves
            if req.get("relay"):
                # an aggregation-tree relay (ISSUE 10): a first-class
                # member (TTL, eviction, reap all apply), marked so the
                # topology panel can draw the tree
                self.relays.add(sid)
                if req.get("bind"):
                    self.relay_binds[sid] = str(req["bind"])
            mesh = req.get("mesh")
            if isinstance(mesh, dict) and mesh:
                # a pod-sliced leaf (ISSUE 18) advertised its slice shape
                self.slave_meshes[sid] = {str(k): int(v)
                                          for k, v in mesh.items()}
            else:
                self.slave_meshes.pop(sid, None)
            self.slaves[sid] = time.time()
            if req.get("relay") and newly_live:
                # relay membership grew mid-run: re-plan (ISSUE 11)
                self._replan(f"relay {sid} joined")
            rep = {"ok": True, "version": PROTOCOL_VERSION,
                   "class_lengths": list(self.loader.class_lengths),
                   "resumed": self.resumed,
                   "epoch": int(self.loader.epoch_number)}
            if self.elastic_rehome and not req.get("relay"):
                # runtime tree healing (ISSUE 11): a LEAF registering
                # directly while live relays exist is an orphan (its
                # relay died and it fell back here) — steer it back
                # under the tree; the client keeps THIS endpoint as its
                # fallback, so a dead rehome target costs one more
                # backoff window, never the slave
                target = self._rehome_target()
                if target:
                    rep["rehome"] = target
            return rep
        if cmd in ("job", "update") and sid not in self.registered:
            # the handshake is a gate, not advice: a refused (or never
            # registered) peer gets no params and applies no deltas.
            # ``unregistered`` (protocol v2, NOT ``done``) tells a slave
            # that outlived a master restart to re-register, not exit.
            return {"ok": False, "unregistered": True,
                    "error": f"slave {sid!r} is not registered"}
        if cmd == "job":
            if bool(self.decision.complete):
                return {"done": True}       # terminal — never throttled
            if sid in self.relays and req.get("leaves") is not None:
                # the quorum membership piggyback is read BEFORE the
                # rate limit below: a throttled relay's refused
                # requests must still refresh its subtree leaf count,
                # or /readyz and the --min-slaves gate would hold a
                # stale view exactly while the fleet is under load
                try:
                    self._relay_leaves[sid] = max(0, int(req["leaves"]))
                except (TypeError, ValueError):
                    pass
            if not self._ingress.try_take(sid):
                # per-slave ingress admission (ISSUE 14): the serving
                # plane's token bucket on the master's door.  Refused
                # as ``wait`` — the slave's existing poll_sleep path —
                # so a misbehaving flood is throttled, counted, and
                # NEVER fatal (no strike, no eviction; its finished
                # updates are still taken below).
                self._m["rate_limited_ingress"].inc()
                return {"wait": True, "rate_limited": True,
                        "policy": "rate_limited",
                        "error": f"slave {sid!r} is over the per-slave "
                                 f"ingress rate limit "
                                 f"({self._ingress.rate:g} job "
                                 f"requests/s)"}
            # (the relay ``leaves`` piggyback — ISSUE 11's quorum view
            # through trees — was already read above, pre-admission)
            if not self.quorum_met():
                # quorum gate (ISSUE 11): below min_slaves the master
                # PAUSES dispatch — peers wait (and re-ask) instead of
                # burning the job stream on a fleet too small to make
                # progress; readiness reports degraded meanwhile
                return {"wait": True, "degraded": True,
                        "members": self.member_count(),
                        "min_slaves": self.min_slaves}
            # batched fetch (ISSUE 10): a relay asks with count=k and
            # gets up to k jobs under ONE params broadcast — the
            # O(slaves) -> O(fanout) flip on the job-request side.  A
            # count-less request keeps the historical flat reply shape.
            count = max(1, min(int(req.get("count", 1) or 1), 64))
            entries: List[dict] = []
            job = None
            for _ in range(count):
                job = self._next_job()
                if job is None or job is self._WAIT:
                    break
                self._job_seq += 1
                jid = self._job_seq
                self._inflight[jid] = (job, time.time(), sid)
                # trace_id: the cross-process correlation key (ISSUE
                # 5).  It rides the v3 metadata frame as an OPTIONAL
                # dict key — the slave echoes it in the update, spans
                # on both sides carry it, and an old peer that ignores
                # it still works.
                # ``step``: the apply-counter stamp (ISSUE 11) — the
                # params version this job computes against; the slave
                # echoes it with its update, and the delta's staleness
                # is the applies elapsed since
                entry = {"job_id": jid, "job": job,
                         "trace_id": f"{self._run_tag}-{jid}",
                         "train": job["class"] == TRAIN,
                         "step": self._apply_step}
                if self.job_deadline:
                    # deadline propagation (ISSUE 14): a BUDGET, not a
                    # timestamp (clocks differ) — the live reap window:
                    # past it the job is re-queued here anyway, so a
                    # slave/relay must drop it instead of computing it
                    entry["deadline_ms"] = \
                        self.effective_job_timeout() * 1e3
                entries.append(entry)
            if not entries:
                if job is self._WAIT:
                    return {"wait": True}   # client sleeps and re-asks
                return {"done": True}
            if req.get("prefetch"):
                # the client's pipeline socket asked for this job ahead
                # of need — the fetch overlapped compute (ISSUE 3)
                self._m["prefetch_hit"].inc()
            params = self.snapshot_params()
            if count <= 1:
                return dict(entries[0], params=params)
            return {"jobs": entries, "params": params}
        if cmd == "update":
            # fleet observability piggyback (ISSUE 20): slaves/relays
            # ride completed spans and journal events on their updates
            # — additive keys, ignored by a pre-ISSUE-20 master
            if (req.get("spans") or req.get("events")
                    or req.get("fwd_obs")):
                from znicz_tpu import telemetry

                origin = str(req.get("origin") or sid)
                if req.get("spans"):
                    telemetry.fleet_trace().ingest(origin, req["spans"])
                if req.get("events"):
                    telemetry.fleet_events().ingest(origin,
                                                    req["events"])
                # obs payloads a relay tier forwarded on behalf of its
                # leaves — each keeps the LEAF's origin, so a slave two
                # hops down still renders as its own fleet participant
                for fwd in req.get("fwd_obs") or []:
                    if not isinstance(fwd, dict):
                        continue
                    fo = str(fwd.get("origin") or sid)
                    if fwd.get("spans"):
                        telemetry.fleet_trace().ingest(fo, fwd["spans"])
                    if fwd.get("events"):
                        telemetry.fleet_events().ingest(fo,
                                                        fwd["events"])
            if "contributors" in req:
                return self._handle_aggregated(req, sid)
            jid = req.get("job_id")
            entry = self._inflight.pop(jid, None)
            if entry is None:
                # job already reaped/re-queued (slow slave) or finished —
                # the update must be DROPPED, not applied (async staleness
                # bound: one job, one accepted update)
                self._m["stale_updates"].inc()
                return {"ok": False, "stale": True}
            job, t_issued, _ = entry
            # round-trip duration of a slave that DID answer — feeds the
            # adaptive reap timeout (recorded even for replies refused
            # below: they still prove the slave's latency)
            self._durations.append(time.time() - t_issued)
            # NOTE: from here on the job is out of _inflight — every
            # refusal path below must either re-queue it or drop it
            # DELIBERATELY (bounded policy); nothing may raise.
            if "minibatches" in job:
                # a segment reply must carry one metrics dict PER
                # minibatch — a short (or long) list means the slave ran
                # a different job than assigned, and zip() would silently
                # truncate the feed; refuse the whole update (deltas
                # included — they came from the same broken run) and
                # re-queue the job so the work is not lost.  Bounded: a
                # deterministically-broken slave (version skew) would
                # otherwise refetch and re-fail the same job forever —
                # after MAX_BAD_REPLIES the non-tail segment is dropped
                # (its metrics are lost like a stale update's; Decision
                # control flow never depends on non-tail feeds).
                ms = req.get("metrics") or []
                if not isinstance(ms, (list, tuple)) \
                        or len(ms) != len(job["minibatches"]) \
                        or not all(m is None or isinstance(m, dict)
                                   for m in ms):
                    n = len(ms) if hasattr(ms, "__len__") else type(ms)
                    return self._refuse_update(
                        job, sid, f"segment metrics length {n!r} != "
                                  f"{len(job['minibatches'])}")
            elif not (req.get("metrics") is None
                      or isinstance(req.get("metrics"), dict)):
                # a singleton job's metrics must be a dict (or absent):
                # _feed_decision would raise on anything else, and the
                # job — already popped — would be lost silently
                return self._refuse_update(
                    job, sid, "metrics payload is "
                              f"{type(req.get('metrics')).__name__}, "
                              "not a dict")
            s = self._staleness(req.get("step"), sid)
            if req.get("deltas"):
                if self.staleness_bound > 0 and s > self.staleness_bound:
                    return self._refuse_stale(job, sid, s)
                reason = self._quarantine_reason(req["deltas"])
                if reason:
                    return self._refuse_update(
                        job, sid, f"delta quarantined: {reason}",
                        counter="quarantined_updates", quarantined=True)
                self.apply_deltas(req["deltas"],
                                  scale=self._stale_scale(s))
            # async arrivals after completion must not rewind decision state
            if not bool(self.decision.complete):
                if "minibatches" in job:
                    # segment job: per-minibatch metrics, fed in order
                    ms = req.get("metrics") or []
                    for mb, m in zip(job["minibatches"], ms):
                        self._feed_decision(mb, m or {})
                else:
                    # `or {}`: a present-but-None metrics key passed the
                    # type guard (None is legal) but must not reach
                    # _feed_decision's .get calls
                    self._feed_decision(job, req.get("metrics") or {})
            self._m["jobs_done"].inc()
            self.slo.record("apply_progress", True)
            self.jobs_by_slave[sid] = self.jobs_by_slave.get(sid, 0) + 1
            return {"ok": True, "complete": bool(self.decision.complete)}
        return {"error": f"unknown cmd {cmd!r}"}

    def _handle_aggregated(self, req: dict, sid: str) -> dict:
        """A relay's pre-aggregated update (ISSUE 10): ONE summed delta
        plus a per-contributor manifest.  The accounting mirrors the
        star EXACTLY, per contributor: stale jobs dropped and counted,
        relay-edge refusals counted as quarantined and re-queued,
        malformed metrics refused under the bounded MAX_BAD_REPLIES
        policy, round-trip durations feeding the adaptive reaper, the
        Decision fed per minibatch in manifest order, and ``jobs_done``
        attributed to the LEAF slave ids.  The summed delta passes the
        same quarantine (norm normalized per contributing delta) and is
        applied ONCE; when IT is refused, every fresh contributor's job
        is re-queued — the sum is indivisible, so none of its inputs
        may land (requeue-per-child).  The same indivisibility rule
        runs the other way: a DELTA-BEARING contributor refused for
        malformed metrics aborts the whole aggregate (the star's order
        is refuse-BEFORE-apply, and its gradient cannot be subtracted
        from the sum) — innocent siblings are re-queued without a
        strike, so nothing lands twice when the re-dispatched jobs
        come back.

        Documented staleness: a contributor reaped while its delta sat
        in a relay flush buffer is dropped from the books here while
        its (already-summed) share of the delta lands — bounded by the
        relay flush window, far inside the adaptive reap timeout."""
        contributors = req.get("contributors")
        if not isinstance(contributors, (list, tuple)) or not all(
                isinstance(c, dict) for c in contributors):
            # raises out to _reply_frames' bad-frame refusal: nothing
            # has been popped from _inflight yet, so nothing is lost
            raise ValueError("contributors manifest is not a list of "
                             "dicts")
        now = time.time()
        if self._tracer.enabled:
            # ISSUE 20 satellite: each contributor's trace_id reaches
            # the MASTER-side timeline — a leaf's trace stitches
            # through the relay hop instead of dead-ending there
            t0 = time.perf_counter()
            for c in contributors:
                if c.get("trace_id"):
                    self._tracer.add(
                        "master", "aggregate_contrib", t0, 0.0,
                        {"trace_id": c.get("trace_id"),
                         "job_id": c.get("job_id"),
                         "leaf": str(c.get("id", sid)), "relay": sid})
        n_delta = sum(1 for c in contributors if c.get("delta"))
        fresh: List[tuple] = []         # (contrib, job, staleness)
        malformed: List[tuple] = []     # (contrib, job, why)
        outcomes: Dict = {}
        for c in contributors:
            jid = c.get("job_id")
            entry = self._inflight.pop(jid, None)
            if entry is None:
                self._m["stale_updates"].inc()
                outcomes[jid] = "stale"
                continue
            job, t_issued, _ = entry
            self._durations.append(now - t_issued)
            cid = str(c.get("id", sid))
            # per-LEAF staleness (ISSUE 11): the manifest carries each
            # contributor's job stamp, so the histograms and the bound
            # see through the tree exactly as through the star
            s = self._staleness(c.get("step"), cid)
            if c.get("refused"):
                self._refuse_update(
                    job, cid, f"delta quarantined at relay {sid!r}: "
                              f"{c['refused']}",
                    counter="quarantined_updates", quarantined=True)
                outcomes[jid] = "quarantined"
                continue
            metrics = c.get("metrics")
            why = None
            if "minibatches" in job:
                ms = metrics or []
                if not isinstance(ms, (list, tuple)) \
                        or len(ms) != len(job["minibatches"]) \
                        or not all(m is None or isinstance(m, dict)
                                   for m in ms):
                    n = len(ms) if hasattr(ms, "__len__") else type(ms)
                    why = (f"segment metrics length {n!r} != "
                           f"{len(job['minibatches'])}")
            elif not (metrics is None or isinstance(metrics, dict)):
                why = ("metrics payload is "
                       f"{type(metrics).__name__}, not a dict")
            if why is not None:
                malformed.append((c, job, why))
                outcomes[jid] = "refused"
                continue
            fresh.append((c, job, s))
        deltas = req.get("deltas")
        if malformed and deltas and any(c.get("delta")
                                        for c, _, _ in malformed):
            # a delta-bearing contributor with a malformed reply: its
            # gradient is baked into the INDIVISIBLE sum, and the
            # star's order is refuse-BEFORE-apply — so the whole
            # aggregate is refused: the malformed children take the
            # bounded bad-reply policy, their innocent siblings come
            # back via the reaper's counter with no strike
            for c, job, why in malformed:
                self._refuse_update(job, str(c.get("id", sid)), why)
            for c, job, _ in fresh:
                self._pending.append(job)
                self._m["jobs_requeued"].inc()
                outcomes[c.get("job_id")] = "requeued"
            return {"ok": False, "outcomes": outcomes,
                    "error": "aggregate refused: " + "; ".join(
                        w for _, _, w in malformed)}
        for c, job, why in malformed:
            # delta-less malformed replies (eval metrics) refuse
            # per-child exactly like the star — nothing of theirs is
            # in the sum
            self._refuse_update(job, str(c.get("id", sid)), why)
        if deltas and self.staleness_bound > 0:
            # bounded staleness through the tree (ISSUE 11): a
            # delta-bearing contributor past the bound is baked into
            # the INDIVISIBLE sum, so — exactly like the malformed
            # abort — the whole aggregate is refused: the over-bound
            # children re-queue under ``stale_refused`` with no
            # strike, their innocent siblings under ``jobs_requeued``
            # with no strike, and nothing lands twice when the
            # re-dispatched jobs come back
            over, rest = [], []
            for t in fresh:
                (over if (t[0].get("delta")
                          and t[2] > self.staleness_bound)
                 else rest).append(t)
            if over:
                for c, job, s in over:
                    self._refuse_stale(job, str(c.get("id", sid)), s)
                    outcomes[c.get("job_id")] = "stale_refused"
                for c, job, _ in rest:
                    self._pending.append(job)
                    self._m["jobs_requeued"].inc()
                    outcomes[c.get("job_id")] = "requeued"
                return {"ok": False, "stale_refused": True,
                        "outcomes": outcomes,
                        "error": "aggregate refused: "
                                 f"{len(over)} contributor delta(s) "
                                 "beyond the staleness bound"}
        # the apply is gated on a FRESH delta-bearing contributor: a
        # relay re-sends the same flush bytes after a lost reply (the
        # client's resend discipline), and on the second delivery every
        # contributor pops as stale — the sum must then be DROPPED like
        # a stale star update, or the gradient lands twice
        if deltas and any(c.get("delta") for c, _, _ in fresh):
            reason = self._quarantine_reason(deltas,
                                             n_contrib=max(1, n_delta))
            if reason:
                for c, job, _ in fresh:
                    self._refuse_update(
                        job, str(c.get("id", sid)),
                        f"aggregated delta quarantined: {reason}",
                        counter="quarantined_updates", quarantined=True)
                return {"ok": False, "quarantined": True,
                        "error": f"delta quarantined: {reason}",
                        "outcomes": outcomes}
            # staleness-weighted apply of the indivisible sum: one
            # scale for all contributors — their MEAN staleness (the
            # sum already mixes their gradients; the mean discounts it
            # exactly as much as the per-contributor weights would on
            # average)
            stales = [s for c, _, s in fresh if c.get("delta")]
            self.apply_deltas(
                deltas,
                scale=self._stale_scale(float(np.mean(stales))
                                        if stales else 0.0))
        for c, job, _ in fresh:
            # async arrivals after completion must not rewind decision
            # state (same guard as the star path)
            if not bool(self.decision.complete):
                if "minibatches" in job:
                    for mb, m in zip(job["minibatches"],
                                     c.get("metrics") or []):
                        self._feed_decision(mb, m or {})
                else:
                    self._feed_decision(job, c.get("metrics") or {})
            cid = str(c.get("id", sid))
            self._m["jobs_done"].inc()
            self.slo.record("apply_progress", True)
            self.jobs_by_slave[cid] = self.jobs_by_slave.get(cid, 0) + 1
            outcomes[c.get("job_id")] = "ok"
        self._m["aggregated_updates"].inc()
        return {"ok": True, "complete": bool(self.decision.complete),
                "outcomes": outcomes}


# historical counter attributes, generated from COUNTERS (name + HELP
# defined exactly once; read/write for resume restore)
for _name, _help in Server.COUNTERS.items():
    setattr(Server, _name, _server_counter(_name, _help))
del _name, _help
