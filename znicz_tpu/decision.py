"""Decision units: training control (rebuild of ``znicz/decision.py``).

Runs once per minibatch, right after the evaluator.  Accumulates per-class
epoch statistics, and at epoch end (the loader's TRAIN tail):

  - tracks the best validation metric (n_err for GD, mse for MSE),
  - raises ``improved`` (the snapshotter's trigger),
  - raises ``complete`` when ``max_epochs`` is reached or validation hasn't
    improved for ``fail_iterations`` epochs,
  - maintains ``gd_skip`` — the Bool that gates every GD unit off for
    TEST/VALID minibatches (only TRAIN minibatches backprop; reference
    semantics).

Class indices follow the reference: TEST=0, VALID=1, TRAIN=2; the loader
serves one full pass over test, then valid, then train per epoch.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TEST, TRAIN, VALID
from znicz_tpu.memory import Array

CLASS_NAMES = ("test", "valid", "train")


class DecisionBase(Unit):
    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.max_epochs = kwargs.get("max_epochs", 10)
        #: epochs without validation improvement before stopping (0 = off)
        self.fail_iterations = kwargs.get("fail_iterations", 0)
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.epoch_ended = Bool(False)
        self.gd_skip = Bool(False)
        # linked from loader:
        self.minibatch_class = TRAIN
        self.last_minibatch = False
        self.class_ended = False
        self.epoch_number = 0
        self.class_lengths: List[int] = [0, 0, 0]
        # linked from evaluator:
        self.minibatch_loss = 0.0
        # epoch accumulators / history
        self.epoch_metrics = [None, None, None]   # last finished epoch
        self._acc_loss = [0.0, 0.0, 0.0]
        self._acc_batches = [0, 0, 0]
        self.best_metric = np.inf
        self.best_epoch = -1
        self._fails = 0
        self.on_epoch_end = []                    # callbacks(decision)
        # telemetry (ISSUE 5): the decision loop's live state as
        # collect-time gauges — zero hot-path writes, the scrape reads
        # the attributes this unit already maintains.  weak_fn: the
        # process-global registry must not pin the decision (and the
        # whole workflow graph behind its links) after the run
        from znicz_tpu import telemetry

        _sc = telemetry.scope("decision")
        _sc.gauge("epoch_number", "current epoch",
                  fn=telemetry.weak_fn(
                      self, lambda d: float(d.epoch_number)))
        _sc.gauge("best_metric", "best validation metric so far",
                  fn=telemetry.weak_fn(
                      self, lambda d: float(d.best_metric)))
        _sc.gauge("train_complete", "1 once training stopped",
                  fn=telemetry.weak_fn(
                      self, lambda d: float(bool(d.complete))))

    # -- metric plumbing (subclasses refine) ----------------------------------

    def _accumulate(self, klass: int) -> None:
        self._acc_loss[klass] += float(self.minibatch_loss)
        self._acc_batches[klass] += 1

    def _class_metric(self, klass: int) -> float:
        b = max(1, self._acc_batches[klass])
        return self._acc_loss[klass] / b

    def _reset_class(self, klass: int) -> None:
        self._acc_loss[klass] = 0.0
        self._acc_batches[klass] = 0

    def _validation_class(self) -> int:
        """Improvement is judged on VALID if present, else TRAIN."""
        return VALID if self.class_lengths[VALID] else TRAIN

    def improvement_metric(self) -> float:
        return self._class_metric(self._validation_class())

    # -- run ------------------------------------------------------------------

    def run(self):
        klass = int(self.minibatch_class)
        self._accumulate(klass)
        self.epoch_ended.set(False)
        if self.class_ended:
            self.epoch_metrics[klass] = self._summarize(klass)
        if self.last_minibatch:            # end of TRAIN == end of epoch
            metric = self.improvement_metric()
            if metric < self.best_metric - 1e-12:
                self.best_metric = metric
                self.best_epoch = int(self.epoch_number)
                self.improved.set(True)
                self._fails = 0
            else:
                self.improved.set(False)
                self._fails += 1
            done = (self.epoch_number + 1 >= self.max_epochs or
                    (self.fail_iterations and
                     self._fails >= self.fail_iterations))
            self.complete.set(done)
            self.epoch_ended.set(True)
            self._log_epoch()
            for cb in self.on_epoch_end:
                cb(self)
            for k in (TEST, VALID, TRAIN):
                self._reset_class(k)
        # GD units run only on TRAIN minibatches while not complete.
        self.gd_skip.set(klass != TRAIN or bool(self.complete))

    def _summarize(self, klass: int):
        return {"loss": self._class_metric(klass)}

    def _log_epoch(self):
        parts = []
        for k in (TEST, VALID, TRAIN):
            if self.class_lengths[k] and self.epoch_metrics[k] is not None:
                m = self.epoch_metrics[k]
                stats = ", ".join(
                    f"{key}={val:.6g}" if isinstance(val, float)
                    else f"{key}={val}"
                    for key, val in m.items()
                    if isinstance(val, (int, float)) and key != "confusion")
                parts.append(f"{CLASS_NAMES[k]}: {stats}")
        self.info("epoch %d  %s%s", self.epoch_number, "  ".join(parts),
                  "  *" if bool(self.improved) else "")


class DecisionGD(DecisionBase):
    """Classification: tracks n_err% per class + confusion matrix; judges
    improvement on validation error count."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        # linked from evaluator:
        self.minibatch_n_err = 0
        self.confusion_matrix = None
        self.max_err_output_sum = 0.0
        self._acc_n_err = [0, 0, 0]
        self._acc_samples = [0, 0, 0]
        self._acc_confusion: List[Optional[np.ndarray]] = [None, None, None]
        self.minibatch_size = 0

    def _accumulate(self, klass: int) -> None:
        super()._accumulate(klass)
        self._acc_n_err[klass] += int(self.minibatch_n_err)
        self._acc_samples[klass] += int(self.minibatch_size)
        if self.confusion_matrix is not None:
            conf = self.confusion_matrix
            if isinstance(conf, Array):        # unit path: evaluator Array
                conf = np.asarray(conf.map_read())
            # size<=1 is the evaluator's confusion-disabled sentinel
            # (wide heads skip the (C,C) reporting transfer)
            if conf.size > 1:
                # deliberately NOT np.asarray'd: the fused path feeds
                # device-resident matrices, and `+` keeps the running sum
                # on device — the (C,C) transfer happens only when a
                # consumer (plotter/report/test) actually reads the epoch
                # metric, so wide heads cost nothing per epoch on slow
                # host links (VERDICT r3 missing #4)
                if self._acc_confusion[klass] is None:
                    self._acc_confusion[klass] = conf.copy()
                else:
                    self._acc_confusion[klass] = \
                        self._acc_confusion[klass] + conf

    def _reset_class(self, klass: int) -> None:
        super()._reset_class(klass)
        self._acc_n_err[klass] = 0
        self._acc_samples[klass] = 0
        self._acc_confusion[klass] = None

    def improvement_metric(self) -> float:
        k = self._validation_class()
        return self._acc_n_err[k] / max(1, self._acc_samples[k])

    def _summarize(self, klass: int):
        n = max(1, self._acc_samples[klass])
        return {"loss": self._class_metric(klass),
                "n_err": self._acc_n_err[klass],
                "err_pct": 100.0 * self._acc_n_err[klass] / n,
                "confusion": self._acc_confusion[klass]}


class DecisionMSE(DecisionBase):
    """Regression/autoencoder: improvement on validation mean loss."""

    def _summarize(self, klass: int):
        return {"loss": self._class_metric(klass),
                "mse": self._class_metric(klass)}
