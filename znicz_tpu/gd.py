"""Fully-connected backward units (rebuild of ``znicz/gd.py``).

``GradientDescent`` (linear), ``GDTanh``, ``GDRELU``, ``GDStrictRELU``,
``GDSigmoid``, ``GDSoftmax``.  Each is the vjp of its forward twin (see
nn_units.GradientDescentBase); ``GDSoftmax`` takes the vjp of the *linear*
part only because the evaluator's ``err_output = softmax - target`` is
already the cross-entropy cotangent at the logits (the reference's fused
softmax+CE backward kernel did exactly this).
"""

from __future__ import annotations

from znicz_tpu.nn_units import GradientDescentBase
from znicz_tpu.ops.linear import linear


class GradientDescent(GradientDescentBase):
    """Backward for any All2All* via vjp of forward.apply."""


class GDTanh(GradientDescent):
    pass


class GDRELU(GradientDescent):
    pass


class GDStrictRELU(GradientDescent):
    pass


class GDSigmoid(GradientDescent):
    pass


class GDSoftmax(GradientDescent):
    """err_output is d(CE)/d(logits): bypass the softmax in the vjp."""

    def backward_apply(self, params, x):
        fwd = self.forward
        y = linear(x, params["weights"], params.get("bias"),
                   weights_transposed=fwd.weights_transposed)
        return y.reshape((x.shape[0],) + fwd.output_sample_shape)


#: forward-class-name -> GD class (StandardWorkflow uses this).
GD_BY_FORWARD = {
    "All2All": GradientDescent,
    "All2AllTanh": GDTanh,
    "All2AllRELU": GDRELU,
    "All2AllStrictRELU": GDStrictRELU,
    "All2AllSigmoid": GDSigmoid,
    "All2AllSoftmax": GDSoftmax,
}
