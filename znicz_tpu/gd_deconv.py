"""Deconvolution backward unit (rebuild of ``znicz/gd_deconv.py``) — the vjp
of Deconv.apply; because Deconv itself is a conv-vjp, the weight gradient and
err_input XLA emits here are ordinary forward-conv forms (transpose of a
transpose).  Works with tied weights: when the Deconv shares its weight Array
with an encoder Conv, the update lands in the shared tensor."""

from __future__ import annotations

from znicz_tpu.nn_units import GradientDescentBase


class GDDeconv(GradientDescentBase):
    pass


class GDDeconvTanh(GDDeconv):
    pass


class GDDeconvSigmoid(GDDeconv):
    pass
