"""Genetics end-to-end over a REAL sample: GA tunes the MNIST learning
rate through actual ``python -m znicz_tpu`` launcher subprocesses
(--fused fast path, --backend cpu pinning, --fitness JSON) — the full
reference workflow, not the fake-workflow harness."""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root


def test_ga_tunes_real_mnist_lr(tmp_path):
    from znicz_tpu.genetics import (GeneticsOptimizer, SubprocessEvaluator,
                                    Tune)

    prng.reset(1013)
    cfg = root.ga_mnist_real
    cfg.learning_rate = Tune(0.02, 0.005, 0.6)
    evaluator = SubprocessEvaluator(
        workflow="mnist",
        overrides=["root.mnist.loader.n_train=120",
                   "root.mnist.loader.n_valid=60",
                   "root.mnist.loader.minibatch_size=60",
                   "root.mnist.decision.max_epochs=2",
                   f"root.common.dirs.snapshots={tmp_path}",
                   "--backend", "cpu", "--fused"],
        prefix="root.mnist", timeout=300.0)
    opt = GeneticsOptimizer(
        config_root=cfg, generations=2, population=3, elite=1,
        workers=2, subprocess_evaluator=evaluator)
    best, fitness = opt.run()

    assert np.isfinite(fitness)
    assert 0.0 <= fitness <= 1.0            # valid-err fraction
    assert 0.005 <= best[0] <= 0.6          # tuned lr stayed in range
    assert len(opt.history) == 2            # one entry per generation
    # fitness is monotone non-increasing across generations (elitism)
    assert opt.history[-1] <= opt.history[0]
    assert opt.max_parallel >= 2            # really ran concurrently
