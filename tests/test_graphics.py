"""Live plot streaming: GraphicsServer (XPUB) -> separate GraphicsClient
process rendering the same figures the offline path produces (SURVEY.md L9
"Graphics")."""

import os
import subprocess
import sys

import numpy as np

from znicz_tpu.core.config import root


def test_live_streaming_to_client_process(tmp_path):
    """Spawn the real client process, stream two epochs of error curves
    plus a weights tile through a training-shaped plotter set, assert the
    client rendered every figure."""
    from znicz_tpu.graphics import GraphicsServer
    from znicz_tpu.memory import Array
    from znicz_tpu.plotting_units import AccumulatingPlotter, Weights2D

    out = tmp_path / "live"
    server = GraphicsServer.start("tcp://127.0.0.1:*")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu.graphics", server.endpoint,
             str(out), "--max-figures", "3", "--timeout", "60"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, text=True)
        assert server.wait_for_subscribers(1, timeout=30)

        losses = iter([2.0, 1.0])
        acc = AccumulatingPlotter(name="live_loss",
                                  fetch=lambda: next(losses))
        weights = Weights2D(
            name="live_w",
            source=Array(np.random.default_rng(0).normal(
                size=(4, 16)).astype(np.float32)),
            sample_shape=(4, 4))
        acc.run()          # epoch 0
        acc.run()          # epoch 1
        weights.run()
        stdout, _ = proc.communicate(timeout=60)
    finally:
        GraphicsServer.stop()
    assert proc.returncode == 0
    assert "rendered 3 figures" in stdout
    assert (out / "live_loss.png").exists()
    assert (out / "live_w.png").exists()
    # while a server is active, units stream INSTEAD of rendering offline
    assert not os.path.exists(os.path.join(
        root.common.dirs.get("plots", "plots"), "live_loss.png"))


def test_graceful_offline_degradation(tmp_path):
    """No server active -> plotters render offline PNGs exactly as before."""
    from znicz_tpu.graphics import GraphicsServer
    from znicz_tpu.plotting_units import AccumulatingPlotter

    assert GraphicsServer.active() is None
    root.common.dirs.plots = str(tmp_path)
    vals = iter([1.0, 0.5])
    acc = AccumulatingPlotter(name="off_loss", fetch=lambda: next(vals))
    acc.run()
    acc.run()
    assert acc.values == [1.0, 0.5]
    assert os.path.exists(acc.path())


def test_render_false_still_accumulates(tmp_path):
    """render=False plotters keep their raw series (for tests/notebooks)
    without writing any file."""
    from znicz_tpu.plotting_units import AccumulatingPlotter

    root.common.dirs.plots = str(tmp_path)
    vals = iter([2.0, 1.0])
    acc = AccumulatingPlotter(name="noren", fetch=lambda: next(vals),
                              render=False)
    acc.run()
    acc.run()
    assert acc.values == [2.0, 1.0]
    assert not os.path.exists(acc.path())


def test_client_refuses_non_loopback_endpoint(tmp_path):
    """Pickled payloads from an arbitrary host would be code execution;
    the client must refuse non-loopback endpoints unless overridden."""
    import pytest

    from znicz_tpu.graphics import GraphicsClient, _is_loopback

    with pytest.raises(ValueError, match="loopback"):
        GraphicsClient("tcp://198.51.100.7:5555", str(tmp_path))
    assert _is_loopback("tcp://127.0.0.1:9000")
    assert _is_loopback("ipc:///tmp/sock")
    assert not _is_loopback("tcp://[2001:db8::1]:9000")


def test_client_renders_all_plotter_kinds(tmp_path):
    """Every plotter kind round-trips snapshot -> client render (in-proc
    client; the subprocess path is covered above)."""
    from znicz_tpu.graphics import GraphicsClient
    from znicz_tpu.memory import Array
    from znicz_tpu import plotting_units as pu

    rng = np.random.default_rng(3)

    class StubSOM:                         # KohonenHits only reads these
        hits = Array(rng.integers(0, 9, size=(12,)).astype(np.int32))
        sy, sx, total = 3, 4, 36

    plotters = [
        pu.AccumulatingPlotter(name="k_acc", fetch=iter([1.0]).__next__),
        pu.Weights2D(name="k_w", source=Array(rng.normal(
            size=(4, 9)).astype(np.float32)), sample_shape=(3, 3)),
        pu.MatrixPlotter(name="k_m", fetch=lambda: np.eye(3)),
        pu.KohonenHits(name="k_som", forward=StubSOM()),
        pu.MultiHistogram(name="k_h", source=Array(rng.normal(
            size=(50,)).astype(np.float32))),
    ]
    client = GraphicsClient.__new__(GraphicsClient)   # render() only
    client.out_dir = str(tmp_path)
    for p in plotters:
        payload = {"kind": "figure", "cls": type(p).__name__,
                   "name": p.name, "data": p.snapshot()}
        import pickle

        payload = pickle.loads(pickle.dumps(payload))  # the wire trip
        path = client.render(payload)
        assert path is not None and os.path.exists(path), p.name


def test_fused_training_streams_plots_live(tmp_path):
    """Full integration: a fused training run with StandardWorkflow-wired
    plotters streams its epoch figures to a real GraphicsClient process
    (error curve + weights + confusion over two epochs)."""
    from znicz_tpu.core import prng
    from znicz_tpu.graphics import GraphicsServer
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.mnist import MnistLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.common.dirs.snapshots = str(tmp_path)
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    wf = StandardWorkflow(
        name="MnistLive",
        loader=MnistLoader(name="loader", minibatch_size=60),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 50}, "<-": dict(gd)},
                {"type": "softmax",
                 "->": {"output_sample_shape": 10}, "<-": dict(gd)}],
        loss_function="softmax",
        decision_config={"max_epochs": 2},
        plotters=True)
    wf.initialize(device=None)

    out = tmp_path / "live"
    server = GraphicsServer.start("tcp://127.0.0.1:*")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu.graphics", server.endpoint,
             str(out), "--max-figures", "6", "--timeout", "120"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, text=True)
        assert server.wait_for_subscribers(1, timeout=30)
        FusedTrainer(wf).run()          # 2 epochs x 3 figures
        stdout, _ = proc.communicate(timeout=120)
    finally:
        GraphicsServer.stop()
    assert proc.returncode == 0
    assert "rendered 6 figures" in stdout
    for png in ("plot_err.png", "plot_weights.png", "plot_confusion.png"):
        assert (out / png).exists(), png
