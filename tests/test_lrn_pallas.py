"""Pallas LRN kernel vs the jnp oracle (znicz_tpu/lrn.py): forward and
gradient agreement (interpreter mode on the CPU test platform)."""

import numpy as np

from znicz_tpu.core.config import root


def _jnp_lrn(x, n=5, alpha=1e-4, beta=0.75, k=2.0):
    import jax.numpy as jnp

    half = n // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    acc = jnp.zeros_like(x)
    for j in range(n):
        acc = acc + padded[..., j:j + x.shape[-1]]
    return x / jnp.power(k + alpha * acc, beta)


def test_pallas_lrn_forward_and_grad_match_oracle():
    import jax
    import jax.numpy as jnp

    from znicz_tpu.ops.lrn_pallas import lrn

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 9, 9, 96)).astype(np.float32) * 2)

    y = lrn(x)
    y_ref = _jnp_lrn(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)

    # gradient: custom_vjp vs autodiff through the oracle
    cot = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))
    g = jax.grad(lambda t: jnp.sum(lrn(t) * cot))(x)
    g_ref = jax.grad(lambda t: jnp.sum(_jnp_lrn(t) * cot))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-6)


def test_pallas_lrn_flag_routes_unit(tmp_path):
    """root.common.engine.pallas_lrn routes LRNormalizerForward.apply
    through the kernel; output matches the default path."""
    import jax.numpy as jnp

    from znicz_tpu.lrn import LRNormalizerForward

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(2, 5, 5, 32)).astype(np.float32))
    u = LRNormalizerForward(name="lrn")
    base = np.asarray(u.apply({}, x))
    root.common.engine.pallas_lrn = True
    try:
        fast = np.asarray(u.apply({}, x))
    finally:
        root.common.engine.pallas_lrn = False
    np.testing.assert_allclose(fast, base, rtol=1e-5, atol=1e-6)


def test_fused_block_lrn_stage_matches_oracle():
    """The single-pass conv-block kernel (pallas_fused_block) degenerates
    to relu -> LRN under a 1x1/s1 identity pool — its LRN stage must match
    the same oracle the standalone Pallas LRN kernel is held to, forward
    AND gradient (the fused bwd's closed-form LRN term)."""
    import jax
    import jax.numpy as jnp

    from znicz_tpu.pallas_fused_block import fused_block

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(2, 6, 6, 96)).astype(np.float32) * 2)
    b = jnp.zeros((96,), jnp.float32)

    def oracle(t):
        return _jnp_lrn(jnp.maximum(t, 0.0))

    y = fused_block(x, b, 5, 1e-4, 0.75, 2.0, (1, 1, 1, 1))
    np.testing.assert_allclose(np.asarray(y), np.asarray(oracle(x)),
                               rtol=1e-5, atol=1e-6)

    cot = jnp.asarray(rng.normal(size=x.shape).astype(np.float32))
    g = jax.grad(lambda t: jnp.sum(
        fused_block(t, b, 5, 1e-4, 0.75, 2.0, (1, 1, 1, 1)) * cot))(x)
    g_ref = jax.grad(lambda t: jnp.sum(oracle(t) * cot))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=2e-4, atol=2e-6)


def test_pallas_lrn_odd_channel_and_row_counts():
    """Row padding (rows not a multiple of TILE_R) and non-128 channel
    widths round-trip correctly."""
    import jax.numpy as jnp

    from znicz_tpu.ops.lrn_pallas import lrn

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(3, 7, 96)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(lrn(x)),
                               np.asarray(_jnp_lrn(x)),
                               rtol=1e-5, atol=1e-6)
