"""The real-data escape hatch (VERDICT r2 weak #2 / next-round #4): the
``root.<sample>.loader.data_path`` .npz route must be exercised code, not
an untested promise — this writes real .npz files and trains from them."""

import numpy as np
import pytest

from znicz_tpu.core.config import root


def _write_npz(path, data, labels):
    np.savez(str(path), data=data.astype(np.float32),
             labels=labels.astype(np.int32))
    return str(path)


def test_mnist_trains_from_npz(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    rng = np.random.default_rng(7)
    n = 180
    # recognizable structure: class k lights up a distinct 7x7 block row
    data = rng.normal(0.1, 0.05, size=(n, 28, 28)).astype(np.float32)
    labels = (np.arange(n) % 10).astype(np.int32)
    for i in range(n):
        k = labels[i]
        data[i, (k % 4) * 7:(k % 4) * 7 + 7, (k // 4) * 7:(k // 4) * 7 + 7] \
            += 1.0
    path = _write_npz(tmp_path / "mnist.npz", data, labels)

    prng.reset(1013)
    root.mnist.loader.data_path = path
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    root.common.dirs.snapshots = str(tmp_path)
    try:
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        # the loader REALLY loaded the .npz, not the procedural fallback
        np.testing.assert_allclose(
            np.asarray(wf.loader.original_data.mem).reshape(n, -1),
            data.reshape(n, -1), rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(wf.loader.original_labels.mem), labels)
        wf.run()
        assert bool(wf.decision.complete)
        valid = wf.decision.epoch_metrics[1]
        assert valid is not None and valid["err_pct"] < 50.0, valid
    finally:
        root.mnist.loader.data_path = ""


def test_cifar_trains_from_npz(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import cifar

    rng = np.random.default_rng(9)
    n = 150
    data = rng.normal(0.2, 0.1, size=(n, 32, 32, 3)).astype(np.float32)
    labels = (np.arange(n) % 10).astype(np.int32)
    for i in range(n):
        k = labels[i]
        data[i, (k % 5) * 6:(k % 5) * 6 + 6, :, k % 3] += 0.8
    path = _write_npz(tmp_path / "cifar.npz", data, labels)

    prng.reset(1013)
    root.cifar.loader.data_path = path
    root.cifar.loader.n_train = 100
    root.cifar.loader.n_valid = 50
    root.cifar.loader.n_test = 0
    root.cifar.loader.minibatch_size = 50
    root.cifar.decision.max_epochs = 2
    root.common.dirs.snapshots = str(tmp_path)
    try:
        wf = cifar.CifarWorkflow()
        wf.initialize(device=None)
        np.testing.assert_allclose(
            np.asarray(wf.loader.original_data.mem), data, rtol=1e-6)
        wf.run()
        assert bool(wf.decision.complete)
    finally:
        root.cifar.loader.data_path = ""


def test_missing_npz_falls_back_to_procedural(tmp_path):
    from znicz_tpu import datasets

    data, labels = datasets.load_or_generate(
        str(tmp_path / "nope.npz"), datasets.digits, 12)
    assert data.shape == (12, 28, 28) and labels.shape == (12,)


def test_yale_faces_sample_trains_from_real_files(tmp_path, monkeypatch):
    """YaleFaces-style sample: synthesizes a PNG directory tree, loads it
    through FullBatchFileImageLoader (directory scan -> PIL decode ->
    native u8->f32), and learns identity under lighting variation."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import yale_faces

    prng.reset(1013)
    monkeypatch.chdir(tmp_path)
    root.common.dirs.snapshots = str(tmp_path)
    root.yale_faces.loader.data_dir = str(tmp_path / "faces")
    root.yale_faces.loader.n_subjects = 5
    root.yale_faces.loader.n_train_per_subject = 16
    root.yale_faces.loader.n_valid_per_subject = 4
    root.yale_faces.loader.minibatch_size = 40
    root.yale_faces.decision.max_epochs = 25
    try:
        wf = yale_faces.run()
    finally:
        root.yale_faces.loader.data_dir = "yale_faces_data"

    import os

    # real files on disk, loaded through the image pipeline
    assert os.path.isdir(tmp_path / "faces" / "train" / "subject_00")
    assert wf.loader.class_names == [f"subject_{i:02d}" for i in range(5)]
    assert tuple(wf.loader.original_data.shape)[1:] == (32, 32, 3)
    dec = wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    # 5 identities, chance err = 80%
    assert valid is not None and valid["err_pct"] < 55.0, valid


def test_alexnet_trains_from_image_directory(tmp_path):
    """The north-star workflow's real-data route (VERDICT r3 item 7):
    a class-directory tree of image FILES feeds the AlexNet sample via
    FullBatchFileImageLoader + the image_size knob, and one epoch of
    fused training runs end to end."""
    import os

    from PIL import Image

    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import alexnet

    rng = np.random.default_rng(11)
    for split, n_per in (("train", 6), ("valid", 2)):
        for ci, cname in enumerate(("ants", "bees", "wasps")):
            d = tmp_path / split / cname
            os.makedirs(d)
            for i in range(n_per):
                # class-coded brightness so one epoch can reduce the loss
                arr = rng.integers(0, 80, (64, 64, 3)).astype(np.uint8)
                arr[:, :, ci] += 120
                Image.fromarray(arr).save(str(d / f"{i}.png"))

    prng.reset(1013)
    root.common.dirs.snapshots = str(tmp_path)
    cfg = root.alexnet.loader
    saved = {k: cfg.get(k) for k in ("train_dir", "valid_dir",
                                     "image_size", "minibatch_size")}
    saved_epochs = root.alexnet.decision.get("max_epochs")
    try:
        cfg.train_dir = str(tmp_path / "train")
        cfg.valid_dir = str(tmp_path / "valid")
        cfg.image_size = 64
        cfg.minibatch_size = 6
        root.alexnet.decision.max_epochs = 1
        wf = alexnet.AlexNetWorkflow()
        wf.initialize(device=None)
        assert wf.loader.class_names == ["ants", "bees", "wasps"]
        assert tuple(wf.loader.original_data.shape)[1:] == (64, 64, 3)
        assert wf.loader.class_lengths == [0, 6, 18]
        # the softmax head was sized from the directory tree
        assert wf.forwards[-1].output_samples_number == 3
        FusedTrainer(wf).run()
    finally:
        for k, v in saved.items():
            setattr(cfg, k, v)
        root.alexnet.decision.max_epochs = saved_epochs
    dec = wf.decision
    assert bool(dec.complete)
    assert np.isfinite(dec.epoch_metrics[2]["loss"])


def test_alexnet_streams_from_image_directory(tmp_path):
    """The ImageNet-at-scale route: root.alexnet.loader.stream=True feeds
    the SAME class-directory tree through a decode-on-demand
    ImageFileSource + StreamingLoader — nothing decoded up front, and a
    1 MB budget forces host-staged segments (files decoded only when a
    dispatch stages them)."""
    import os

    from PIL import Image

    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import alexnet

    rng = np.random.default_rng(13)
    for split, n_per in (("train", 6), ("valid", 2)):
        for ci, cname in enumerate(("ants", "bees", "wasps")):
            d = tmp_path / split / cname
            os.makedirs(d)
            for i in range(n_per):
                arr = rng.integers(0, 80, (64, 64, 3)).astype(np.uint8)
                arr[:, :, ci] += 120
                Image.fromarray(arr).save(str(d / f"{i}.png"))

    prng.reset(1013)
    root.common.dirs.snapshots = str(tmp_path)
    cfg = root.alexnet.loader
    saved = {k: cfg.get(k) for k in
             ("train_dir", "valid_dir", "image_size", "minibatch_size",
              "stream", "stream_budget_mb")}
    saved_epochs = root.alexnet.decision.get("max_epochs")
    try:
        cfg.train_dir = str(tmp_path / "train")
        cfg.valid_dir = str(tmp_path / "valid")
        cfg.image_size = 64
        cfg.minibatch_size = 6
        cfg.stream = True
        cfg.stream_budget_mb = 0.05     # force host-staged segments
        root.alexnet.decision.max_epochs = 1
        wf = alexnet.AlexNetWorkflow()
        wf.initialize(device=None)
        assert wf.loader.streaming and not wf.loader.device_resident
        assert wf.loader.class_lengths == [0, 6, 18]
        assert wf.forwards[-1].output_samples_number == 3
        trainer = FusedTrainer(wf)
        assert trainer.staging
        trainer.run()
    finally:
        for k, v in saved.items():
            setattr(cfg, k, v)
        root.alexnet.decision.max_epochs = saved_epochs
    assert bool(wf.decision.complete)
    assert np.isfinite(wf.decision.epoch_metrics[2]["loss"])
