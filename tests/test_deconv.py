"""Deconv/Depooling numerics: adjoint properties + the MnistAE e2e gate
(BASELINE config[2])."""

import numpy as np
import pytest

from znicz_tpu.conv import Conv
from znicz_tpu.core.config import root
from znicz_tpu.deconv import Deconv
from znicz_tpu.depooling import Depooling, GDDepooling
from znicz_tpu.gd_deconv import GDDeconv
from znicz_tpu.memory import Array
from znicz_tpu.pooling import MaxPooling


def test_deconv_is_conv_adjoint():
    """<conv(x), y> == <x, deconv(y)> for all x, y (exact adjoint)."""
    rng = np.random.default_rng(17)
    x = rng.normal(size=(2, 6, 6, 3)).astype(np.float32)
    conv = Conv(name="adc", n_kernels=4, kx=3, ky=3, sliding=(2, 2),
                padding=(1, 1, 1, 1), include_bias=False)
    conv.input = Array(x)
    conv.initialize(device=None)
    conv.run()
    cy = np.array(conv.output.map_read())

    dec = Deconv(name="add", weights_from=conv)
    y = rng.normal(size=cy.shape).astype(np.float32)
    dec.input = Array(y)
    dec.output_shape_from = conv.input
    dec.initialize(device=None)
    dec.run()
    dx = np.array(dec.output.map_read())
    assert dx.shape == x.shape
    np.testing.assert_allclose(np.sum(cy * y), np.sum(x * dx), rtol=1e-4)


def test_deconv_own_weights_shape_inference():
    rng = np.random.default_rng(18)
    y = rng.normal(size=(1, 3, 3, 4)).astype(np.float32)
    dec = Deconv(name="own", n_kernels=4, kx=2, ky=2, sliding=(2, 2))
    dec.input = Array(y)
    dec.initialize(device=None)
    assert dec.weights.shape == (4, 2, 2, 1)
    dec.run()
    assert tuple(dec.output.shape) == (1, 6, 6, 1)


def test_gd_deconv_finite_differences():
    rng = np.random.default_rng(19)
    x = rng.normal(size=(1, 3, 3, 2)).astype(np.float32)
    dec = Deconv(name="gdd", n_kernels=2, kx=2, ky=2, sliding=(2, 2),
                 output_sample_shape=(6, 6, 1))
    dec.input = Array(x)
    dec.initialize(device=None)
    w0 = dec.weights.mem.copy()
    dec.run()
    err = rng.normal(size=dec.output.shape).astype(np.float32)
    gd = GDDeconv(name="gddgd", forward=dec, learning_rate=1.0,
                  need_err_input=True)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    dW = w0 - np.array(dec.weights.map_read())

    import jax
    import jax.numpy as jnp

    def loss(w):
        y = dec.apply({"weights": jnp.asarray(w)}, jnp.asarray(x))
        return float(jnp.sum(jnp.asarray(err) * y))

    eps = 1e-3
    for idx in [(0, 0, 0, 0), (1, 1, 1, 0)]:
        wp = w0.copy(); wp[idx] += eps
        wm = w0.copy(); wm[idx] -= eps
        num = (loss(wp) - loss(wm)) / (2 * eps)
        assert abs(num - dW[idx]) < 5e-2 * max(1.0, abs(num)), idx


def test_depooling_scatters_to_pool_offsets():
    rng = np.random.default_rng(20)
    x = rng.normal(size=(1, 4, 4, 1)).astype(np.float32)
    pool = MaxPooling(name="dpp", kx=2, ky=2)
    pool.input = Array(x)
    pool.initialize(device=None)
    pool.run()
    v = rng.normal(size=(1, 2, 2, 1)).astype(np.float32)
    dep = Depooling(name="dpu", pooling_from=pool)
    dep.input = Array(v)
    dep.initialize(device=None)
    dep.run()
    up = np.array(dep.output.map_read())
    assert up.shape == x.shape
    # each value lands exactly at its window's argmax
    off = np.array(pool.input_offset.map_read())
    want = np.zeros_like(x)
    for oy in range(2):
        for ox in range(2):
            dy, dx = divmod(int(off[0, oy, ox, 0]), 2)
            want[0, oy * 2 + dy, ox * 2 + dx, 0] = v[0, oy, ox, 0]
    np.testing.assert_allclose(up, want)
    # GD gathers back: adjoint round-trip
    gd = GDDepooling(name="dpgd", forward=dep)
    err = rng.normal(size=x.shape).astype(np.float32)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    got = np.array(gd.err_input.map_read())
    for oy in range(2):
        for ox in range(2):
            dy, dx = divmod(int(off[0, oy, ox, 0]), 2)
            assert got[0, oy, ox, 0] == err[0, oy * 2 + dy, ox * 2 + dx, 0]


def test_depooling_over_avg_pooling_spreads_uniformly():
    from znicz_tpu.pooling import AvgPooling

    x = np.ones((1, 4, 4, 1), np.float32)
    pool = AvgPooling(name="dpa", kx=2, ky=2)
    pool.input = Array(x)
    pool.initialize(device=None)
    pool.run()
    v = np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1)
    dep = Depooling(name="dpau", pooling_from=pool)
    dep.input = Array(v)
    dep.initialize(device=None)
    dep.run()
    up = np.array(dep.output.map_read())
    want = np.repeat(np.repeat(v, 2, axis=1), 2, axis=2) / 4.0
    np.testing.assert_allclose(up, want, rtol=1e-6)


@pytest.fixture
def small_ae(tmp_path):
    root.mnist_ae.loader.n_train = 400
    root.mnist_ae.loader.n_valid = 100
    root.mnist_ae.loader.n_test = 0
    root.mnist_ae.loader.minibatch_size = 50
    root.mnist_ae.decision.max_epochs = 5
    root.common.dirs.snapshots = str(tmp_path)
    yield


def test_mnist_ae_trains(small_ae):
    from znicz_tpu.samples import mnist_ae

    losses = []
    wf = mnist_ae.MnistAEWorkflow()
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    wf.initialize(device=None)
    wf.run()
    assert bool(wf.decision.complete)
    assert losses[-1] < losses[0] * 0.7, losses   # reconstruction improves
    # tied weights: encoder and decoder share the same Array
    assert wf.deconv.weights is wf.conv.weights