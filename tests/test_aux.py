"""Aux subsystems: lr_adjust policies, plotting units, image saver,
genetics GA, launcher CLI."""

import json
import os

import numpy as np
import pytest

from znicz_tpu.core.config import Config, root


def test_lr_policies():
    from znicz_tpu.lr_adjust import (ExpPolicy, FixedPolicy, InvPolicy,
                                     StepPolicy, make_policy)

    assert FixedPolicy()(0.1, 500) == 0.1
    assert abs(StepPolicy(gamma=0.1, step=100)(1.0, 250) - 0.01) < 1e-12
    assert abs(ExpPolicy(gamma=0.5)(1.0, 3) - 0.125) < 1e-12
    inv = InvPolicy(gamma=0.1, power=1.0)
    assert abs(inv(1.0, 10) - 0.5) < 1e-12
    assert isinstance(make_policy("step"), StepPolicy)


def test_lr_adjust_rewrites_gd_rates():
    from znicz_tpu.all2all import All2All
    from znicz_tpu.gd import GradientDescent
    from znicz_tpu.lr_adjust import ExpPolicy, LearningRateAdjust
    from znicz_tpu.memory import Array

    fwd = All2All(name="lrfwd", output_sample_shape=(2,))
    fwd.input = Array(np.ones((2, 3), np.float32))
    fwd.initialize(device=None)
    gd = GradientDescent(name="lrgd", forward=fwd, learning_rate=1.0)
    adj = LearningRateAdjust(name="lra")
    adj.add_gd(gd, ExpPolicy(gamma=0.5))
    adj.run()
    assert gd.learning_rate == 1.0      # iteration 0
    adj.run()
    assert gd.learning_rate == 0.5
    adj.run()
    assert gd.learning_rate == 0.25


def test_plotters_render_pngs(tmp_path):
    from znicz_tpu.memory import Array
    from znicz_tpu.plotting_units import (AccumulatingPlotter, MatrixPlotter,
                                          MultiHistogram, Weights2D)

    root.common.dirs.plots = str(tmp_path)
    vals = iter([3.0, 2.0, 1.0])
    acc = AccumulatingPlotter(name="acc_plot", fetch=lambda: next(vals))
    for _ in range(3):
        acc.run()
    assert acc.values == [3.0, 2.0, 1.0]
    assert os.path.exists(acc.path())

    w = Weights2D(name="w_plot",
                  source=Array(np.random.default_rng(0).normal(
                      size=(9, 16)).astype(np.float32)),
                  sample_shape=(4, 4))
    w.run()
    assert os.path.exists(w.path())

    m = MatrixPlotter(name="conf_plot",
                      fetch=lambda: np.eye(4, dtype=np.int32))
    m.run()
    assert os.path.exists(m.path())

    h = MultiHistogram(name="hist_plot",
                       source=Array(np.random.default_rng(1).normal(
                           size=(100,)).astype(np.float32)))
    h.run()
    assert os.path.exists(h.path())


def test_image_saver(tmp_path):
    from znicz_tpu.image_saver import ImageSaver
    from znicz_tpu.memory import Array

    root.common.dirs.image_saver = str(tmp_path)
    sv = ImageSaver(name="imgsave", limit=8)
    rng = np.random.default_rng(3)
    sv.input = Array(rng.random(size=(4, 16)).astype(np.float32))
    sv.labels = Array(np.array([0, 1, 2, 3], np.int32))
    probs = np.full((4, 4), 0.1, np.float32)
    probs[np.arange(4), [0, 1, 0, 0]] = 0.7   # samples 2,3 misclassified
    sv.output = Array(probs)
    sv.batch_size = 4
    sv.epoch_number = 0
    sv.last_minibatch = True
    sv.run()
    files = os.listdir(os.path.join(str(tmp_path), "epoch_0"))
    assert len(files) == 2
    assert any(f.startswith("2_as_0") for f in files)


def test_genetics_finds_minimum():
    from znicz_tpu.genetics import GeneticsOptimizer, Tune, find_tunes

    cfg = Config("groot")
    cfg.model.x = Tune(5.0, -10.0, 10.0)
    cfg.model.y = Tune(-3.0, -10.0, 10.0)
    tunes = find_tunes(cfg)
    assert [p for p, _ in tunes] == ["model.x", "model.y"]

    def evaluate():
        x = cfg.model.get("x")
        y = cfg.model.get("y")
        return (x - 2.0) ** 2 + (y - 1.0) ** 2

    opt = GeneticsOptimizer(evaluate, cfg, generations=12, population=12)
    best, fitness = opt.run()
    assert fitness < 0.5, (best, fitness)
    assert abs(cfg.model.get("x") - 2.0) < 1.0


def test_launcher_runs_sample(tmp_path, capsys):
    from znicz_tpu.launcher import main

    root.common.dirs.snapshots = str(tmp_path)
    rc = main(["mnist",
               "root.mnist.loader.n_train=120",
               "root.mnist.loader.n_valid=60",
               "root.mnist.loader.minibatch_size=60",
               "root.mnist.decision.max_epochs=1",
               "--workflow-graph", str(tmp_path / "g.dot")])
    assert rc == 0
    dot = (tmp_path / "g.dot").read_text()
    assert "repeater" in dot and "->" in dot


def test_launcher_list():
    from znicz_tpu.launcher import main

    assert main(["--list"]) == 0

def test_wine_sample(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    root.wine.decision.max_epochs = 15
    from znicz_tpu.samples import wine

    wf = wine.run()
    dec = wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    # 3 well-separated clusters after mean-disp normalization: near-perfect
    assert valid["err_pct"] < 15.0, valid


def test_device_benchmark_and_aliases():
    from znicz_tpu.accelerated_units import (AcceleratedUnit,
                                             AcceleratedWorkflow,
                                             DeviceBenchmark)
    from znicz_tpu.core.workflow import Workflow
    from znicz_tpu.nn_units import ForwardBase

    assert AcceleratedUnit is ForwardBase
    assert AcceleratedWorkflow is Workflow
    bench = DeviceBenchmark(size=64, repeats=2)
    results = bench.run()
    assert "cpu" in results
    assert bench.best() == "cpu"


def test_standard_workflow_wires_observers(tmp_path):
    """SURVEY §2.2 StandardWorkflow row: plotters and image_saver
    auto-link when asked — error curve / weights tiles / confusion PNGs
    render at epoch ends, misclassified samples get dumped."""
    import os

    from znicz_tpu.core import prng
    from znicz_tpu.samples.mnist import MnistLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.common.dirs.snapshots = str(tmp_path)
    root.common.dirs.plots = str(tmp_path / "plots")
    root.common.dirs.image_saver = str(tmp_path / "imgs")
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    wf = StandardWorkflow(
        name="MnistObs",
        loader=MnistLoader(name="loader", minibatch_size=60),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 50}, "<-": dict(gd)},
                {"type": "softmax",
                 "->": {"output_sample_shape": 10}, "<-": dict(gd)}],
        loss_function="softmax",
        decision_config={"max_epochs": 2},
        image_saver_config={"limit": 8},
        plotters=True)
    wf.initialize(device=None)
    wf.run()
    assert bool(wf.decision.complete)
    pngs = set(os.listdir(tmp_path / "plots"))
    assert {"plot_err.png", "plot_weights.png",
            "plot_confusion.png"} <= pngs
    # plotters only ran at epoch ends (2 epochs -> 2 accumulated points)
    assert len(wf.plotters[0].values) == 2
    # misclassified dumps exist for at least one epoch
    epochs = os.listdir(tmp_path / "imgs")
    assert epochs and any(os.listdir(tmp_path / "imgs" / e)
                          for e in epochs)
    # the stop lap must NOT advance the loader past the end of training
    # (EndPoint waits on the plot chain; the repeater is blocked once
    # complete)
    assert wf.loader.samples_served == 2 * (120 + 60)
    # the error plotter recorded real (non-default) metric values
    assert any(v > 0 for v in wf.plotters[0].values)


def test_fused_engine_runs_plotters_at_epoch_ends(tmp_path):
    """The fast path drives epoch-granular plotters too (writeback puts
    weights in the unit Arrays before the hook)."""
    import os

    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples.mnist import MnistLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.reset(1013)
    root.mnist.loader.n_train = 120
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.common.dirs.snapshots = str(tmp_path)
    root.common.dirs.plots = str(tmp_path / "plots")
    gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
    wf = StandardWorkflow(
        name="MnistObsFused",
        loader=MnistLoader(name="loader", minibatch_size=60),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 50}, "<-": dict(gd)},
                {"type": "softmax",
                 "->": {"output_sample_shape": 10}, "<-": dict(gd)}],
        loss_function="softmax",
        decision_config={"max_epochs": 2},
        plotters=True)
    wf.initialize(device=None)
    FusedTrainer(wf).run()
    assert bool(wf.decision.complete)
    assert len(wf.plotters[0].values) == 2          # one point per epoch
    pngs = set(os.listdir(tmp_path / "plots"))
    assert {"plot_err.png", "plot_weights.png",
            "plot_confusion.png"} <= pngs


def test_plotters_mse_workflow(tmp_path):
    """plotters=True on an MSE workflow plots the validation loss (the
    err_pct key does not exist there — review finding)."""
    import os

    from znicz_tpu.core import prng
    from znicz_tpu.samples.video_ae import VideoAELoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    prng.reset(1013)
    root.video_ae.loader.n_train = 200
    root.video_ae.loader.n_valid = 100
    root.video_ae.loader.minibatch_size = 100
    root.common.dirs.snapshots = str(tmp_path)
    root.common.dirs.plots = str(tmp_path / "plots")
    gd = {"learning_rate": 0.05, "gradient_moment": 0.9}
    wf = StandardWorkflow(
        name="VideoAEPlots",
        loader=VideoAELoader(name="loader", targets_from_data=True,
                             minibatch_size=100),
        layers=[{"type": "all2all_tanh",
                 "->": {"output_sample_shape": 24}, "<-": dict(gd)},
                {"type": "all2all",
                 "->": {"output_sample_shape": (16, 16)}, "<-": dict(gd)}],
        loss_function="mse",
        decision_config={"max_epochs": 2},
        plotters=True)
    wf.initialize(device=None)
    wf.run()
    assert bool(wf.decision.complete)
    assert len(wf.plotters[0].values) == 2
    assert all(v > 0 for v in wf.plotters[0].values)   # real MSE values
    assert wf.plotters[0].ylabel == "valid loss"
    assert os.path.exists(tmp_path / "plots" / "plot_err.png")
