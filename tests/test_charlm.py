"""charlm — the first sequence workload end-to-end (ISSUE 15): seeded
convergence band under FusedTrainer, fused-tail on/off parity, the unit
engine's seq evaluator, snapshot -> inference-load -> serving, the
master/slave role, and the launcher CLI (solo + --serve)."""

import threading

import numpy as np
import pytest

from znicz_tpu.core.config import root


def _tiny_charlm_cfg(tmp_path=None, max_epochs=2, seq_len=32):
    from znicz_tpu.core import prng

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 384, "n_valid": 48, "n_test": 0,
                               "seq_len": seq_len, "minibatch_size": 32})
    root.charlm.model.update({"vocab": 32, "embed": 48, "heads": 2,
                              "ffn": 96})
    root.charlm.learning_rate = 1.0
    root.charlm.decision.max_epochs = max_epochs
    if tmp_path is not None:
        root.common.dirs.snapshots = str(tmp_path)


def _build(tmp_path=None, **kw):
    from znicz_tpu.samples.charlm import CharLMWorkflow

    _tiny_charlm_cfg(tmp_path, **kw)
    wf = CharLMWorkflow()
    wf.initialize(device=None)
    if tmp_path is not None:
        wf.snapshotter.directory = str(tmp_path)
    return wf


def _params_of(wf):
    return {f.name: {k: np.array(a.map_read())
                     for k, a in f.params().items()}
            for f in wf.forwards}


def _train_fused(tmp_path, fused_tail: bool, max_epochs=3):
    from znicz_tpu.engine import train

    root.common.engine.fused = True
    root.common.engine.fused_tail = fused_tail
    try:
        wf = _build(tmp_path, max_epochs=max_epochs)
        train(wf)
    finally:
        root.common.engine.fused = False
        root.common.engine.fused_tail = False
    return wf


def test_charlm_fused_converges_seeded_band(tmp_path):
    """The acceptance band: charlm trains under FusedTrainer to a
    seeded convergence band — token error on VALID collapses far below
    the ~97% random baseline for vocab 32 (the stride corpus needs
    CONTEXT, so the attention layer is load-bearing)."""
    wf = _train_fused(tmp_path, fused_tail=False, max_epochs=8)
    dec = wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    # err_pct here counts TOKEN errors over VALID samples x seq_len
    err = valid["n_err"] / (48 * 32) * 100.0
    assert err < 50.0, (err, valid)


def test_charlm_fused_tail_parity(tmp_path):
    """The fused seq-FFN/softmax epilogues (fused_tail on) reproduce
    the composed path within the PR 7 parity regime over a short
    horizon (identical metrics, params to 5e-3 after 2 epochs —
    longer horizons diverge chaotically under momentum, exactly as
    PR 7 pinned for the AlexNet tail)."""
    wf_off = _train_fused(tmp_path / "off", fused_tail=False,
                          max_epochs=2)
    wf_on = _train_fused(tmp_path / "on", fused_tail=True, max_epochs=2)
    assert wf_on.decision.epoch_metrics[1]["n_err"] == pytest.approx(
        wf_off.decision.epoch_metrics[1]["n_err"], rel=0.05)
    p_off, p_on = _params_of(wf_off), _params_of(wf_on)
    for name in p_off:
        for k in p_off[name]:
            np.testing.assert_allclose(
                p_off[name][k], p_on[name][k], rtol=5e-3, atol=5e-4,
                err_msg=f"{name}.{k} fused-tail parity")
    # the seq epilogue actually matched: plan covers the FFN
    from znicz_tpu.pallas_fused_block import plan_fused_tail

    root.common.engine.fused_tail = True
    try:
        plan = plan_fused_tail(wf_on.forwards)
    finally:
        root.common.engine.fused_tail = False
    kinds = {spec.kind for spec in plan.values()}
    assert "seq_epilogue" in kinds, plan


def test_charlm_unit_engine_matches_fused_direction(tmp_path):
    """The unit-at-a-time engine (the reference execution semantics)
    trains the same graph: loss drops and the first-epoch VALID error
    lands near the fused run's (same seeded data, same update rule)."""
    from znicz_tpu.engine import train

    wf = _build(tmp_path, max_epochs=6)
    train(wf)
    dec = wf.decision
    assert bool(dec.complete)
    assert dec.epoch_metrics[1] is not None
    assert dec.epoch_metrics[1]["n_err"] < 0.60 * 48 * 32


def test_charlm_snapshot_serves_variable_length(tmp_path):
    """Snapshot -> snapshotter inference-load -> InferenceServer: the
    charlm checkpoint loads like any other (satellite 6), the service
    runs the 2-D ladder (declared by the workflow), variable-length
    requests come back (n, len, vocab) with zero recompiles after
    warmup, and a probe's rows are a bit-exact pure function of its own
    rows + own length within a pinned bucket."""
    from znicz_tpu import snapshotter
    from znicz_tpu.engine import train
    from znicz_tpu.serving import InferenceClient, InferenceServer

    wf = _build(tmp_path, max_epochs=1)
    train(wf)
    path = wf.snapshotter.save("charlm_serve_test")
    trained = _params_of(wf)

    fresh = _build()
    meta = snapshotter.load_inference(fresh, path)
    assert "units" not in meta
    for f in fresh.forwards:
        for k, a in f.params().items():
            np.testing.assert_array_equal(np.array(a.map_read()),
                                          trained[f.name][k])

    srv = InferenceServer(fresh, max_batch=4, max_delay_ms=2.0).start()
    cli = InferenceClient(srv.endpoint, timeout=60)
    try:
        ladder = srv.batcher.ladder
        assert ladder.seq_rungs is not None
        assert ladder.seq_rungs[-1] == 32      # the trained window
        warm = srv.runner.compiles
        assert warm == len(ladder.buckets())
        rng = np.random.default_rng(5)
        for L in (3, 9, 17, 32, 5):
            y = cli.infer(rng.integers(1, 32, size=(2, L)
                                       ).astype(np.uint8))
            assert y.shape == (2, L, 32), (L, y.shape)
            assert np.all(np.isfinite(y))
        assert srv.runner.compiles == warm      # zero recompiles
        # masked 0-ULP: probe co-batched with different same-rung
        # neighbors (rows rung pinned at 4) comes back bit-identical
        probe = rng.integers(1, 32, size=(2, 10)).astype(np.uint8)
        replies = []
        for fill_len in (9, 12, 16):
            fill = rng.integers(1, 32, size=(2, fill_len)
                                ).astype(np.uint8)
            rid_p, rid_f = cli.submit(probe), cli.submit(fill)
            got = {}
            while len(got) < 2:
                for rep in cli.collect(0.05):
                    got[rep["req_id"]] = rep
            assert got[rid_p].get("ok") and got[rid_f].get("ok")
            replies.append(got[rid_p]["y"])
        assert all(np.array_equal(replies[0], y) for y in replies[1:])
        # pad_ratio is measured and exported
        stats = srv.batcher.stats()
        assert stats["real_cells"] > 0
        assert isinstance(stats["pad_ratio"], dict)
    finally:
        cli.close()
        srv.stop()


def test_charlm_master_slave_trains(tmp_path):
    """The distributed role needs no special-casing: a charlm master
    serves jobs to a charlm slave over wire v3 and training completes
    with the deltas applied (satellite 6).  lr is kept at 0.3 here: the
    aggressive-lr momentum ramp the solo tests use grows delta norms
    past the master's 25x-running-median quarantine (the PR 2 fault
    model working exactly as designed — refuse-and-requeue), which is
    chaos-harness territory, not this role test's."""
    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17693"

    def build_ms(tag):
        from znicz_tpu.samples.charlm import CharLMWorkflow

        _tiny_charlm_cfg(tag, max_epochs=2)
        root.charlm.learning_rate = 0.3
        wf = CharLMWorkflow()
        wf.initialize(device=None)
        wf.snapshotter.directory = str(tag)
        return wf

    master_wf = build_ms(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=60.0)
    slave = Client(build_ms(tmp_path / "s"),
                   endpoint=endpoint, slave_id="charlm0")
    errors = []

    def worker():
        try:
            slave.run()
        except BaseException as e:
            errors.append(repr(e))
            raise

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    server.serve()
    t.join(timeout=60)
    assert not errors, errors
    assert not t.is_alive()
    assert bool(master_wf.decision.complete)
    assert server.jobs_done > 0
    assert server.jobs_by_slave.get("charlm0", 0) > 0


def test_launcher_charlm_solo_cli(tmp_path):
    """``python -m znicz_tpu charlm`` (satellite 6): the bundled-sample
    name resolves and a tiny solo run completes."""
    from znicz_tpu.launcher import SAMPLES, main

    assert "charlm" in SAMPLES
    rc = main([
        "charlm",
        "root.charlm.loader.n_train=96",
        "root.charlm.loader.n_valid=32",
        "root.charlm.loader.seq_len=16",
        "root.charlm.decision.max_epochs=1",
        f"root.common.dirs.snapshots={tmp_path}",
    ])
    assert rc == 0


def test_launcher_charlm_serve_cli(tmp_path):
    """``--serve`` on the charlm sample (satellite 6): the launcher
    builds the workflow without training, the service comes up on the
    2-D ladder, and variable-length uint8 requests are answered."""
    from znicz_tpu.launcher import main
    from znicz_tpu.serving import InferenceClient

    _tiny_charlm_cfg(tmp_path, seq_len=16)
    endpoint = "tcp://127.0.0.1:17694"
    root.common.serving.max_requests = 2
    rc = {}

    def run_cli():
        rc["code"] = main([
            "charlm", "--serve", endpoint,
            "root.charlm.loader.n_train=96",
            "root.charlm.loader.n_valid=32",
            "root.charlm.loader.seq_len=16",
            "root.common.serving.max_batch=4",   # 3x5 buckets to warm
        ])

    t = threading.Thread(target=run_cli)
    t.start()
    try:
        # resend_after_s past the timeout: a resend during the 2-D
        # warmup would burn the server's max_requests budget on a
        # duplicate and strand the second request
        cli = InferenceClient(endpoint, timeout=90, resend_after_s=120.0)
        try:
            y = cli.infer(np.ones((2, 5), np.uint8), timeout=90)
            assert y.shape == (2, 5, 32)
            y = cli.infer(np.ones((1, 16), np.uint8), timeout=90)
            assert y.shape == (1, 16, 32)
        finally:
            cli.close()
        t.join(timeout=60)
        assert not t.is_alive()
        assert rc["code"] == 0
    finally:
        root.common.serving.max_requests = None
        t.join(timeout=5)
