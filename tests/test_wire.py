"""Wire protocol v3 (ISSUE 3): codec roundtrip property tests, delta
quantization with error feedback, frame validation, legacy (v2)
handling — and the seeded end-to-end acceptance run: an int8 wire with
error feedback reaches the f32 wire's final loss while moving >= 3.5x
fewer bytes per update, with the job-prefetch pipeline reporting hits."""

import pickle
import threading

import numpy as np
import pytest

from znicz_tpu.core.config import root
from znicz_tpu.parallel import wire


# -- roundtrip property tests --------------------------------------------------


def _assert_same_tree(a, b):
    assert type(a) is type(b) or (isinstance(a, np.ndarray)
                                  and isinstance(b, np.ndarray))
    if isinstance(a, dict):
        assert a.keys() == b.keys()
        for k in a:
            _assert_same_tree(a[k], b[k])
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _assert_same_tree(x, y)
    elif isinstance(a, np.ndarray):
        assert a.shape == b.shape and a.dtype == b.dtype, (a.dtype, b.dtype)
        np.testing.assert_array_equal(a, b)
    else:
        assert a == b


def test_roundtrip_raw_tensors_exact():
    """f32 wire: every ndarray — scalar (0-d), empty, non-contiguous,
    bool/int/float dtypes, nested in dicts/lists/tuples — comes back
    bit-exact with shape and dtype preserved; non-array leaves ride the
    metadata untouched."""
    rng = np.random.default_rng(7)
    msg = {
        "cmd": "update", "job_id": 3, "note": "plain strings survive",
        "deltas": {"conv1": {"weights": rng.normal(
            size=(5, 3, 3, 4)).astype(np.float32),
            "bias": rng.normal(size=4).astype(np.float64)}},
        "scalar": np.array(2.5, np.float32),            # 0-d
        "empty": np.zeros((0, 3), np.float32),          # zero rows
        "noncontig": np.arange(24).reshape(4, 6)[:, ::2],
        "bools": np.array([True, False, True]),
        "mixed": [np.int16([1, 2, 3]), (np.uint8([9]), "tail"), 1.25],
    }
    frames, enc = wire.encode_message(msg)
    # one metadata frame + one buffer frame per tensor, nothing pickled
    # twice: the tensor bytes are NOT inside frame 0
    assert len(frames) == 1 + enc["tensors"]
    assert enc["tensors"] == 8
    dec, info = wire.decode_message(frames)
    assert not info["legacy"]
    _assert_same_tree(msg, dec)
    # raw wire: logical bytes == wire bytes (no quantization applied)
    assert enc["raw_bytes"] == enc["wire_bytes"] > 0
    assert info["raw_bytes"] == enc["raw_bytes"]


@pytest.mark.parametrize("wire_dtype,bytes_per_el,tol_of_absmax", [
    ("bfloat16", 2, 1 / 256),     # bf16: 8 mantissa bits
    ("int8", 1, 1 / 254 + 1e-7),  # absmax/127 scale, round-to-nearest
])
def test_quantized_roundtrip_error_bounds(wire_dtype, bytes_per_el,
                                          tol_of_absmax):
    rng = np.random.default_rng(11)
    for shape in [(64, 32), (7,), (1,), (), (0,)]:
        a = (rng.normal(size=shape) * 0.01).astype(np.float32)
        qt = wire.quantize(a, wire_dtype)
        assert isinstance(qt, wire.QuantizedTensor)
        frames, enc = wire.encode_message({"d": qt})
        assert enc["wire_bytes"] == a.size * bytes_per_el
        assert enc["raw_bytes"] == a.size * 4
        dec, _ = wire.decode_message(frames)
        back = dec["d"]
        assert back.shape == a.shape and back.dtype == np.float32
        if a.size:
            absmax = float(np.max(np.abs(a)))
            assert np.max(np.abs(back - a)) <= tol_of_absmax * absmax + 1e-9


def test_int8_error_feedback_keeps_cumulative_error_bounded():
    """The error-feedback property (Seide'14): the SUM of dequantized
    deltas tracks the sum of true deltas to within ~one step's
    quantization grid, not the naive O(sqrt(steps)) random-walk error —
    this is why int8 training converges like f32."""
    enc = wire.DeltaEncoder("int8")
    rng = np.random.default_rng(3)
    true_sum = np.zeros((32, 16), np.float32)
    wire_sum = np.zeros_like(true_sum)
    naive_err = 0.0
    max_scale = 0.0
    for _ in range(100):
        d = rng.normal(0, 0.01, true_sum.shape).astype(np.float32)
        true_sum += d
        qt = enc.encode({"l": {"w": d}})["l"]["w"]
        max_scale = max(max_scale, qt.scale)
        wire_sum += wire.dequantize(qt)
        naive = wire.quantize(d, "int8")
        naive_err += np.max(np.abs(wire.dequantize(naive) - d))
    fed_err = float(np.max(np.abs(true_sum - wire_sum)))
    # with feedback: bounded by ~one quantization step, forever
    assert fed_err <= 2 * max_scale, (fed_err, max_scale)
    # without feedback the per-step errors accumulate far past that
    assert naive_err > 10 * fed_err


def test_nonfinite_deltas_bypass_quantization():
    """int8 cannot carry a NaN — a diverging slave's non-finite delta is
    shipped RAW so the master's quarantine still sees it."""
    enc = wire.DeltaEncoder("int8")
    d = {"l": {"w": np.array([np.nan, 1.0], np.float32)}}
    out = enc.encode(d)["l"]["w"]
    assert isinstance(out, np.ndarray)          # not QuantizedTensor
    frames, _ = wire.encode_message({"deltas": out})
    dec, _ = wire.decode_message(frames)
    assert np.isnan(dec["deltas"][0]) and dec["deltas"][1] == 1.0


def test_compression_roundtrip_and_ratio():
    """Cold-path params compression: zlib shrinks compressible tensors
    (and is dropped when it would not help); lz4 degrades to raw when the
    library is absent."""
    msg = {"params": {"fc": {"weights": np.zeros((64, 64), np.float32)}}}
    frames, enc = wire.encode_message(msg, compress="zlib")
    assert enc["wire_bytes"] < enc["raw_bytes"] / 10
    dec, info = wire.decode_message(frames)
    np.testing.assert_array_equal(dec["params"]["fc"]["weights"],
                                  msg["params"]["fc"]["weights"])
    assert info["raw_bytes"] / info["wire_bytes"] > 10
    # incompressible noise (full-entropy bytes): the compressed frame
    # would be LARGER, so the codec keeps the raw buffer
    noise = {"w": np.random.default_rng(0).integers(
        0, 256, (64, 64), dtype=np.uint8)}
    frames, enc = wire.encode_message(noise, compress="zlib")
    assert enc["wire_bytes"] == enc["raw_bytes"]
    # lz4 path: roundtrips when available, silently raw when not
    frames, _ = wire.encode_message(msg, compress="lz4")
    dec, _ = wire.decode_message(frames)
    np.testing.assert_array_equal(dec["params"]["fc"]["weights"],
                                  msg["params"]["fc"]["weights"])


def test_corrupt_and_short_frames_detected():
    """A tampered tensor frame (wrong length), a truncated metadata
    frame, and a wrong frame COUNT are all WireErrors — never silently
    reshaped garbage."""
    msg = {"deltas": {"l": {"w": np.ones((16, 16), np.float32)}},
           "empty": np.zeros(0, np.float32)}
    frames, _ = wire.encode_message(msg)
    from znicz_tpu.parallel.chaos import corrupt_payload

    for i in range(len(frames)):        # corrupt EVERY frame in turn
        bad = [bytes(f) if isinstance(f, bytes) else bytes(f)
               for f in frames]
        bad[i] = corrupt_payload(bad[i])
        with pytest.raises(wire.WireError):
            wire.decode_message(bad)
    with pytest.raises(wire.WireError):
        wire.decode_message(frames[:-1])        # frame count mismatch
    with pytest.raises(wire.WireError):
        wire.decode_message([])


def test_legacy_v2_frame_detected_and_refused_readably(tmp_path):
    """A v2 peer's single-pickle frame decodes with legacy=True; the
    server answers a v2-version register with a refusal IN LEGACY
    FRAMING that names both protocol revisions — the old slave can read
    why it was turned away."""
    obj, info = wire.decode_message([pickle.dumps({"cmd": "job"})])
    assert info["legacy"] and obj == {"cmd": "job"}

    import tests.test_master_slave as tms

    master_wf = tms._make_workflow(tmp_path / "m")
    from znicz_tpu.server import Server

    server = Server(master_wf)
    legacy_register = pickle.dumps(
        {"cmd": "register", "id": "old", "version": 2,
         "workflow_digest": "whatever"})
    rep_frames = server._reply_frames([legacy_register])
    assert len(rep_frames) == 1                 # legacy framing back
    rep = pickle.loads(rep_frames[0])           # a v2 peer CAN read it
    assert rep["ok"] is False
    assert "version mismatch" in rep["error"]
    assert "v3 multipart" in rep["error"]
    assert "old" not in server.slaves


def test_split_envelope_edges():
    """The ROUTER-framing splitter: empty frames BEFORE the payload are
    the delimiter, but an empty TENSOR frame inside a delimiter-less v3
    stack (direct REP traffic) must not be mistaken for one — the magic
    on the metadata frame marks where payload begins."""
    meta = wire.MAGIC + b"\x80"
    assert wire.split_envelope([b"id", b"\x00\x01", b"", meta, b""]) == \
        ([b"id", b"\x00\x01", b""], [meta, b""])
    assert wire.split_envelope([meta, b"", b"data"]) == \
        ([], [meta, b"", b"data"])                  # empty tensor frame
    assert wire.split_envelope([b"legacy-pickle"]) == \
        ([], [b"legacy-pickle"])
    # and the REAL encode of an empty tensor roundtrips through a
    # delimiter-less stack unharmed
    frames, _ = wire.encode_message({"e": np.zeros(0, np.float32)})
    env, payload = wire.split_envelope([bytes(f) for f in frames])
    assert env == [] and len(payload) == 2
    dec, _ = wire.decode_message(payload)
    assert dec["e"].shape == (0,)


def test_wire_dtype_canonicalization():
    assert wire.canonical_wire_dtype("bf16") == "bfloat16"
    assert wire.canonical_wire_dtype("") == "float32"
    with pytest.raises(ValueError, match="wire_dtype"):
        wire.canonical_wire_dtype("int4")


# -- the seeded end-to-end acceptance run --------------------------------------


def _run_fleet(tmp_path, endpoint, n_slaves=2):
    """One seeded 2-slave master/slave training; returns (server, slaves,
    final validation err%)."""
    import tests.test_master_slave as tms
    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    master_wf = tms._make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=60.0)
    slaves = [Client(tms._make_workflow(tmp_path / f"s{i}"),
                     endpoint=endpoint, slave_id=f"w{i}")
              for i in range(n_slaves)]
    errors = []

    def worker(s):
        try:
            s.run()
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    server.serve()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)
    dec = master_wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    assert valid is not None
    return server, slaves, float(valid["err_pct"])


def test_int8_wire_matches_f32_with_3_5x_fewer_bytes(tmp_path):
    """THE acceptance run (ISSUE 3): the same seeded 2-slave MNIST
    training once over the f32 wire and once over int8+error-feedback.
    The int8 run must (a) move >= 3.5x fewer bytes per update (server
    counters), (b) land in the same converged quality band, and (c) show
    the prefetch pipeline actually hiding fetches (nonzero prefetch
    hits on both client and server sides)."""
    old = root.common.engine.get("wire_dtype", None)
    try:
        root.common.engine.wire_dtype = "float32"
        srv_f32, slaves_f32, err_f32 = _run_fleet(
            tmp_path / "f32", "tcp://127.0.0.1:17640")
        root.common.engine.wire_dtype = "int8"
        srv_i8, slaves_i8, err_i8 = _run_fleet(
            tmp_path / "i8", "tcp://127.0.0.1:17641")
    finally:
        if old is None:
            del root.common.engine.wire_dtype
        else:
            root.common.engine.wire_dtype = old

    # (a) bytes per update: >= 3.5x fewer on the int8 wire, vs BOTH the
    # f32-v3 wire (server counters) and a measured v2 baseline — the
    # pickle blob a v2 slave would have shipped for one representative
    # update (this fleet's full trainable delta set + metrics)
    bpu_f32 = srv_f32.bytes_per_update()
    bpu_i8 = srv_i8.bytes_per_update()
    assert bpu_f32 and bpu_i8 and srv_i8.updates_received > 0
    assert bpu_f32 >= 3.5 * bpu_i8, (bpu_f32, bpu_i8)
    v2_update = {"cmd": "update", "id": "w0", "job_id": 1,
                 "deltas": {f.name: {k: np.asarray(a.map_read(),
                                                   np.float32)
                                     for k, a in f.params().items()}
                            for f in srv_f32.workflow.forwards
                            if f.has_weights},
                 "metrics": {"loss": 1.0, "n_err": 0,
                             "confusion": np.zeros((10, 10), np.int64)}}
    v2_bytes = len(pickle.dumps(v2_update, pickle.HIGHEST_PROTOCOL))
    assert v2_bytes >= 3.5 * bpu_i8, (v2_bytes, bpu_i8)
    # (b) convergence parity: same converged band as every other seeded
    # master/slave test (async replicas differ run to run regardless of
    # wire; both must land converged)
    assert err_f32 < 70.0 and err_i8 < 70.0, (err_f32, err_i8)
    assert abs(err_i8 - err_f32) < 25.0, (err_f32, err_i8)
    # (c) the prefetch pipeline engaged: jobs were fetched ahead on the
    # second socket and consumed without a blocking round trip
    for srv, slaves in ((srv_f32, slaves_f32), (srv_i8, slaves_i8)):
        assert srv.prefetch_hit > 0
        assert sum(s.prefetch_hits for s in slaves) > 0
    # the server-side compression accounting agrees: int8 tensor traffic
    # shrank the INBOUND tensor bytes ~4x (metadata excluded; the
    # outbound params broadcast stays f32 and dilutes the combined ratio)
    ratio = srv_i8.compression_ratio("in")
    assert ratio is not None and ratio > 3.0, ratio
    combined = srv_i8.compression_ratio()
    assert combined is not None and 1.0 < combined < ratio
    # books still balance on both wires
    for srv in (srv_f32, srv_i8):
        assert srv.jobs_done == sum(srv.jobs_by_slave.values())
        assert srv.bytes_in > 0 and srv.bytes_out > 0


def test_bf16_wire_end_to_end(tmp_path):
    """The bf16 wire (2x fewer delta bytes, no scale bookkeeping) also
    trains to the quality band — the cheap middle ground."""
    old = root.common.engine.get("wire_dtype", None)
    try:
        root.common.engine.wire_dtype = "bf16"      # alias spelling
        srv, slaves, err = _run_fleet(
            tmp_path / "bf16", "tcp://127.0.0.1:17642", n_slaves=1)
    finally:
        if old is None:
            del root.common.engine.wire_dtype
        else:
            root.common.engine.wire_dtype = old
    assert err < 70.0, err
    assert slaves[0].wire_dtype == "bfloat16"
    ratio = srv.compression_ratio("in")
    assert ratio is not None and ratio > 1.5, ratio


def test_wire_compress_params_broadcast(tmp_path):
    """root.common.engine.wire_compress=zlib shrinks the master->slave
    params broadcast; training is unchanged."""
    old = root.common.engine.get("wire_compress", None)
    try:
        root.common.engine.wire_compress = "zlib"
        srv, _, err = _run_fleet(
            tmp_path / "z", "tcp://127.0.0.1:17643", n_slaves=1)
    finally:
        if old is None:
            del root.common.engine.wire_compress
        else:
            root.common.engine.wire_compress = old
    assert err < 70.0, err
    assert srv.wire_compress == "zlib"
    ratio = srv.compression_ratio("out")
    assert ratio is not None and ratio > 1.0, ratio


def test_peek_and_restamp_share_tensor_frames():
    """The balancer's forward path (ISSUE 12): peek reads the skeleton
    without materializing tensors, restamp rewrites top-level keys while
    the tensor frames are SHARED bytes — and both refuse corruption."""
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    frames, _ = wire.encode_message(
        {"cmd": "infer", "req_id": 5, "client": "c", "x": x})
    skel = wire.peek_message(frames)
    assert skel["cmd"] == "infer" and skel["req_id"] == 5
    # the tensor leaf is a slot placeholder, never a materialized array
    assert not isinstance(skel["x"], np.ndarray)
    # restamp: req_id rewritten, lb added, client REMOVED (None), the
    # tensor frame is the very same bytes object
    out = wire.restamp_message(frames, req_id=99, lb=True, client=None)
    assert out[1] is frames[1]
    msg, _ = wire.decode_message(out)
    assert msg["req_id"] == 99 and msg["lb"] is True
    assert "client" not in msg
    np.testing.assert_array_equal(msg["x"], x)
    # round-trip restamp restores the original id byte-compatibly
    back, _ = wire.decode_message(wire.restamp_message(out, req_id=5,
                                                       lb=None))
    assert back["req_id"] == 5 and "lb" not in back
    # corruption refusals: torn metadata, a length-mismatched tensor
    # frame, a missing frame, and legacy framing all raise at peek
    from znicz_tpu.parallel.chaos import corrupt_payload

    with pytest.raises(wire.WireError):
        wire.peek_message([corrupt_payload(bytes(frames[0]))]
                          + frames[1:])
    with pytest.raises(wire.WireError):
        wire.peek_message([frames[0],
                           corrupt_payload(bytes(frames[1]))])
    with pytest.raises(wire.WireError):
        wire.peek_message(frames[:1])
    with pytest.raises(wire.WireError):
        wire.peek_message([pickle.dumps({"cmd": "infer"})])
    with pytest.raises(wire.WireError):
        wire.restamp_message([pickle.dumps({"a": 1})], lb=True)
    # a non-dict skeleton cannot be a request: refused at peek
    listy, _ = wire.encode_message([1, 2, 3])
    with pytest.raises(wire.WireError):
        wire.peek_message(listy)
