"""Attention: single-device correctness, ring-attention sequence
parallelism over 8 virtual devices (exactness vs full attention), MHA unit
fwd/bwd."""

import numpy as np
import pytest

from znicz_tpu.memory import Array
from znicz_tpu.ops.attention import attention, ring_attention


def np_attention(q, k, v, causal=False):
    b, t, h, d = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.triu(np.ones((t, t), bool), 1)
        s = np.where(mask[None, None], -np.inf, s)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_attention_matches_numpy(causal):
    rng = np.random.default_rng(31)
    q, k, v = (rng.normal(size=(2, 8, 2, 4)).astype(np.float32)
               for _ in range(3))
    got = np.array(attention(q, k, v, causal=causal))
    want = np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact_over_8_shards(causal):
    import jax
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.parallel.mesh import make_mesh, shard_map

    mesh = make_mesh(axes=("sp",))
    n = mesh.shape["sp"]
    assert n == 8
    rng = np.random.default_rng(33)
    T = 8 * n                                    # 8 tokens per device
    q, k, v = (rng.normal(size=(2, T, 2, 4)).astype(np.float32)
               for _ in range(3))

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"))
    got = np.array(ring(q, k, v))
    want = np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_mha_unit_fwd_bwd():
    from znicz_tpu.attention import GDMultiHeadAttention, MultiHeadAttention

    rng = np.random.default_rng(35)
    x = rng.normal(size=(2, 6, 8)).astype(np.float32)
    mha = MultiHeadAttention(name="mha", heads=2, causal=True)
    mha.input = Array(x)
    mha.initialize(device=None)
    mha.run()
    out = np.array(mha.output.map_read())
    assert out.shape == x.shape
    # oracle
    q = (x @ mha.proj["wq"].mem).reshape(2, 6, 2, 4)
    k = (x @ mha.proj["wk"].mem).reshape(2, 6, 2, 4)
    v = (x @ mha.proj["wv"].mem).reshape(2, 6, 2, 4)
    want = np_attention(q, k, v, causal=True).reshape(2, 6, 8) \
        @ mha.proj["wo"].mem
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    gd = GDMultiHeadAttention(name="mhagd", forward=mha, learning_rate=1.0,
                              need_err_input=True)
    err = rng.normal(size=out.shape).astype(np.float32)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    w0 = mha.proj["wo"].mem.copy()
    gd.run()
    dW = w0 - np.array(mha.proj["wo"].map_read())

    eps = 1e-2
    import jax.numpy as jnp

    def loss(wo):
        params = {kk: jnp.asarray(a.mem) for kk, a in mha.proj.items()}
        params["wo"] = jnp.asarray(wo)
        return float(jnp.sum(jnp.asarray(err) * mha.apply(params,
                                                          jnp.asarray(x))))

    for idx in [(0, 0), (5, 3)]:
        wp = w0.copy(); wp[idx] += eps
        wm = w0.copy(); wm[idx] -= eps
        num = (loss(wp) - loss(wm)) / (2 * eps)
        assert abs(num - dW[idx]) < 5e-2 * max(1.0, abs(num)), idx
    assert np.array(gd.err_input.map_read()).shape == x.shape

def test_sequence_parallel_training_grads_match_and_learn():
    """Long-context training end-to-end: grads flow THROUGH ring attention
    under shard_map over an ('sp',) mesh, match the single-device
    computation exactly, and a few SGD steps reduce the loss."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.ops.attention import attention, ring_attention
    from znicz_tpu.parallel.mesh import make_mesh, shard_map

    B, T, H, D, E = 2, 32, 2, 8, 16
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(B, T, E)).astype(np.float32))
    y = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
    params = {k: jnp.asarray(rng.normal(size=(E, H * D)).astype(np.float32)
                             / np.sqrt(E))
              for k in ("wq", "wk", "wv")}
    params["wo"] = jnp.asarray(
        rng.normal(size=(H * D, E)).astype(np.float32) / np.sqrt(H * D))

    def model(p, x, ring):
        b, t, e = x.shape
        q = (x @ p["wq"]).reshape(b, t, H, D)
        k = (x @ p["wk"]).reshape(b, t, H, D)
        v = (x @ p["wv"]).reshape(b, t, H, D)
        o = (ring_attention(q, k, v, "sp", causal=True) if ring
             else attention(q, k, v, causal=True))
        return o.reshape(b, t, H * D) @ p["wo"]

    mesh = make_mesh((8,), ("sp",))

    def sp_loss(p, x, y):
        # x/y arrive sequence-sharded: (B, T/8, E) per device
        out = model(p, x, ring=True)
        local = jnp.mean(jnp.square(out - y))
        return jax.lax.pmean(local, "sp")

    spec = P(None, "sp", None)
    sharded_loss = shard_map(sp_loss, mesh=mesh, in_specs=(P(), spec, spec),
                             out_specs=P())

    def ref_loss(p, x, y):
        return jnp.mean(jnp.square(model(p, x, ring=False) - y))

    g_sp = jax.jit(jax.grad(sharded_loss))(params, x, y)
    g_ref = jax.jit(jax.grad(ref_loss))(params, x, y)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_sp[k]),
                                   np.asarray(g_ref[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

    # a few sequence-parallel SGD steps actually learn
    @jax.jit
    def step(p, x, y):
        loss, g = jax.value_and_grad(sharded_loss)(p, x, y)
        return {k: p[k] - 0.3 * g[k] for k in p}, loss

    losses = []
    p = params
    for _ in range(30):
        p, loss = step(p, x, y)
        losses.append(float(loss))
    assert losses[-1] < 0.8 * losses[0], losses
    assert all(b <= a + 1e-6 for a, b in zip(losses, losses[1:])), losses
