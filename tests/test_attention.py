"""Attention: single-device correctness, ring-attention sequence
parallelism over 8 virtual devices (exactness vs full attention), MHA unit
fwd/bwd."""

import numpy as np
import pytest

from znicz_tpu.memory import Array
from znicz_tpu.ops.attention import attention, ring_attention


def np_attention(q, k, v, causal=False):
    b, t, h, d = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.triu(np.ones((t, t), bool), 1)
        s = np.where(mask[None, None], -np.inf, s)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_attention_matches_numpy(causal):
    rng = np.random.default_rng(31)
    q, k, v = (rng.normal(size=(2, 8, 2, 4)).astype(np.float32)
               for _ in range(3))
    got = np.array(attention(q, k, v, causal=causal))
    want = np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact_over_8_shards(causal):
    import jax
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.parallel.mesh import make_mesh, shard_map

    mesh = make_mesh(axes=("sp",))
    n = mesh.shape["sp"]
    assert n == 8
    rng = np.random.default_rng(33)
    T = 8 * n                                    # 8 tokens per device
    q, k, v = (rng.normal(size=(2, T, 2, 4)).astype(np.float32)
               for _ in range(3))

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"))
    got = np.array(ring(q, k, v))
    want = np_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_mha_unit_fwd_bwd():
    from znicz_tpu.attention import GDMultiHeadAttention, MultiHeadAttention

    rng = np.random.default_rng(35)
    x = rng.normal(size=(2, 6, 8)).astype(np.float32)
    mha = MultiHeadAttention(name="mha", heads=2, causal=True)
    mha.input = Array(x)
    mha.initialize(device=None)
    mha.run()
    out = np.array(mha.output.map_read())
    assert out.shape == x.shape
    # oracle
    q = (x @ mha.proj["wq"].mem).reshape(2, 6, 2, 4)
    k = (x @ mha.proj["wk"].mem).reshape(2, 6, 2, 4)
    v = (x @ mha.proj["wv"].mem).reshape(2, 6, 2, 4)
    want = np_attention(q, k, v, causal=True).reshape(2, 6, 8) \
        @ mha.proj["wo"].mem
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    gd = GDMultiHeadAttention(name="mhagd", forward=mha, learning_rate=1.0,
                              need_err_input=True)
    err = rng.normal(size=out.shape).astype(np.float32)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    w0 = mha.proj["wo"].mem.copy()
    gd.run()
    dW = w0 - np.array(mha.proj["wo"].map_read())

    eps = 1e-2
    import jax.numpy as jnp

    def loss(wo):
        params = {kk: jnp.asarray(a.mem) for kk, a in mha.proj.items()}
        params["wo"] = jnp.asarray(wo)
        return float(jnp.sum(jnp.asarray(err) * mha.apply(params,
                                                          jnp.asarray(x))))

    for idx in [(0, 0), (5, 3)]:
        wp = w0.copy(); wp[idx] += eps
        wm = w0.copy(); wm[idx] -= eps
        num = (loss(wp) - loss(wm)) / (2 * eps)
        assert abs(num - dW[idx]) < 5e-2 * max(1.0, abs(num)), idx
    assert np.array(gd.err_input.map_read()).shape == x.shape

def test_attention_causal_offsets():
    """``attention(q_offset, k_offset)``: the global-position causal
    masking sharded blocks rely on.  A query block computed with its
    global offset over the full key set must equal the matching rows of
    full causal attention, and explicit offsets must reproduce a numpy
    oracle masking ``kpos > qpos``."""
    rng = np.random.default_rng(41)
    q, k, v = (rng.normal(size=(2, 8, 2, 4)).astype(np.float32)
               for _ in range(3))
    full = np.array(attention(q, k, v, causal=True))
    blk = np.array(attention(q[:, 4:], k, v, causal=True, q_offset=4))
    np.testing.assert_allclose(blk, full[:, 4:], rtol=1e-6, atol=1e-7)

    # numpy oracle with explicit global positions: queries at 4..7,
    # keys at 2..5 (k_offset=2) — key j visible iff 2+j <= 4+i
    qb, kb, vb = q[:, 4:], k[:, 2:6], v[:, 2:6]
    got = np.array(attention(qb, kb, vb, causal=True,
                             q_offset=4, k_offset=2))
    s = np.einsum("bqhd,bkhd->bhqk", qb, kb) / np.sqrt(4)
    qpos = 4 + np.arange(4)
    kpos = 2 + np.arange(4)
    s = np.where(kpos[None, None, None, :] > qpos[None, None, :, None],
                 -np.inf, s)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, vb)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_attention_k_valid_mask_length_independence():
    """``k_valid`` (ISSUE 15): masked pad keys carry exactly zero
    probability mass, so a row's output over its own L real keys equals
    the unpadded computation — for non-causal attention too, where the
    causal structure gives no free independence."""
    rng = np.random.default_rng(43)
    L, T = 5, 8
    q, k, v = (rng.normal(size=(2, T, 2, 4)).astype(np.float32)
               for _ in range(3))
    # garbage in the padded tail must be invisible behind the mask
    k[:, L:] = 1e3
    v[:, L:] = -1e3
    k_valid = np.zeros((2, T), bool)
    k_valid[:, :L] = True
    got = np.array(attention(q, k, v, k_valid=k_valid))
    want = np.array(attention(q, k[:, :L], v[:, :L]))
    np.testing.assert_allclose(got[:, :L], want[:, :L],
                               rtol=1e-5, atol=1e-6)


def test_gd_mha_grads_match_attention_oracle_and_fd():
    """Gradient-parity oracle for GDMultiHeadAttention (ISSUE 15
    satellite): the unit's applied updates (lr=1, no momentum/decay)
    must equal ``jax.grad`` of a loss built DIRECTLY on
    ``ops.attention.attention`` for every projection, with finite
    differences spot-checking the oracle itself."""
    import jax
    import jax.numpy as jnp

    from znicz_tpu.attention import GDMultiHeadAttention, MultiHeadAttention

    rng = np.random.default_rng(45)
    B, T, H, D, E = 2, 6, 2, 4, 8
    x = rng.normal(size=(B, T, E)).astype(np.float32)
    mha = MultiHeadAttention(name="mha_orc", heads=H, causal=True)
    mha.input = Array(x)
    mha.initialize(device=None)
    mha.run()
    err = rng.normal(size=(B, T, E)).astype(np.float32)

    gd = GDMultiHeadAttention(name="mha_orc_gd", forward=mha,
                              learning_rate=1.0, gradient_moment=0.0,
                              need_err_input=True)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    w0 = {kk: np.array(a.map_read()) for kk, a in mha.proj.items()}
    gd.run()
    applied = {kk: w0[kk] - np.array(a.map_read())
               for kk, a in mha.proj.items()}

    def oracle(params, xx):
        q = (xx @ params["wq"]).reshape(B, T, H, D)
        k = (xx @ params["wk"]).reshape(B, T, H, D)
        v = (xx @ params["wv"]).reshape(B, T, H, D)
        o = attention(q, k, v, causal=True)
        return o.reshape(B, T, H * D) @ params["wo"]

    def loss(params):
        return jnp.sum(jnp.asarray(err) * oracle(params, jnp.asarray(x)))

    grads = jax.grad(loss)({kk: jnp.asarray(w) for kk, w in w0.items()})
    for kk in ("wq", "wk", "wv", "wo"):
        np.testing.assert_allclose(
            applied[kk], np.asarray(grads[kk]), rtol=2e-4, atol=1e-6,
            err_msg=f"GD update for {kk} != jax.grad of the "
                    f"ops.attention oracle")
    # finite differences validate the oracle itself (two entries per
    # matrix class: an input proj and the output proj)
    eps = 1e-2
    for kk, idx in (("wq", (1, 2)), ("wo", (3, 5))):
        wp = {m: w.copy() for m, w in w0.items()}
        wm = {m: w.copy() for m, w in w0.items()}
        wp[kk][idx] += eps
        wm[kk][idx] -= eps
        num = (loss({m: jnp.asarray(w) for m, w in wp.items()})
               - loss({m: jnp.asarray(w) for m, w in wm.items()})) \
            / (2 * eps)
        num = float(num)
        assert abs(num - applied[kk][idx]) < 5e-2 * max(1.0, abs(num)), \
            (kk, idx, num, applied[kk][idx])
    assert np.array(gd.err_input.map_read()).shape == x.shape


def test_seq_parallel_knob_routes_mha_through_ring():
    """``root.common.engine.seq_parallel`` (ISSUE 15): with the knob on,
    MultiHeadAttention.apply runs ring attention over an ("sp",) mesh of
    virtual devices and matches the dense path numerically; a seq length
    the mesh cannot split falls back to the dense core; the knob off is
    the bit-exact single-device path."""
    from znicz_tpu.core.config import root

    from znicz_tpu.attention import MultiHeadAttention

    rng = np.random.default_rng(47)
    x = rng.normal(size=(2, 32, 8)).astype(np.float32)

    def build(name):
        mha = MultiHeadAttention(name=name, heads=2, causal=True)
        mha.input = Array(x)
        mha.initialize(device=None)
        return mha

    base = build("mha_sp_off")
    base.run()
    ref = np.array(base.output.map_read())
    try:
        root.common.engine.seq_parallel = 8
        sp = build("mha_sp_on")
        assert sp._sp_mesh is not None and sp._sp_mesh.size == 8
        for kk, a in base.proj.items():            # identical weights
            sp.proj[kk].mem = np.array(a.map_read())
        sp.run()
        got = np.array(sp.output.map_read())
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)
        # a length the mesh cannot split (serving's short buckets)
        # falls back to the dense core instead of failing
        short = rng.normal(size=(2, 6, 8)).astype(np.float32)
        out = np.array(sp.apply(
            {kk: np.array(a.map_read()) for kk, a in sp.proj.items()},
            short))
        assert out.shape == short.shape
        # a non-divisible TRAINED length is refused readably
        bad = MultiHeadAttention(name="mha_sp_bad", heads=2, causal=True)
        bad.input = Array(rng.normal(size=(2, 30, 8)
                                     ).astype(np.float32))
        with pytest.raises(ValueError, match="seq_parallel"):
            bad.initialize(device=None)
    finally:
        root.common.engine.seq_parallel = 0


def test_sequence_parallel_training_grads_match_and_learn():
    """Long-context training end-to-end: grads flow THROUGH ring attention
    under shard_map over an ('sp',) mesh, match the single-device
    computation exactly, and a few SGD steps reduce the loss."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from znicz_tpu.ops.attention import attention, ring_attention
    from znicz_tpu.parallel.mesh import make_mesh, shard_map

    B, T, H, D, E = 2, 32, 2, 8, 16
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(B, T, E)).astype(np.float32))
    y = jnp.asarray(np.roll(np.asarray(x), -1, axis=1))
    params = {k: jnp.asarray(rng.normal(size=(E, H * D)).astype(np.float32)
                             / np.sqrt(E))
              for k in ("wq", "wk", "wv")}
    params["wo"] = jnp.asarray(
        rng.normal(size=(H * D, E)).astype(np.float32) / np.sqrt(H * D))

    def model(p, x, ring):
        b, t, e = x.shape
        q = (x @ p["wq"]).reshape(b, t, H, D)
        k = (x @ p["wk"]).reshape(b, t, H, D)
        v = (x @ p["wv"]).reshape(b, t, H, D)
        o = (ring_attention(q, k, v, "sp", causal=True) if ring
             else attention(q, k, v, causal=True))
        return o.reshape(b, t, H * D) @ p["wo"]

    mesh = make_mesh((8,), ("sp",))

    def sp_loss(p, x, y):
        # x/y arrive sequence-sharded: (B, T/8, E) per device
        out = model(p, x, ring=True)
        local = jnp.mean(jnp.square(out - y))
        return jax.lax.pmean(local, "sp")

    spec = P(None, "sp", None)
    sharded_loss = shard_map(sp_loss, mesh=mesh, in_specs=(P(), spec, spec),
                             out_specs=P())

    def ref_loss(p, x, y):
        return jnp.mean(jnp.square(model(p, x, ring=False) - y))

    g_sp = jax.jit(jax.grad(sharded_loss))(params, x, y)
    g_ref = jax.jit(jax.grad(ref_loss))(params, x, y)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_sp[k]),
                                   np.asarray(g_ref[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

    # a few sequence-parallel SGD steps actually learn
    @jax.jit
    def step(p, x, y):
        loss, g = jax.value_and_grad(sharded_loss)(p, x, y)
        return {k: p[k] - 0.3 * g[k] for k in p}, loss

    losses = []
    p = params
    for _ in range(30):
        p, loss = step(p, x, y)
        losses.append(float(loss))
    assert losses[-1] < 0.8 * losses[0], losses
    assert all(b <= a + 1e-6 for a, b in zip(losses, losses[1:])), losses
