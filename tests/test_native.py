"""C++ native host runtime: build, bindings, numerics."""

import numpy as np
import pytest

from znicz_tpu import native


def test_native_builds_and_loads():
    assert native.build() is not None, "g++ build failed"
    assert native.available()


def test_xorshift_uniform_normal():
    rng = native.XorShift128P(42)
    u = np.zeros(10000, np.float32)
    rng.fill_uniform(u, -1.0, 1.0)
    assert -1.0 <= u.min() and u.max() < 1.0
    assert abs(u.mean()) < 0.05
    n = np.zeros(10000, np.float32)
    rng.fill_normal(n, 2.0)
    assert abs(n.mean()) < 0.1
    assert abs(n.std() - 2.0) < 0.1


def test_xorshift_deterministic():
    a = native.XorShift128P(7)
    b = native.XorShift128P(7)
    ua = np.zeros(100, np.float32)
    ub = np.zeros(100, np.float32)
    a.fill_uniform(ua, 0, 1)
    b.fill_uniform(ub, 0, 1)
    np.testing.assert_array_equal(ua, ub)
    c = native.XorShift128P(8)
    uc = np.zeros(100, np.float32)
    c.fill_uniform(uc, 0, 1)
    assert not np.array_equal(ua, uc)


def test_native_shuffle_is_permutation():
    rng = native.XorShift128P(3)
    arr = np.arange(1000, dtype=np.int32)
    orig = arr.copy()
    rng.shuffle(arr)
    assert not np.array_equal(arr, orig)
    assert np.array_equal(np.sort(arr), orig)


def test_native_gather_matches_numpy():
    rng = np.random.default_rng(5)
    src = rng.normal(size=(50, 7)).astype(np.float32)
    idx = rng.integers(0, 50, size=20).astype(np.int32)
    got = native.gather_f32(src, idx)
    np.testing.assert_array_equal(got, src[idx])


def test_native_u8_to_f32():
    src = np.arange(256, dtype=np.uint8)
    got = native.u8_to_f32(src)
    np.testing.assert_allclose(got, src.astype(np.float32) / 255.0,
                               rtol=1e-6)