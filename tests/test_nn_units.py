"""Per-op numerics vs pure-numpy oracles + gradient checks (SURVEY.md §4:
the reference's unit-test pattern — numpy backend as ground truth, device
backend within float tolerance; here jax-on-cpu is the device)."""

import numpy as np
import pytest

from znicz_tpu.all2all import (
    All2All,
    All2AllRELU,
    All2AllSigmoid,
    All2AllSoftmax,
    All2AllStrictRELU,
    All2AllTanh,
)
from znicz_tpu.gd import GD_BY_FORWARD
from znicz_tpu.memory import Array
from znicz_tpu.ops import activations


def np_act(name, v):
    if name == "tanh":
        return 1.7159 * np.tanh(0.6666 * v)
    if name == "relu":
        return np.log1p(np.exp(v))
    if name == "strict_relu":
        return np.maximum(v, 0.0)
    if name == "sigmoid":
        return 1.0 / (1.0 + np.exp(-v))
    if name == "softmax":
        e = np.exp(v - v.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)
    return v


CASES = [
    (All2All, "linear"),
    (All2AllTanh, "tanh"),
    (All2AllRELU, "relu"),
    (All2AllStrictRELU, "strict_relu"),
    (All2AllSigmoid, "sigmoid"),
    (All2AllSoftmax, "softmax"),
]


@pytest.mark.parametrize("cls,act", CASES)
def test_all2all_forward_matches_numpy(cls, act):
    rng = np.random.default_rng(7)
    x = rng.normal(size=(8, 12)).astype(np.float32)
    fwd = cls(name=f"fwd_{act}", output_sample_shape=(5,))
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    w = fwd.weights.mem
    b = fwd.bias.mem
    want = np_act(act, x @ w.T + b)
    got = np.array(fwd.output.map_read())
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_weights_transposed_storage():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    fwd = All2All(name="fwd_t", output_sample_shape=(3,),
                  weights_transposed=True)
    fwd.input = Array(x)
    fwd.initialize(device=None)
    assert fwd.weights.shape == (6, 3)
    fwd.run()
    want = x @ fwd.weights.mem + fwd.bias.mem
    np.testing.assert_allclose(np.array(fwd.output.map_read()), want,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("cls,act", [c for c in CASES if c[1] != "softmax"])
def test_gd_matches_finite_differences(cls, act):
    """dW from the GD unit == numeric gradient of L = sum(err_output * y)."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=(5, 7)).astype(np.float32)
    err = rng.normal(size=(5, 4)).astype(np.float32)
    fwd = cls(name=f"fd_{act}", output_sample_shape=(4,))
    fwd.input = Array(x)
    fwd.initialize(device=None)
    w0 = fwd.weights.mem.copy()
    b0 = fwd.bias.mem.copy()
    fwd.run()

    gd_cls = GD_BY_FORWARD[cls.__name__]
    gd = gd_cls(name=f"gdfd_{act}", forward=fwd, learning_rate=1.0,
                gradient_moment=0.0)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    # update was w' = w - 1.0 * dW  =>  dW = w0 - w'
    dW = w0 - np.array(fwd.weights.map_read())
    db = b0 - np.array(fwd.bias.map_read())
    err_input = np.array(gd.err_input.map_read())

    def loss(w, b, xx):
        return float(np.sum(err * np_act(act, xx @ w.T + b)))

    eps = 1e-3
    for idx in [(0, 0), (1, 3), (3, 6)]:
        wp = w0.copy(); wp[idx] += eps
        wm = w0.copy(); wm[idx] -= eps
        num = (loss(wp, b0, x) - loss(wm, b0, x)) / (2 * eps)
        assert abs(num - dW[idx]) < 5e-2 * max(1.0, abs(num)), \
            f"dW{idx}: fd={num} unit={dW[idx]}"
    for j in [0, 2]:
        bp = b0.copy(); bp[j] += eps
        bm = b0.copy(); bm[j] -= eps
        num = (loss(w0, bp, x) - loss(w0, bm, x)) / (2 * eps)
        assert abs(num - db[j]) < 5e-2 * max(1.0, abs(num))
    for idx in [(0, 0), (2, 5)]:
        xp = x.copy(); xp[idx] += eps
        xm = x.copy(); xm[idx] -= eps
        num = (loss(w0, b0, xp) - loss(w0, b0, xm)) / (2 * eps)
        assert abs(num - err_input[idx]) < 5e-2 * max(1.0, abs(num))


def test_gd_momentum_and_decay():
    """Velocity accumulation + L2 decay follow the reference formula."""
    x = np.ones((2, 3), np.float32)
    err = np.ones((2, 2), np.float32)
    fwd = All2All(name="momfwd", output_sample_shape=(2,))
    fwd.input = Array(x)
    fwd.initialize(device=None)
    w0 = fwd.weights.mem.copy()
    gd = GD_BY_FORWARD["All2All"](
        name="momgd", forward=fwd, learning_rate=0.1, gradient_moment=0.5,
        weights_decay=0.01, need_err_input=False)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    fwd.run(); gd.run()
    g1 = err.T @ x + 0.01 * w0           # raw grad + L2
    v1 = -0.1 * g1
    np.testing.assert_allclose(np.array(fwd.weights.map_read()), w0 + v1,
                               rtol=1e-5, atol=1e-6)
    w1 = w0 + v1
    fwd.run(); gd.run()
    g2 = err.T @ x + 0.01 * w1
    v2 = 0.5 * v1 - 0.1 * g2
    np.testing.assert_allclose(np.array(fwd.weights.map_read()), w1 + v2,
                               rtol=1e-5, atol=1e-6)


def test_softmax_gd_is_logit_cotangent():
    """GDSoftmax must bypass the softmax jacobian (err_output already is
    dCE/dlogits when err = probs - onehot)."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=(6, 4)).astype(np.float32)
    labels = rng.integers(0, 3, size=6)
    fwd = All2AllSoftmax(name="smfwd", output_sample_shape=(3,))
    fwd.input = Array(x)
    fwd.initialize(device=None)
    w0 = fwd.weights.mem.copy(); b0 = fwd.bias.mem.copy()
    fwd.run()
    probs = np.array(fwd.output.map_read())
    onehot = np.eye(3, dtype=np.float32)[labels]
    err = (probs - onehot) / 6.0
    gd = GD_BY_FORWARD["All2AllSoftmax"](
        name="smgd", forward=fwd, learning_rate=1.0, need_err_input=False)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    dW = w0 - np.array(fwd.weights.map_read())

    def ce(w):
        logits = x @ w.T + b0
        e = np.exp(logits - logits.max(axis=-1, keepdims=True))
        p = e / e.sum(axis=-1, keepdims=True)
        return -np.mean(np.log(p[np.arange(6), labels]))

    eps = 1e-3
    for idx in [(0, 0), (2, 3)]:
        wp = w0.copy(); wp[idx] += eps
        wm = w0.copy(); wm[idx] -= eps
        num = (ce(wp) - ce(wm)) / (2 * eps)
        assert abs(num - dW[idx]) < 1e-2 * max(1.0, abs(num))


def test_activation_constants():
    """The LeCun tanh constants the reference hard-codes."""
    v = np.array([0.5], np.float32)
    got = np.array(activations.tanh_scaled(v))
    np.testing.assert_allclose(got, 1.7159 * np.tanh(0.6666 * 0.5),
                               rtol=1e-6)
