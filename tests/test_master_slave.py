"""Async ZeroMQ master/slave DP mode (reference parity: localhost
master + slaves, SURVEY.md §4 'Distributed testing')."""

import threading
import time

import numpy as np
import pytest

from znicz_tpu.core.config import root


def _make_workflow(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def test_master_slave_trains(tmp_path):
    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17570"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=60.0)

    # two slaves, each with its own replica (same seed -> same dataset)
    slaves = [Client(_make_workflow(tmp_path / f"s{i}"), endpoint=endpoint,
                     slave_id=f"slave{i}") for i in range(2)]

    errors = []

    def worker(s):
        try:
            s.run()
        except BaseException as e:          # surface thread crashes
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    server.serve()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = master_wf.decision
    assert bool(dec.complete)
    # async mode: updates arrive out of order, so epoch attribution is
    # best-effort (reference semantics) — account by job counts instead
    assert server.jobs_done >= 3 * 6 - len(slaves)   # 3 epochs x 6 batches
    assert server.jobs_by_slave.get("slave0", 0) > 0
    assert server.jobs_by_slave.get("slave1", 0) > 0
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    # training actually converged on the master's aggregated params
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid

def _register(sock, slave_id, workflow):
    """Raw-socket handshake (the Client's own first message)."""
    import pickle

    from znicz_tpu.network_common import handshake_request

    msg = handshake_request(workflow)
    msg["id"] = slave_id
    sock.send(pickle.dumps(msg))
    return pickle.loads(sock.recv())


def test_slave_death_requeues_job_and_training_completes(tmp_path):
    """SURVEY §2.4 elastic membership: a slave that takes a job and dies
    must not lose the job — the master re-queues it after job_timeout and a
    slave that joined mid-run finishes the training (VERDICT r2 missing #1)."""
    import pickle

    import zmq

    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17571"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=1.0)
    server_thread = threading.Thread(target=server.serve, daemon=True)
    server_thread.start()

    # the doomed slave: registers, takes a job, dies without replying
    ctx = zmq.Context.instance()
    doomed = ctx.socket(zmq.REQ)
    doomed.setsockopt(zmq.RCVTIMEO, 10_000)
    doomed.setsockopt(zmq.LINGER, 0)
    doomed.connect(endpoint)
    assert _register(doomed, "doomed", master_wf)["ok"]
    doomed.send(pickle.dumps({"cmd": "job", "id": "doomed"}))
    rep = pickle.loads(doomed.recv())
    assert "job" in rep and "params" in rep
    doomed_jid = rep["job_id"]
    doomed.close(0)                          # died mid-job

    # a healthy slave joins MID-RUN (after the death) and finishes the job
    healthy = Client(_make_workflow(tmp_path / "s"), endpoint=endpoint,
                     slave_id="healthy")
    healthy.run()
    server_thread.join(timeout=60)
    assert not server_thread.is_alive()

    dec = master_wf.decision
    assert bool(dec.complete)
    assert server.jobs_requeued >= 1          # the doomed job came back
    assert doomed_jid not in server._inflight
    assert server.jobs_by_slave.get("healthy", 0) > 0
    assert server.jobs_by_slave.get("doomed", 0) == 0
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid


def test_stale_update_dropped_deterministic(tmp_path):
    """One job, one accepted update: an update for a job that was already
    reaped (slow slave past job_timeout) is rejected and does NOT touch the
    master's weights."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, job_timeout=0.0)   # reap instantly
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "s1"})
    jid = rep["job_id"]
    time.sleep(0.01)
    server._reap_lost_jobs()                      # job re-queued
    assert server.jobs_requeued == 1

    before = {f.name: {k: np.array(a.map_read()) for k, a in
                       f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    poisoned = {name: {k: np.full_like(v, 1e6) for k, v in layer.items()}
                for name, layer in before.items()}
    late = server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                           "deltas": poisoned, "metrics": {"loss": 0.0}})
    assert late == {"ok": False, "stale": True}
    assert server.stale_updates == 1
    for f in master_wf.forwards:
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_array_equal(np.array(a.map_read()),
                                              before[f.name][k])


def test_midrun_joiner_receives_current_weights(tmp_path):
    """A slave registering mid-run gets the master's CURRENT params, not
    the initial ones."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    # simulate training progress: nudge the master's weights
    first = next(f for f in master_wf.forwards if f.has_weights)
    w = first.weights.map_write()
    w += 0.125
    current = np.array(first.weights.map_read())

    assert server._handle({"cmd": "register", "id": "late",
                           **_handshake_fields(master_wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "late"})
    assert "params" in rep
    got = np.asarray(rep["params"][first.name]["weights"])
    np.testing.assert_array_equal(got, current)


def _handshake_fields(workflow):
    from znicz_tpu.network_common import handshake_request

    msg = handshake_request(workflow)
    del msg["cmd"]
    return msg


def test_handshake_version_mismatch_refused(tmp_path):
    from znicz_tpu.network_common import workflow_digest
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    rep = server._handle({"cmd": "register", "id": "old", "version": 999,
                          "workflow_digest": workflow_digest(master_wf)})
    assert rep["ok"] is False and "version mismatch" in rep["error"]
    assert "old" not in server.slaves
    # a compatible peer still registers fine afterwards
    assert server._handle({"cmd": "register", "id": "new",
                           **_handshake_fields(master_wf)})["ok"]


def test_handshake_digest_mismatch_refused_client_side(tmp_path):
    """A slave running a DIFFERENT config raises a clean error instead of
    training against incompatible weights."""
    import zmq

    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17572"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint)

    # master thread: answer exactly one request, then exit (the server's
    # own v3 frame path, minus the serve loop)
    def one_reply():
        import zmq as _zmq

        ctx = _zmq.Context.instance()
        sock = ctx.socket(_zmq.REP)
        sock.bind(endpoint)
        try:
            sock.send_multipart(
                server._reply_frames(sock.recv_multipart()))
        finally:
            sock.close(0)

    t = threading.Thread(target=one_reply, daemon=True)
    t.start()

    slave_wf = _make_workflow(tmp_path / "s")
    client = Client(slave_wf, endpoint=endpoint, slave_id="misconfigured")
    import unittest.mock as mock

    from znicz_tpu import network_common

    # the CLIENT's workflow really differs: narrower hidden layer
    bad = {"cmd": "register", "version": network_common.PROTOCOL_VERSION,
           "workflow_digest": "deadbeefdeadbeef"}
    with mock.patch.object(network_common, "handshake_request",
                           return_value=bad):
        with pytest.raises(RuntimeError, match="digest mismatch"):
            client.run()
    t.join(timeout=10)


def test_workflow_digest_semantics(tmp_path):
    """The digest is the weight-delta contract: identical replicas match
    (even across different host paths / unrelated imported config), and a
    changed trainable graph or hyperparameter mismatches."""
    from znicz_tpu.network_common import workflow_digest

    a = _make_workflow(tmp_path / "a")
    root.common.dirs.snapshots = "/somewhere/else/entirely"   # host-local
    root.unrelated_sample.defaults({"x": 1})    # unrelated imported config
    b = _make_workflow(tmp_path / "b")
    assert workflow_digest(a) == workflow_digest(b)

    # post-initialize mutation of the LIVE lr — what a LearningRateAdjust
    # schedule does every step — must NOT change the digest: a slave
    # re-registering mid-training still matches a fresh replica of the
    # identical graph (ADVICE r3).  The digest hashes the hypers frozen
    # at initialize.
    old_lr = b.gds[0].learning_rate
    b.gds[0].learning_rate = old_lr * 2
    assert workflow_digest(a) == workflow_digest(b)
    b.gds[0].learning_rate = old_lr

    # a genuinely differently-CONFIGURED peer still mismatches
    old_cfg_lr = root.mnist.learning_rate
    try:
        root.mnist.learning_rate = old_cfg_lr * 2
        c = _make_workflow(tmp_path / "c")
        assert workflow_digest(a) != workflow_digest(c)
    finally:
        root.mnist.learning_rate = old_cfg_lr

    # STRUCTURAL change without any weight-shape change must also
    # mismatch: peers then compute different functions (review finding —
    # the first digest only covered weighted layers' shapes/hypers)
    old_wt = b.forwards[0].weights_transposed
    b.forwards[0].weights_transposed = not old_wt
    assert workflow_digest(a) != workflow_digest(b)
    b.forwards[0].weights_transposed = old_wt
    assert workflow_digest(a) == workflow_digest(b)

    w = a.forwards[0].weights
    import numpy as np_

    w.mem = np_.zeros((w.shape[0] + 1, w.shape[1]), np_.float32)
    assert workflow_digest(a) != workflow_digest(b)   # shape mismatch


def test_unregistered_slave_gets_no_jobs_or_updates(tmp_path):
    """The handshake is a gate: job/update from a peer that never passed
    (or failed) register must be refused, not served."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    rep = server._handle({"cmd": "job", "id": "ghost"})
    assert rep["ok"] is False and "not registered" in rep["error"]
    rep = server._handle({"cmd": "update", "id": "ghost", "job_id": 1,
                          "deltas": {}, "metrics": {}})
    assert rep["ok"] is False and "not registered" in rep["error"]
    # a refused register does not grant membership either
    server._handle({"cmd": "register", "id": "old", "version": 0,
                    "config_digest": "x"})
    rep = server._handle({"cmd": "job", "id": "old"})
    assert rep["ok"] is False and "not registered" in rep["error"]


def test_web_status_shows_master_topology(tmp_path):
    """The dashboard exposes the master/slave topology like the
    reference's web status did (SURVEY §2.1 Web status)."""
    import json
    import urllib.request

    from znicz_tpu.server import Server
    from znicz_tpu.web_status import WebStatus

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    server._handle({"cmd": "job", "id": "s1"})

    status = WebStatus(port=0).start()
    try:
        status.register(master_wf)
        status.register_server(server)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        master = snap["master"]
        assert master["endpoint"] == server.endpoint
        assert [s["id"] for s in master["slaves"]] == ["s1"]
        assert master["slaves"][0]["last_seen_s"] >= 0
        assert snap["workflows"][0]["name"] == master_wf.name
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "Master" in page and "s1" in page     # topology on the page
    finally:
        status.stop()


def test_launcher_master_slave_modes(tmp_path):
    """The reference CLI's --master/--slave surface (SURVEY §3.1): the
    launcher serves the workflow as the async master / works as a slave
    instead of training locally."""
    import os
    import subprocess
    import sys

    import znicz_tpu
    from znicz_tpu import launcher

    endpoint = "tcp://127.0.0.1:17574"
    overrides = ["root.mnist.loader.n_train=300",
                 "root.mnist.loader.n_valid=60",
                 "root.mnist.loader.minibatch_size=60",
                 "root.mnist.decision.max_epochs=2",
                 f"root.common.dirs.snapshots={tmp_path}"]

    # mutual exclusion is a clean CLI error
    assert launcher.main(["mnist", "--master", "--slave", endpoint]) == 2

    repo = os.path.dirname(os.path.dirname(znicz_tpu.__file__))
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    slave = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "mnist", *overrides,
         "--slave", endpoint], env=env, cwd=str(tmp_path),
        stderr=subprocess.PIPE, text=True)

    rc = {}

    def master():
        rc["master"] = launcher.main(
            ["mnist", *overrides, "--master", endpoint])

    t = threading.Thread(target=master, daemon=True)
    try:
        t.start()
        slave_rc = slave.wait(timeout=240)
        assert slave_rc == 0, slave.stderr.read()[-3000:]
        t.join(timeout=60)
        assert not t.is_alive()
        assert rc.get("master") == 0
    finally:
        root.common.engine.mode = ""
        if slave.poll() is None:
            slave.kill()


def test_slave_clean_error_when_no_master(tmp_path):
    """A slave pointed at a dead endpoint fails with a clear
    ConnectionError, not a raw zmq.Again traceback."""
    from znicz_tpu.client import Client

    client = Client(_make_workflow(tmp_path / "s"),
                    endpoint="tcp://127.0.0.1:17599")
    with pytest.raises(ConnectionError, match="no master answered"):
        client.run(recv_timeout=0.5)


def test_segment_max_bad_replies_drops_after_requeues(tmp_path):
    """PR-1 hardening, now under test: a malformed segment reply (metrics
    length mismatch) is refused and the job re-queued — but only
    MAX_BAD_REPLIES times, after which the non-tail segment is DROPPED so
    a deterministically-broken slave cannot livelock the run."""
    import numpy as np_

    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, segment_steps=3)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    # walk the epoch to the first SEGMENT job (eval singletons come first)
    rep = server._handle({"cmd": "job", "id": "s1"})
    while "minibatches" not in rep["job"]:
        server._handle({"cmd": "update", "id": "s1",
                        "job_id": rep["job_id"], "deltas": None,
                        "metrics": {"loss": 1.0, "n_err": 0}})
        rep = server._handle({"cmd": "job", "id": "s1"})
    seg_idx = np_.array(rep["job"]["minibatches"][0]["indices"])
    for attempt in range(server.MAX_BAD_REPLIES):
        bad = server._handle({"cmd": "update", "id": "s1",
                              "job_id": rep["job_id"], "deltas": None,
                              "metrics": [{"loss": 1.0}]})   # wrong length
        assert bad["ok"] is False and "metrics length" in bad["error"]
        if attempt < server.MAX_BAD_REPLIES - 1:
            assert server._pending           # refused -> re-queued
            rep = server._handle({"cmd": "job", "id": "s1"})
            np_.testing.assert_array_equal(
                np_.array(rep["job"]["minibatches"][0]["indices"]),
                seg_idx)                     # the SAME segment came back
        else:
            assert not server._pending       # bounded: dropped for good
    assert server.bad_updates == server.MAX_BAD_REPLIES
    # the stream moved on: the next job is not that segment again
    rep = server._handle({"cmd": "job", "id": "s1"})
    job = rep.get("job")
    assert job is not None
    nxt = (job["minibatches"][0]["indices"] if "minibatches" in job
           else job["indices"])
    assert not np_.array_equal(np_.array(nxt), seg_idx)


def test_tail_reissued_when_tail_slave_dies(tmp_path):
    """PR-1 epoch-tail ordering under slave death: while the tail is in
    flight other slaves get _WAIT; when the tail's slave dies the job is
    reaped and the tail RE-ISSUED — the epoch closes instead of hanging."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    master_wf.decision.max_epochs = 1        # one epoch: tail ends the run
    server = Server(master_wf, job_timeout=0.2)
    for sid in ("s1", "s2"):
        assert server._handle({"cmd": "register", "id": sid,
                               **_handshake_fields(master_wf)})["ok"]
    # s1 works the epoch until it holds the TAIL job
    rep = server._handle({"cmd": "job", "id": "s1"})
    while not rep["job"].get("last_minibatch"):
        server._handle({"cmd": "update", "id": "s1",
                        "job_id": rep["job_id"], "deltas": None,
                        "metrics": {"loss": 1.0, "n_err": 0}})
        rep = server._handle({"cmd": "job", "id": "s1"})
    tail_jid = rep["job_id"]
    # the tail is outstanding: everyone else must wait, not overrun the
    # epoch boundary
    assert server._handle({"cmd": "job", "id": "s2"}) == {"wait": True}
    # s1 dies without replying; past job_timeout the tail is reaped and
    # re-issued to s2
    time.sleep(0.3)
    rep = server._handle({"cmd": "job", "id": "s2"})
    assert rep["job"].get("last_minibatch"), rep
    assert rep["job_id"] != tail_jid
    assert server.jobs_requeued >= 1
    up = server._handle({"cmd": "update", "id": "s2",
                         "job_id": rep["job_id"], "deltas": None,
                         "metrics": {"loss": 1.0, "n_err": 0}})
    assert up["ok"] is True
    assert bool(master_wf.decision.complete)     # epoch closed, no hang
    assert server._handle({"cmd": "job", "id": "s2"}) == {"done": True}


def test_fused_slaves_train_to_quality_band(tmp_path):
    """VERDICT r4 item 5: two FUSED slaves (each job = a FusedTrainer
    scan dispatch over a k-minibatch segment) train MNIST through the
    async master to the same quality band as the unit-engine slaves —
    protocol, delta aggregation and decision accounting unchanged."""
    from znicz_tpu.client import FusedClient
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17575"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=60.0,
                    segment_steps=3)

    slaves = [FusedClient(_make_workflow(tmp_path / f"s{i}"),
                          endpoint=endpoint, slave_id=f"fslave{i}")
              for i in range(2)]
    errors = []

    def worker(s):
        try:
            s.run()
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    server.serve()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = master_wf.decision
    assert bool(dec.complete)
    assert server.jobs_by_slave.get("fslave0", 0) > 0
    assert server.jobs_by_slave.get("fslave1", 0) > 0
    # segments really were issued (3 epochs x 5 non-tail TRAIN mbs would
    # be 15 singleton jobs; with segment_steps=3 the TRAIN stream packs
    # into far fewer)
    assert server.jobs_done < 3 * 6 + 3 * 2
    # same quality band as test_master_slave_trains' unit slaves
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
    # confusion flowed through the segment path (first-minibatch carrier)
    conf = dec.epoch_metrics[1].get("confusion")
    assert conf is not None and int(np.sum(conf)) > 0


def test_slave_death_requeues_with_fused_slaves(tmp_path):
    """Elastic membership holds for fused slaves: a dead slave's SEGMENT
    job is re-queued and a mid-run-joining FusedClient finishes the
    training (VERDICT r4 item 5 done-criterion)."""
    import pickle

    import zmq

    from znicz_tpu.client import FusedClient
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17576"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=1.0,
                    segment_steps=3)
    server_thread = threading.Thread(target=server.serve, daemon=True)
    server_thread.start()

    ctx = zmq.Context.instance()
    doomed = ctx.socket(zmq.REQ)
    doomed.setsockopt(zmq.RCVTIMEO, 10_000)
    doomed.setsockopt(zmq.LINGER, 0)
    doomed.connect(endpoint)
    assert _register(doomed, "doomed", master_wf)["ok"]
    doomed.send(pickle.dumps({"cmd": "job", "id": "doomed"}))
    rep = pickle.loads(doomed.recv())
    assert "job" in rep and "params" in rep
    doomed_jid = rep["job_id"]
    doomed.close(0)                          # died mid-segment

    healthy = FusedClient(_make_workflow(tmp_path / "s"),
                          endpoint=endpoint, slave_id="healthy")
    healthy.run()
    server_thread.join(timeout=60)
    assert not server_thread.is_alive()

    dec = master_wf.decision
    assert bool(dec.complete)
    assert server.jobs_requeued >= 1
    assert doomed_jid not in server._inflight
    assert server.jobs_by_slave.get("healthy", 0) > 0
    assert server.jobs_by_slave.get("doomed", 0) == 0
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
