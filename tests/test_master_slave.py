"""Async ZeroMQ master/slave DP mode (reference parity: localhost
master + slaves, SURVEY.md §4 'Distributed testing')."""

import threading

import numpy as np
import pytest

from znicz_tpu.core.config import root


def _make_workflow(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def test_master_slave_trains(tmp_path):
    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17570"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint, job_timeout=60.0)

    # two slaves, each with its own replica (same seed -> same dataset)
    slaves = [Client(_make_workflow(tmp_path / f"s{i}"), endpoint=endpoint,
                     slave_id=f"slave{i}") for i in range(2)]

    errors = []

    def worker(s):
        try:
            s.run()
        except BaseException as e:          # surface thread crashes
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    server.serve()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = master_wf.decision
    assert bool(dec.complete)
    # async mode: updates arrive out of order, so epoch attribution is
    # best-effort (reference semantics) — account by job counts instead
    assert server.jobs_done >= 3 * 6 - len(slaves)   # 3 epochs x 6 batches
    assert server.jobs_by_slave.get("slave0", 0) > 0
    assert server.jobs_by_slave.get("slave1", 0) > 0
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    # training actually converged on the master's aggregated params
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid