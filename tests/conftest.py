"""Test configuration: force an 8-virtual-device CPU platform BEFORE any jax
backend initialization so sharding/collective tests run anywhere
(SURVEY.md §4).  The fragile recipe (env forcing, axon-plugin deregistration,
jax.config re-pin) lives in znicz_tpu/virtdev.py, shared with
__graft_entry__.dryrun_multichip."""

from znicz_tpu.virtdev import provision_cpu_devices

provision_cpu_devices(8)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak tests excluded from tier-1 (-m 'not slow')")


@pytest.fixture(autouse=True)
def _fixed_seed():
    """Every test starts from the same global seed and a clean stream table."""
    from znicz_tpu.core import prng

    prng.reset(1013)
    yield
