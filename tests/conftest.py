"""Test configuration: force an 8-virtual-device CPU platform BEFORE any jax
backend initialization so sharding/collective tests run anywhere
(SURVEY.md §4).  The fragile recipe (env forcing, axon-plugin deregistration,
jax.config re-pin) lives in znicz_tpu/virtdev.py, shared with
__graft_entry__.dryrun_multichip."""

from znicz_tpu.virtdev import provision_cpu_devices

provision_cpu_devices(8)

import time  # noqa: E402

import pytest  # noqa: E402

#: tier-1 time-budget guard (ISSUE 7 satellite): the suite's hard cap is
#: 870s (ROADMAP tier-1 command `timeout -k 10 870`); it has been running
#: ~805-835s — one slow new test from a timeout kill.  Past this SOFT
#: budget the terminal summary shouts; the 10-slowest table below it
#: names where the seconds went so the next PR knows what to trim or
#: `slow`-mark.  Informational only — never fails a run.
SOFT_BUDGET_S = 820.0


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soak tests excluded from tier-1 (-m 'not slow')")
    config._znicz_session_t0 = time.perf_counter()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Ten slowest tests + a soft-budget warning (see SOFT_BUDGET_S)."""
    durations = []
    for reports in terminalreporter.stats.values():
        for rep in reports:
            if getattr(rep, "when", None) == "call":
                durations.append((rep.duration, rep.nodeid))
    if not durations:
        return
    tr = terminalreporter
    wall = time.perf_counter() - getattr(config, "_znicz_session_t0",
                                         time.perf_counter())
    tr.write_sep("-", "tier-1 time budget")
    for dur, nodeid in sorted(durations, reverse=True)[:10]:
        tr.write_line(f"  {dur:7.2f}s  {nodeid}")
    tr.write_line(f"  session wall {wall:.1f}s over {len(durations)} "
                  f"test calls (soft budget {SOFT_BUDGET_S:.0f}s, "
                  f"hard cap 870s)")
    if wall > SOFT_BUDGET_S and len(durations) > 50:
        # len() gate: a single-file run that happens to be long must not
        # shout about the SUITE budget
        tr.write_line(
            f"  WARNING: tier-1 wall time {wall:.1f}s exceeds the "
            f"{SOFT_BUDGET_S:.0f}s soft budget — the 870s hard cap is "
            "close; slow-mark or trim before adding more (ISSUE 7)")


@pytest.fixture(autouse=True)
def _fixed_seed():
    """Every test starts from the same global seed and a clean stream table."""
    from znicz_tpu.core import prng

    prng.reset(1013)
    yield
