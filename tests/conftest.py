"""Test configuration: force an 8-virtual-device CPU platform BEFORE jax
import so sharding/collective tests run anywhere (SURVEY.md §4)."""

import os

# Force (not setdefault): this machine pre-exports JAX_PLATFORMS=axon (remote
# TPU), under which the suite would compile remotely and hang; and
# --xla_force_host_platform_device_count only applies to the cpu platform.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The axon (remote-TPU) PJRT plugin registers itself from sitecustomize.py
# BEFORE this file runs.  Even under JAX_PLATFORMS=cpu, jax initializes every
# *registered* plugin, and the axon tunnel is single-claim: a second process
# blocks forever in make_c_api_client.  Deregister the factory so tests are
# pure-CPU and can run concurrently with TPU work.
try:
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # register() may have already pinned jax_platforms=axon via jax.config
    # (which overrides the env var) — pin it back.
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fixed_seed():
    """Every test starts from the same global seed and a clean stream table."""
    from znicz_tpu.core import prng

    prng._streams.clear()
    prng.seed_all(1013)
    yield
