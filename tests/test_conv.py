"""Conv unit numerics vs a pure-numpy direct convolution oracle + grad check."""

import numpy as np
import pytest

from znicz_tpu.conv import Conv, ConvRELU, ConvStrictRELU, ConvTanh
from znicz_tpu.gd_conv import GD_BY_FORWARD_CONV
from znicz_tpu.memory import Array


def np_conv(x, w, b, sliding, padding):
    """Direct NHWC conv oracle. w: (K, ky, kx, C)."""
    left, top, right, bottom = padding
    sy, sx = sliding
    xb = np.pad(x, ((0, 0), (top, bottom), (left, right), (0, 0)))
    B, H, W, C = xb.shape
    K, ky, kx, _ = w.shape
    oh = (H - ky) // sy + 1
    ow = (W - kx) // sx + 1
    y = np.zeros((B, oh, ow, K), np.float32)
    for oy in range(oh):
        for ox in range(ow):
            patch = xb[:, oy * sy:oy * sy + ky, ox * sx:ox * sx + kx, :]
            y[:, oy, ox, :] = np.tensordot(patch, w, axes=([1, 2, 3],
                                                           [1, 2, 3]))
    return y + b


@pytest.mark.parametrize("sliding,padding", [
    ((1, 1), (0, 0, 0, 0)),
    ((2, 2), (1, 1, 1, 1)),
    ((1, 2), (2, 1, 0, 3)),
])
def test_conv_matches_numpy(sliding, padding):
    rng = np.random.default_rng(21)
    x = rng.normal(size=(2, 8, 9, 3)).astype(np.float32)
    fwd = Conv(name=f"c{sliding}{padding}", n_kernels=4, kx=3, ky=3,
               sliding=sliding, padding=padding)
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    want = np_conv(x, fwd.weights.mem, fwd.bias.mem, sliding, padding)
    got = np.array(fwd.output.map_read())
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv_activations():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 5, 5, 2)).astype(np.float32)
    for cls, act in [(ConvTanh, lambda v: 1.7159 * np.tanh(0.6666 * v)),
                     (ConvRELU, lambda v: np.log1p(np.exp(v))),
                     (ConvStrictRELU, lambda v: np.maximum(v, 0))]:
        fwd = cls(name=f"ca_{cls.__name__}", n_kernels=3, kx=3, ky=3)
        fwd.input = Array(x)
        fwd.initialize(device=None)
        fwd.run()
        lin = np_conv(x, fwd.weights.mem, fwd.bias.mem, (1, 1), (0, 0, 0, 0))
        np.testing.assert_allclose(np.array(fwd.output.map_read()), act(lin),
                                   rtol=1e-4, atol=1e-4)


def test_gd_conv_finite_differences():
    rng = np.random.default_rng(31)
    x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
    fwd = ConvTanh(name="gcf", n_kernels=3, kx=3, ky=3, sliding=(1, 1),
                   padding=(1, 1, 1, 1))
    fwd.input = Array(x)
    fwd.initialize(device=None)
    w0 = fwd.weights.mem.copy()
    b0 = fwd.bias.mem.copy()
    fwd.run()
    err = rng.normal(size=fwd.output.shape).astype(np.float32)
    gd = GD_BY_FORWARD_CONV["ConvTanh"](
        name="gcfgd", forward=fwd, learning_rate=1.0, gradient_moment=0.0)
    gd.err_output = Array(err)
    gd.initialize(device=None)
    gd.run()
    dW = w0 - np.array(fwd.weights.map_read())
    err_input = np.array(gd.err_input.map_read())

    def loss(w, xx):
        lin = np_conv(xx, w, b0, (1, 1), (1, 1, 1, 1))
        return float(np.sum(err * 1.7159 * np.tanh(0.6666 * lin)))

    eps = 1e-3
    for idx in [(0, 0, 0, 0), (2, 1, 2, 1), (1, 2, 0, 1)]:
        wp = w0.copy(); wp[idx] += eps
        wm = w0.copy(); wm[idx] -= eps
        num = (loss(wp, x) - loss(wm, x)) / (2 * eps)
        assert abs(num - dW[idx]) < 5e-2 * max(1.0, abs(num)), idx
    for idx in [(0, 0, 0, 0), (1, 3, 4, 1)]:
        xp = x.copy(); xp[idx] += eps
        xm = x.copy(); xm[idx] -= eps
        num = (loss(w0, xp) - loss(w0, xm)) / (2 * eps)
        assert abs(num - err_input[idx]) < 5e-2 * max(1.0, abs(num)), idx
