"""CI lints, now riding znicz-lint (ISSUE 9): no NEW ad-hoc counter
attributes (ISSUE 5 satellite) and no silently-ignored serving/engine
config knobs (ISSUE 6/7 satellites).

Historical note: these started as three hand-rolled regexes in this
file.  The regexes were line-anchored (missed ``self.x = self.x + 1``)
and blind to aliasing — binding a config subtree to a variable hid
every later ``.get()`` read, so the lint had to REFUSE aliasing itself
(the old ``SERVING_ALIAS`` / ``ENGINE_ALIAS`` patterns).  ISSUE 9
ported all three onto the AST checkers in ``znicz_tpu/analysis/``:
alias-bound reads now RESOLVE (see ``_admission_from_config`` in
serving/frontend.py, which binds the admission subtree to a local),
and the refusals are retired.  The test names survive; each is a thin
wrapper over the corresponding analyzer rule.

The counter ALLOWLIST (attributes that look counter-ish but are STATE,
not metrics — e.g. ``parallel/fused.py steps_done``, the PRNG/step-key
stream position; ``loader/base.py samples_served``, the loader cursor;
the kohonen epoch accumulators) moved WITH its rationale comments to
``znicz_tpu/analysis/counters.py`` so the ``python -m
znicz_tpu.analysis`` CLI and this test share one source of truth;
``test_allowlist_is_the_single_source_of_truth`` below pins the
historical entries so they cannot silently vanish.
"""

import pathlib
import textwrap

from znicz_tpu.analysis import run
from znicz_tpu.analysis.config_knob import (ConfigKnobChecker,
                                            load_declared_tables)
from znicz_tpu.analysis.counters import (ALLOWLIST,
                                         CounterRegistryChecker)
from znicz_tpu.analysis.core import Module

PKG = pathlib.Path(__file__).resolve().parent.parent / "znicz_tpu"


def _check(checker, code, rel="fixture.py"):
    """Run one checker over a fixture snippet."""
    module = Module(pathlib.Path(rel), rel, textwrap.dedent(code))
    return [f.message for f in checker.check(module)]


def _live(rule):
    """Unbaselined findings of one rule over the real package."""
    analysis = run(PKG, rules=[rule])
    assert not analysis.parse_errors, analysis.parse_errors
    return [f.render() for f in analysis.findings]


# -- ad-hoc counter lint (ISSUE 5 satellite) -----------------------------------


def test_no_adhoc_counters_outside_the_registry():
    offenders = _live("counter-registry")
    assert not offenders, (
        "ad-hoc counter increments found — register them in "
        "znicz_tpu/telemetry instead (telemetry.scope(...).counter(...)"
        ".inc()), or allowlist non-metric state with a justification in "
        "znicz_tpu/analysis/counters.py:\n  " + "\n  ".join(offenders))


def test_lint_pattern_catches_the_regression_class():
    """The checker must actually fire on the style it polices — and on
    the ``self.x = self.x + 1`` spelling the old regex never saw."""
    checker = CounterRegistryChecker(allowlist=())
    tp = _check(checker, """
        class S:
            def f(self):
                self.bad_frames += 1
                self.retry_count += n
                self.bad_frames = self.bad_frames + 1   # regex blind spot
                if fast: self.served += 1               # one-liner too
    """)
    assert len(tp) == 4, tp
    tn = _check(checker, """
        class S:
            def f(self):
                self._pos += 1                  # cursor, not metric
                unit.run_count += 1             # not self.
                self.total = other.total + 1    # copy, not increment
    """)
    assert not tn, tn


def test_allowlist_is_the_single_source_of_truth():
    """The historical allowlist entries (with their reasons) moved to
    the checker module; pin them so they cannot silently vanish."""
    for pair in {("parallel/fused.py", "steps_done"),
                 ("loader/base.py", "samples_served"),
                 ("graphics.py", "received"),
                 ("kohonen.py", "_batches"),
                 ("kohonen.py", "total")}:
        assert pair in ALLOWLIST, pair
    # and every allowlisted site still exists in the package — a stale
    # allowlist entry is a hole waiting for a regression to crawl in
    for rel, attr in ALLOWLIST:
        text = (PKG / rel).read_text()
        assert f"self.{attr}" in text, (rel, attr)


# -- serving config-knob lint (ISSUE 6 satellite) ------------------------------


def test_every_serving_config_read_is_declared_in_defaults():
    offenders = _live("config-knob")
    assert not offenders, (
        "config keys read in code but missing from the declaration "
        "tables — an undeclared knob is silently ignored by dotted "
        "overrides; declare it (or fix the typo):\n  "
        + "\n  ".join(offenders))


def test_serving_config_lint_catches_the_regression_class():
    """Undeclared keys fire (literal OR alias-bound), declared keys and
    the dynamic ``.get(variable)`` read stay quiet."""
    checker = ConfigKnobChecker(PKG)
    assert _check(checker, """
        from znicz_tpu.core.config import root
        x = root.common.serving.get("bogus_knob", 1)
    """)
    assert not _check(checker, """
        from znicz_tpu.core.config import root
        x = root.common.serving.get("max_batch", 32)
        y = root.common.serving.admission.get("rate_limit", 0)
    """)
    # the frontend's dynamic read (variable key) contributes no path
    assert not _check(checker, """
        from znicz_tpu.core.config import root
        def _cfg(name):
            return root.common.serving.get(name, DEFAULTS[name])
    """)
    # ALIASING NOW RESOLVES (the old lint refused it outright): a
    # declared read through the alias passes, a typo through it fires
    assert not _check(checker, """
        from znicz_tpu.core.config import root
        def f():
            adm = root.common.serving.admission
            return adm.get("rate_limit", 0)
    """)
    offenders = _check(checker, """
        from znicz_tpu.core.config import root
        def f():
            adm = root.common.serving.admission
            return adm.get("rate_limi", 0)
    """)
    assert offenders and "admission.rate_limi" in offenders[0]
    # what alias resolution CANNOT follow — a subtree escaping the
    # local scope — is still refused, preserving the old guarantee
    assert _check(checker, """
        from znicz_tpu.core.config import root
        def f(g):
            g(root.common.serving.admission)
    """)


# -- engine config-knob lint (ISSUE 7 satellite) -------------------------------


def test_every_engine_config_read_is_declared_in_defaults():
    # same analyzer rule covers both trees; the package-wide run in
    # test_every_serving_config_read_is_declared_in_defaults already
    # proves zero live findings — here we pin the engine table contents
    # the old test asserted, plus the AST-extracted tables matching the
    # imported Python ones (table-extraction rot guard)
    tables = load_declared_tables(PKG)
    from znicz_tpu.core.config import ENGINE_DEFAULTS
    from znicz_tpu.serving.frontend import DEFAULTS

    def flat(d, prefix=""):
        out = set()
        for k, v in d.items():
            out.add(prefix + k)
            if isinstance(v, dict):
                out |= flat(v, prefix + k + ".")
        return out

    # the engine tree nests since ISSUE 18 (mesh.{data,model}), so the
    # AST tables flatten to dotted leaves + subtree keys like serving's
    assert tables["engine"][0] | tables["engine"][1] == flat(ENGINE_DEFAULTS)
    assert tables["serving"][0] | tables["serving"][1] == flat(DEFAULTS)


def test_engine_config_lint_catches_the_regression_class():
    checker = ConfigKnobChecker(PKG)
    assert _check(checker, """
        from znicz_tpu.core.config import root
        x = root.common.engine.get("bogus_knob", 1)
    """)
    # a WRITE of an undeclared key is an offense too (sample configs
    # SET knobs the engine later reads)
    assert _check(checker, """
        from znicz_tpu.core.config import root
        root.common.engine.compute_dtyp = "bf16"
    """)
    assert not _check(checker, """
        from znicz_tpu.core.config import root
        root.common.engine.compute_dtype = "bf16"
        chunk = root.common.engine.get("scan_chunk", 8)
        if x == root.common.engine:
            pass
    """)
    for key in ("compute_dtype", "fused_tail", "async_staging",
                "staging_donate", "xla_latency_hiding", "scan_chunk"):
        assert key in load_declared_tables(PKG)["engine"][0], key
    # engine-tree aliasing resolves now as well
    assert not _check(checker, """
        from znicz_tpu.core.config import root
        def f():
            eng = root.common.engine
            return eng.get("scan_chunk", 8)
    """)
    offenders = _check(checker, """
        from znicz_tpu.core.config import root
        def f():
            eng = root.common.engine
            return eng.get("scan_chunky", 8)
    """)
    assert offenders and "scan_chunky" in offenders[0]
