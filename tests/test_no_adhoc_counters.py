"""CI lint (ISSUE 5 satellite): no NEW ad-hoc counter attributes.

PRs 1-4 each grew bespoke ``self.<name> += 1`` counters (``bad_frames``,
``prefetch_hits``, ``shed``, ...), readable only through whichever panel
their owner happened to wire up.  ISSUE 5 moved them all into the
telemetry registry (znicz_tpu/telemetry/), where every counter is
exported uniformly on ``/metrics``.  This test greps the package for
counter-suffixed bare increments so a future PR cannot regress into
ad-hoc accounting: a new counter must either go through
``telemetry.scope(...).counter(...)`` or be added to the ALLOWLIST
below with a one-line justification.
"""

import pathlib
import re

PKG = pathlib.Path(__file__).resolve().parent.parent / "znicz_tpu"

#: attribute-name suffixes that mean "this is a counter": the union of
#: every counter name the registry migration absorbed, so the regression
#: class is exactly "a counter like the ones we already centralized"
SUFFIXES = ("count", "total", "hits", "frames", "saves", "done",
            "requeued", "reconnects", "replies", "registrations",
            "updates", "rejected", "shed", "oversized", "compiles",
            "received", "served", "batches", "errors", "resends")

PATTERN = re.compile(
    r"^\s*self\.(?P<name>[a-z0-9_]*(?:" + "|".join(SUFFIXES)
    + r"))\s*\+=", re.M)

#: (path-relative-to-znicz_tpu, attribute) pairs that look counter-ish
#: but are STATE, not metrics — each with its reason
ALLOWLIST = {
    # PRNG/step-key stream position: training semantics (jax_key(step)),
    # not accounting; mirrored into the registry as trainer/train_steps
    ("parallel/fused.py", "steps_done"),
    # loader cursor over the resident set (drives epoch bookkeeping)
    ("loader/base.py", "samples_served"),
    # graphics PUB/SUB frame cursor on the plotting side-channel
    ("graphics.py", "received"),
    # kohonen epoch accumulators (averaged into qerror / the winners
    # histogram, then reset)
    ("kohonen.py", "_batches"),
    ("kohonen.py", "total"),
}


def test_no_adhoc_counters_outside_the_registry():
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(PKG).as_posix()
        if rel.startswith("telemetry/"):
            continue                    # the registry implements itself
        text = path.read_text()
        for m in PATTERN.finditer(text):
            name = m.group("name")
            if (rel, name) in ALLOWLIST:
                continue
            line = text.count("\n", 0, m.start()) + 1
            offenders.append(f"{rel}:{line}: self.{name} += ...")
    assert not offenders, (
        "ad-hoc counter increments found — register them in "
        "znicz_tpu/telemetry instead (telemetry.scope(...).counter(...)"
        ".inc()), or allowlist non-metric state with a justification:\n  "
        + "\n  ".join(offenders))


def test_lint_pattern_catches_the_regression_class():
    """The pattern must actually fire on the style it polices."""
    assert PATTERN.search("        self.bad_frames += 1")
    assert PATTERN.search("self.retry_count += n")
    assert not PATTERN.search("self._pos += 1")          # cursor, not metric
    assert not PATTERN.search("unit.run_count += 1")     # not self.
