"""CI lints: no NEW ad-hoc counter attributes (ISSUE 5 satellite), and
no silently-ignored serving config knobs (ISSUE 6 satellite).

PRs 1-4 each grew bespoke ``self.<name> += 1`` counters (``bad_frames``,
``prefetch_hits``, ``shed``, ...), readable only through whichever panel
their owner happened to wire up.  ISSUE 5 moved them all into the
telemetry registry (znicz_tpu/telemetry/), where every counter is
exported uniformly on ``/metrics``.  This test greps the package for
counter-suffixed bare increments so a future PR cannot regress into
ad-hoc accounting: a new counter must either go through
``telemetry.scope(...).counter(...)`` or be added to the ALLOWLIST
below with a one-line justification.
"""

import pathlib
import re

PKG = pathlib.Path(__file__).resolve().parent.parent / "znicz_tpu"

#: attribute-name suffixes that mean "this is a counter": the union of
#: every counter name the registry migration absorbed, so the regression
#: class is exactly "a counter like the ones we already centralized"
SUFFIXES = ("count", "total", "hits", "frames", "saves", "done",
            "requeued", "reconnects", "replies", "registrations",
            "updates", "rejected", "shed", "oversized", "compiles",
            "received", "served", "batches", "errors", "resends")

PATTERN = re.compile(
    r"^\s*self\.(?P<name>[a-z0-9_]*(?:" + "|".join(SUFFIXES)
    + r"))\s*\+=", re.M)

#: (path-relative-to-znicz_tpu, attribute) pairs that look counter-ish
#: but are STATE, not metrics — each with its reason
ALLOWLIST = {
    # PRNG/step-key stream position: training semantics (jax_key(step)),
    # not accounting; mirrored into the registry as trainer/train_steps
    ("parallel/fused.py", "steps_done"),
    # loader cursor over the resident set (drives epoch bookkeeping)
    ("loader/base.py", "samples_served"),
    # graphics PUB/SUB frame cursor on the plotting side-channel
    ("graphics.py", "received"),
    # kohonen epoch accumulators (averaged into qerror / the winners
    # histogram, then reset)
    ("kohonen.py", "_batches"),
    ("kohonen.py", "total"),
}


def test_no_adhoc_counters_outside_the_registry():
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(PKG).as_posix()
        if rel.startswith("telemetry/"):
            continue                    # the registry implements itself
        text = path.read_text()
        for m in PATTERN.finditer(text):
            name = m.group("name")
            if (rel, name) in ALLOWLIST:
                continue
            line = text.count("\n", 0, m.start()) + 1
            offenders.append(f"{rel}:{line}: self.{name} += ...")
    assert not offenders, (
        "ad-hoc counter increments found — register them in "
        "znicz_tpu/telemetry instead (telemetry.scope(...).counter(...)"
        ".inc()), or allowlist non-metric state with a justification:\n  "
        + "\n  ".join(offenders))


def test_lint_pattern_catches_the_regression_class():
    """The pattern must actually fire on the style it polices."""
    assert PATTERN.search("        self.bad_frames += 1")
    assert PATTERN.search("self.retry_count += n")
    assert not PATTERN.search("self._pos += 1")          # cursor, not metric
    assert not PATTERN.search("unit.run_count += 1")     # not self.


# -- serving config-knob lint (ISSUE 6 satellite) ------------------------------
#
# A ``root.common.serving.*`` read whose key is missing from the serving
# DEFAULTS table is config the service will silently ignore under the
# dotted-override CLI (the Config tree autovivifies, so a typo'd or
# undeclared knob reads as its default forever, no error).  Every key
# the package reads must be declared in serving/frontend.py DEFAULTS.

SERVING_CFG = re.compile(
    r"root\.common\.serving\b(?P<chain>(?:\.get\(\s*\"\w+\"|\.\w+)*)")

#: binding a serving config SUBTREE to a variable (``node =
#: root.common.serving.admission``) hides every ``node.get("key")``
#: read from the textual lint above — refuse the aliasing itself and
#: force literal chains at each read site
SERVING_ALIAS = re.compile(
    r"(?<![=!<>])=\s*root\.common\.serving(?:\.[A-Za-z_]\w*)*\s*(?:#.*)?$",
    re.M)

#: extracts the dotted key path from one matched access chain; a bare
#: ``.get(variable`` contributes nothing (the frontend's _cfg helper is
#: keyed off DEFAULTS by construction)
_CHAIN_TOKEN = re.compile(r'\.get\(\s*"(\w+)"|\.(\w+)')


def _chain_key(chain: str):
    tokens = [lit or attr for lit, attr in _CHAIN_TOKEN.findall(chain)
              if (lit or attr) != "get"]
    return ".".join(tokens)


def _flat_defaults():
    from znicz_tpu.serving.frontend import DEFAULTS

    def walk(d, prefix=""):
        out = set()
        for k, v in d.items():
            out.add(prefix + k)
            if isinstance(v, dict):
                out |= walk(v, prefix + k + ".")
        return out

    return walk(DEFAULTS)


def test_every_serving_config_read_is_declared_in_defaults():
    declared = _flat_defaults()
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(PKG).as_posix()
        text = path.read_text()
        for m in SERVING_CFG.finditer(text):
            key = _chain_key(m.group("chain"))
            if key and key not in declared:
                line = text.count("\n", 0, m.start()) + 1
                offenders.append(
                    f"{rel}:{line}: root.common.serving.{key}")
        for m in SERVING_ALIAS.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            offenders.append(
                f"{rel}:{line}: serving config subtree bound to a "
                f"variable — later .get() reads are invisible to this "
                f"lint; spell the literal chain at each read site")
    assert not offenders, (
        "serving config keys read in code but missing from the serving "
        "DEFAULTS table (znicz_tpu/serving/frontend.py) — an undeclared "
        "knob is silently ignored by dotted overrides; declare it (or "
        "fix the typo):\n  " + "\n  ".join(offenders))


# -- engine config-knob lint (ISSUE 7 satellite) -------------------------------
#
# Same regression class as the serving lint above, for the tree where
# this PR's knobs land (``compute_dtype``, ``fused_tail``,
# ``async_staging``, ``staging_donate``, ``xla_latency_hiding``): every
# literal ``root.common.engine.*`` read in the package must be declared
# in core/config.py ENGINE_DEFAULTS, and the subtree must never be bound
# to a variable (which would hide later ``.get()`` reads from the lint).

ENGINE_CFG = re.compile(
    r"root\.common\.engine\b(?P<chain>(?:\.get\(\s*\"\w+\"|\.\w+)*)")

ENGINE_ALIAS = re.compile(
    r"(?<![=!<>])=\s*root\.common\.engine\s*(?:#.*)?$", re.M)


def _engine_defaults():
    from znicz_tpu.core.config import ENGINE_DEFAULTS

    return set(ENGINE_DEFAULTS)


def test_every_engine_config_read_is_declared_in_defaults():
    declared = _engine_defaults()
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(PKG).as_posix()
        text = path.read_text()
        for m in ENGINE_CFG.finditer(text):
            key = _chain_key(m.group("chain"))
            if key and key not in declared:
                line = text.count("\n", 0, m.start()) + 1
                offenders.append(
                    f"{rel}:{line}: root.common.engine.{key}")
        for m in ENGINE_ALIAS.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            offenders.append(
                f"{rel}:{line}: engine config subtree bound to a "
                f"variable — later .get() reads are invisible to this "
                f"lint; spell the literal chain at each read site")
    assert not offenders, (
        "engine config keys read in code but missing from "
        "ENGINE_DEFAULTS (znicz_tpu/core/config.py) — an undeclared "
        "knob is silently ignored by dotted overrides; declare it (or "
        "fix the typo):\n  " + "\n  ".join(offenders))


def test_engine_config_lint_catches_the_regression_class():
    m = ENGINE_CFG.search('root.common.engine.get("bogus_knob", 1)')
    assert _chain_key(m.group("chain")) == "bogus_knob"
    assert "bogus_knob" not in _engine_defaults()
    m = ENGINE_CFG.search('root.common.engine.compute_dtype = "bf16"')
    assert _chain_key(m.group("chain")) == "compute_dtype"
    for key in ("compute_dtype", "fused_tail", "async_staging",
                "staging_donate", "xla_latency_hiding", "scan_chunk"):
        assert key in _engine_defaults(), key
    # aliasing the subtree is itself an offense; literal reads are not
    assert ENGINE_ALIAS.search("eng = root.common.engine")
    assert not ENGINE_ALIAS.search(
        'chunk = root.common.engine.get("scan_chunk", 8)')
    assert not ENGINE_ALIAS.search(
        "if x == root.common.engine:")


def test_serving_config_lint_catches_the_regression_class():
    """The lint must fire on undeclared keys and stay quiet on
    declared ones and on the dynamic _cfg read."""
    m = SERVING_CFG.search('root.common.serving.get("bogus_knob", 1)')
    assert _chain_key(m.group("chain")) == "bogus_knob"
    assert "bogus_knob" not in _flat_defaults()
    m = SERVING_CFG.search(
        'root.common.serving.admission.get("rate_limit", 0)')
    assert _chain_key(m.group("chain")) == "admission.rate_limit"
    assert "admission.rate_limit" in _flat_defaults()
    assert "max_batch" in _flat_defaults()
    # the frontend's dynamic read (variable key) contributes no path
    m = SERVING_CFG.search("root.common.serving.get(name, DEFAULTS[name])")
    assert _chain_key(m.group("chain")) == ""
    # aliasing a subtree is itself an offense; a .get READ is not
    assert SERVING_ALIAS.search("node = root.common.serving.admission")
    assert SERVING_ALIAS.search("x = root.common.serving  # comment")
    assert not SERVING_ALIAS.search(
        'web_port = root.common.serving.get("web_port", None)')
    assert not SERVING_ALIAS.search(
        "if x == root.common.serving.admission:")
