"""Unified telemetry subsystem tests (ISSUE 5): registry/histogram
semantics, Prometheus exposition validity, the trace ring + Chrome
trace JSON, the web_status ``/metrics``/``/trace.json`` endpoints and
lock discipline, trace_id correlation over the wire, and the
three-subsystem one-run proof (training step + wire codec + serving
batch spans in one ring)."""

import json
import re
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from znicz_tpu import telemetry
from znicz_tpu.core.config import root
from znicz_tpu.telemetry.metrics import Histogram, MetricsRegistry
from znicz_tpu.telemetry.trace import NULL_SPAN, TraceRing

# -- histogram ring quantiles (ISSUE 5 satellite) ------------------------------


def test_histogram_empty_and_single_sample():
    h = Histogram("lat_seconds")
    assert h.quantile(0.5) is None and h.count == 0 and h.sum == 0.0
    assert h.quantiles() == {0.5: None, 0.9: None, 0.99: None}
    h.observe(3.25)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == 3.25
    assert h.count == 1 and h.sum == 3.25


def test_histogram_ring_wraparound_quantiles_over_recent_window():
    h = Histogram("lat_seconds", size=8)
    for v in range(8):                       # fill: 0..7
        h.observe(v)
    assert h.quantile(0.0) == 0.0
    for v in range(100, 108):                # wrap: ring now 100..107
        h.observe(v)
    assert h.count == 16                     # lifetime totals survive
    assert h.sum == sum(range(8)) + sum(range(100, 108))
    assert h.quantile(0.0) == 100.0          # the old window is GONE
    assert h.quantile(1.0) == 107.0
    assert 100.0 <= h.quantile(0.5) <= 107.0
    assert h.window().size == 8


def test_registry_thread_safety_under_concurrent_increments():
    """ISSUE 5 satellite: the prefetcher thread and the main client
    thread increment the same registry concurrently (plus a scraper
    rendering mid-flight) without losing a single count — the failure
    mode of the old ``self.x += 1`` attributes under a property."""
    reg = MetricsRegistry()
    sc = reg.scope("soak")
    c = sc.counter("hits")
    h = sc.histogram("lat_seconds", size=128)
    n_threads, per_thread = 4, 20_000
    stop = threading.Event()

    def scrape():
        while not stop.is_set():
            reg.render_prometheus()

    def bump():
        for i in range(per_thread):
            c.inc()
            if i % 97 == 0:
                h.observe(i)

    scraper = threading.Thread(target=scrape, daemon=True)
    scraper.start()
    workers = [threading.Thread(target=bump) for _ in range(n_threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    stop.set()
    scraper.join(5)
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * len(range(0, per_thread, 97))


# -- Prometheus exposition -----------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE = re.compile(
    rf"^{_NAME}(\{{({_NAME}=\"(\\.|[^\"\\])*\"(,{_NAME}=\"(\\.|[^\"\\])*\")*)?\}})?"
    rf" (-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|\+Inf|-Inf|NaN)$")


def _validate_exposition(text: str):
    """Minimal strict check of the text format: every line is a HELP,
    TYPE, or well-formed sample; every sample's family has a TYPE."""
    typed = set()
    samples = 0
    for ln in text.rstrip("\n").split("\n"):
        if ln.startswith("# HELP "):
            continue
        if ln.startswith("# TYPE "):
            _, _, name, kind = ln.split(" ", 3)
            assert kind in ("counter", "gauge", "summary"), ln
            typed.add(name)
            continue
        assert _SAMPLE.match(ln), f"malformed sample line: {ln!r}"
        name = ln.split("{", 1)[0].split(" ", 1)[0]
        base = re.sub(r"_(sum|count)$", "", name)
        assert name in typed or base in typed, f"untyped sample: {ln!r}"
        samples += 1
    return samples


def test_prometheus_exposition_valid_with_edge_values():
    reg = MetricsRegistry()
    sc = reg.scope("edge")
    sc.counter("hits").inc(41)
    sc.gauge("best_metric", fn=lambda: float("inf"))
    sc.gauge("broken", fn=lambda: 1 / 0)     # must render NaN, not raise
    sc.gauge("labeled", 'help with "quotes"', tag='va"l\nue')
    h = sc.histogram("lat_seconds", size=16)
    sc.histogram("never_observed_seconds")   # empty: only _sum/_count
    for v in (1.0, 2.0, 4.0):
        h.observe(v)
    text = reg.render_prometheus()
    assert _validate_exposition(text) >= 8
    assert 'znicz_hits_total{component="edge"} 41' in text
    assert "+Inf" in text and "NaN" in text
    assert 'quantile="0.5"' in text
    assert "znicz_lat_seconds_count" in text


def test_latest_registration_wins_per_label_set():
    """A rebuilt component replaces its predecessor's series instead of
    leaking one per instance; the old object keeps working standalone."""
    reg = MetricsRegistry()
    a = reg.scope("master").counter("jobs_done")
    a.inc(7)
    b = reg.scope("master").counter("jobs_done")
    b.inc(1)
    text = reg.render_prometheus()
    assert text.count("znicz_jobs_done_total{") == 1
    assert 'znicz_jobs_done_total{component="master"} 1' in text
    assert a.value == 7                      # instance object unaffected
    with pytest.raises(ValueError, match="already registered"):
        # same exported name (counter names gain _total), another kind
        reg.scope("master").gauge("jobs_done_total")


# -- trace ring ----------------------------------------------------------------


def test_trace_ring_bounded_and_chrome_json_valid():
    ring = TraceRing(capacity=16)
    for i in range(40):
        with ring.span("cat", f"s{i}", job_id=i):
            pass
    assert len(ring.events()) == 16 and ring.recorded == 40
    chrome = ring.chrome_trace()
    blob = json.dumps(chrome)                # must be JSON-serializable
    back = json.loads(blob)
    assert back["traceEvents"] and back["displayTimeUnit"] == "ms"
    ev = back["traceEvents"][0]
    for key in ("name", "cat", "ph", "ts", "dur", "pid", "tid"):
        assert key in ev
    assert ev["ph"] == "X" and ev["args"]["job_id"] == 24


def test_disabled_ring_is_a_noop():
    ring = TraceRing(capacity=8, enabled=False)
    assert ring.span("c", "n") is NULL_SPAN
    with ring.span("c", "n"):
        pass
    ring.add("c", "n", 0.0, 1.0)
    assert ring.events() == [] and ring.recorded == 0


# -- web_status endpoints + lock discipline ------------------------------------


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        assert r.status == 200
        return r.read()


def test_webstatus_metrics_and_trace_endpoints():
    from znicz_tpu.web_status import WebStatus

    telemetry.scope("endpoint_test").counter("hits").inc(3)
    with telemetry.span("endpoint_test", "probe"):
        pass
    status = WebStatus(port=0).start()
    try:
        text = _get(f"http://127.0.0.1:{status.port}/metrics").decode()
        _validate_exposition(text)
        assert 'znicz_hits_total{component="endpoint_test"} 3' in text
        chrome = json.loads(
            _get(f"http://127.0.0.1:{status.port}/trace.json"))
        assert any(e["cat"] == "endpoint_test"
                   for e in chrome["traceEvents"])
        html = _get(f"http://127.0.0.1:{status.port}/").decode()
        assert "/metrics" in html and "/trace.json" in html
    finally:
        status.stop()


def test_webstatus_device_error_is_structured(monkeypatch):
    """ISSUE 5 satellite: backend enumeration failure degrades into
    ``{"error": ..., "devices": []}`` instead of a silent bare []."""
    import jax

    from znicz_tpu.web_status import WebStatus

    def boom():
        raise RuntimeError("no backend reachable")

    monkeypatch.setattr(jax, "devices", boom)
    status = WebStatus(port=0).start()
    try:
        snap = status.snapshot()
        assert snap["devices"] == {"error": "RuntimeError: no backend "
                                            "reachable", "devices": []}
        body = json.loads(
            _get(f"http://127.0.0.1:{status.port}/status.json"))
        assert body["devices"]["error"].startswith("RuntimeError")
        html = _get(f"http://127.0.0.1:{status.port}/").decode()
        assert "unavailable" in html         # page renders, not a 500
    finally:
        status.stop()


def test_stalled_scraper_never_wedges_the_registry():
    """Lock-discipline regression (ISSUE 5 satellite): a scraper that
    connects and never reads must not leave any registry lock held —
    concurrent increments and a second scrape proceed immediately."""
    from znicz_tpu.web_status import WebStatus

    c = telemetry.scope("stall_test").counter("hits")
    status = WebStatus(port=0).start()
    stalled = socket.create_connection(("127.0.0.1", status.port),
                                       timeout=5)
    try:
        stalled.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        time.sleep(0.1)                      # let the handler run
        t0 = time.perf_counter()
        c.inc(5)                             # must not block
        text = _get(f"http://127.0.0.1:{status.port}/metrics",
                    timeout=10).decode()
        assert time.perf_counter() - t0 < 10
        assert 'znicz_hits_total{component="stall_test"} 5' in text
    finally:
        stalled.close()
        status.stop()


# -- trace_id correlation over the wire (ISSUE 5 satellite) --------------------


def _tiny_mnist(n_train=128, n_valid=32, minibatch=32, max_epochs=2,
                layers=(32, 10)):
    from znicz_tpu.samples import mnist

    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = n_valid
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = minibatch
    root.mnist.decision.max_epochs = max_epochs
    root.mnist.layers = list(layers)
    try:
        wf = mnist.MnistWorkflow()
    finally:
        root.mnist.loader.n_train = 4000
        root.mnist.loader.n_valid = 800
        root.mnist.loader.minibatch_size = 60
        root.mnist.decision.max_epochs = 5
        root.mnist.layers = [100, 10]
    wf.initialize(device=None)
    return wf


def test_master_job_carries_trace_id_and_update_echo_is_spanned():
    from znicz_tpu.network_common import handshake_request
    from znicz_tpu.parallel import wire
    from znicz_tpu.server import Server

    wf = _tiny_mnist()
    srv = Server(wf)

    def rpc(msg):
        frames, _ = wire.encode_message(msg)
        rep, _ = wire.decode_message(
            [bytes(f) for f in srv._reply_frames(frames)])
        return rep

    assert rpc(dict(handshake_request(wf), id="s1"))["ok"]
    job = rpc({"cmd": "job", "id": "s1"})
    assert "job" in job
    # the correlation key: unique per job, prefixed by the master's tag
    assert job["trace_id"].endswith(f"-{job['job_id']}")
    upd = rpc({"cmd": "update", "id": "s1", "job_id": job["job_id"],
               "trace_id": job["trace_id"],
               "metrics": {"loss": 1.0, "n_err": 0}})
    assert upd["ok"]
    spans = [e for e in telemetry.tracer().events()
             if e[0] == "master" and e[1] == "handle:update"
             and e[5] and e[5].get("trace_id") == job["trace_id"]]
    assert spans, "master update span must carry the job's trace_id"
    # an OLD peer that does not echo the optional key still works
    job2 = rpc({"cmd": "job", "id": "s1"})
    upd2 = rpc({"cmd": "update", "id": "s1", "job_id": job2["job_id"],
                "metrics": {"loss": 1.0, "n_err": 0}})
    assert upd2["ok"]
    assert srv.jobs_done == 2


# -- the one-run three-subsystem proof (acceptance criterion) ------------------


def test_training_wire_and_serving_spans_in_one_run_and_metrics_cover():
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.server import Server
    from znicz_tpu.serving import InferenceClient, InferenceServer

    telemetry.tracer().clear()
    telemetry.set_enabled(True)
    wf = _tiny_mnist(n_train=256, minibatch=64)
    trainer = FusedTrainer(wf)
    trainer.run()                            # training-step spans
    Server(wf)                               # registers master counters
    srv = InferenceServer(wf, max_batch=4, max_delay_ms=1.0)
    srv.start()
    cli = InferenceClient(srv.endpoint, timeout=60)
    try:
        x = np.zeros((2,) + tuple(srv.runner.sample_shape), np.float32)
        rep = cli.result(cli.submit(x))      # serving + wire spans
        assert rep["y"].shape[0] == 2
        # the reply echoes this client's trace_id (serving correlation)
        assert rep["trace_id"].startswith(cli._tag)
    finally:
        cli.close()
        srv.stop()
    cats = {e[0] for e in telemetry.tracer().events()}
    assert {"train", "wire", "serving"} <= cats, cats
    chrome = telemetry.chrome_trace()
    json.loads(json.dumps(chrome))           # valid Chrome trace JSON
    assert len(chrome["traceEvents"]) > 10

    # /metrics coverage: every counter the web_status panels surfaced
    # pre-ISSUE-5 now exports uniformly (derived ratios like
    # bytes_per_update/qps are computed from these by consumers)
    text = telemetry.render_prometheus()
    _validate_exposition(text)
    for name, series in [
            # master panel
            ("master", "jobs_done"), ("master", "jobs_requeued"),
            ("master", "stale_updates"), ("master", "bad_updates"),
            ("master", "quarantined_updates"),
            ("master", "reregistrations"), ("master", "resume_saves"),
            ("master", "updates_received"), ("master", "update_bytes_in"),
            ("master", "prefetch_hit"), ("master", "bytes_in"),
            ("master", "bytes_out"), ("master", "bad_frames"),
            # serving panel
            ("serving", "requests_in"), ("serving", "served"),
            ("serving", "rejected"), ("serving", "timed_out"),
            ("serving", "bytes_in"), ("serving", "bytes_out"),
            ("serving", "request_latency_seconds_count"),
            ("batcher", "submitted"), ("batcher", "shed"),
            ("batcher", "oversized"), ("batcher", "batches"),
            ("batcher", "batched_rows"), ("batcher", "padded_rows"),
            ("batcher", "bucket_hits"), ("batcher", "queue_depth"),
            ("model", "compiles"), ("model", "jit_cache_size"),
            # workflow panel
            ("decision", "epoch_number"), ("decision", "best_metric"),
            ("trainer", "train_steps"), ("trainer", "images"),
            ("trainer", "step_seconds_count")]:
        pat = re.compile(rf"^znicz_{series}(_total)?\{{[^}}]*"
                         rf'component="{name}"', re.M)
        assert pat.search(text), f"{name}/{series} missing from /metrics"

    # ISSUE 20: the same pins must survive the FLEET-merged exposition.
    # Merging a member snapshot may only APPEND member-labeled rows
    # under the same families — every local series line survives
    # verbatim and every pinned series still matches.
    from znicz_tpu.telemetry.fleet import (FleetMetricsStore,
                                           registry_snapshot,
                                           render_fleet_prometheus)

    store = FleetMetricsStore()
    store.update("r9@1234", registry_snapshot(telemetry.registry()))
    merged = render_fleet_prometheus(telemetry.registry(), store)
    _validate_exposition(merged)
    merged_lines = set(merged.splitlines())
    for ln in text.splitlines():
        assert ln in merged_lines, \
            f"local series line lost in the fleet merge: {ln}"
    for name, series in [("master", "jobs_done"),
                         ("serving", "served"),
                         ("batcher", "batches"),
                         ("trainer", "train_steps")]:
        hits = [ln for ln in merged.splitlines()
                if ln.startswith(f"znicz_{series}")
                and f'component="{name}"' in ln
                and 'member="r9@1234"' in ln]
        assert hits, \
            f"{name}/{series} has no member row in the fleet merge"


# -- concurrent-scrape de-flake guard (ISSUE 5 satellite) ----------------------


@pytest.mark.slow
def test_scrape_concurrent_with_training_stays_in_band():
    """``/metrics`` + ``/trace.json`` must never hold a lock across a
    socket write — scraping concurrently with a training loop must not
    spike step time beyond the interleaved baseline band.  Protocol is
    the PR-4 de-flake shape: quiet/scraped windows INTERLEAVED (a
    container load spike hits both variants), best-of maxima compared
    under a 2x band, bounded rounds with early exit.  A handler that
    serialized training behind a scraper's socket writes would suppress
    every scraped window by multiples."""
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.web_status import WebStatus

    status = WebStatus(port=0).start()
    base = f"http://127.0.0.1:{status.port}"

    def run_once(scraped):
        wf = _tiny_mnist(n_train=1024, n_valid=128, minibatch=128,
                         max_epochs=3, layers=(128, 10))
        trainer = FusedTrainer(wf)
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                try:
                    _get(f"{base}/metrics")
                    _get(f"{base}/trace.json")
                except Exception:
                    pass

        t = None
        if scraped:
            t = threading.Thread(target=scrape, daemon=True)
            t.start()
        try:
            trainer.run()
        finally:
            stop.set()
            if t is not None:
                t.join(10)
        return trainer.stats["warm_img_per_sec"]

    try:
        run_once(False)                     # compile warm
        run_once(True)
        MAX_ROUNDS = 4
        quiet = scraped = 0.0
        for _ in range(MAX_ROUNDS):
            quiet = max(quiet, run_once(False))
            scraped = max(scraped, run_once(True))
            if scraped >= 0.5 * quiet:
                break
        assert scraped >= 0.5 * quiet, (scraped, quiet)
    finally:
        status.stop()
