"""Multi-host FusedTrainer (VERDICT r3 item 4; SURVEY.md §5 comm backend):
TWO OS processes x 4 virtual CPU devices each bring up jax.distributed,
build ONE global {data:8} mesh, and run the REAL FusedTrainer.run() loop —
loader state machine, decision, scans — for two epochs.  Both processes
drive identical host state (same seeds); the global psum crosses the
process (DCN) boundary every step.  Final losses and weights must match
the single-process 8-device run (tests/test_fused.py's oracle property)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import json
    import sys

    from znicz_tpu.virtdev import provision_cpu_devices

    # verify=False: counting devices would initialize the backend, which
    # must not happen before jax.distributed.initialize
    provision_cpu_devices(4, verify=False)
    from znicz_tpu.parallel.mesh import distributed_init, make_mesh

    pid, n, port, snapdir = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3], sys.argv[4])
    distributed_init(coordinator=f"127.0.0.1:{port}",
                     num_processes=n, process_id=pid)
    import numpy as np

    import jax

    assert jax.process_count() == n
    assert len(jax.devices()) == 4 * n          # the global device set
    assert len(jax.local_devices()) == 4

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.common.dirs.snapshots = snapdir
    # config mirrors tests/test_fused.fresh_mnist (the oracle build)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 2
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    mesh = make_mesh(axes=("data",))            # all 8 GLOBAL devices
    assert mesh.shape["data"] == 4 * n
    trainer = FusedTrainer(wf, mesh=mesh)
    trainer.run()
    weights = {f.name: np.asarray(f.weights.map_read()).tolist()
               for f in wf.forwards}
    print("RESULT " + json.dumps({"pid": pid, "losses": losses,
                                  "weights_sum": {
                                      k: float(np.sum(v))
                                      for k, v in weights.items()}}),
          flush=True)
    np.savez(f"{snapdir}/weights_{pid}.npz",
             **{k: np.asarray(v, np.float32) for k, v in weights.items()})
""")


def test_two_process_fused_training_matches_single_process(tmp_path):
    # in-process oracle: the same workflow on this process's 8 virtual
    # devices (the property test_fused.py already pins to single-device)
    from tests.test_fused import fresh_mnist, run_fused
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.mesh import make_mesh

    root.common.dirs.snapshots = str(tmp_path)
    oracle_losses, oracle_weights = run_fused(
        fresh_mnist(), mesh=make_mesh(axes=("data",)))

    worker = tmp_path / "mh_worker.py"
    worker.write_text(WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 2
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the pytest parent pins 8 virtual devices via XLA_FLAGS (conftest);
    # workers must provision their OWN 4-device view
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(n), str(port),
         str(tmp_path)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in range(n)]
    results = {}
    try:
        for pid, proc in enumerate(procs):
            stdout, stderr = proc.communicate(timeout=420)
            assert proc.returncode == 0, (pid, stderr[-3000:])
            line = [ln for ln in stdout.splitlines()
                    if ln.startswith("RESULT ")][-1]
            results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

    # both processes observed identical trajectories (replicated metrics)
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    # and they match the single-process 8-device oracle
    np.testing.assert_allclose(results[0]["losses"], oracle_losses,
                               rtol=1e-4)
    for pid in range(n):
        with np.load(tmp_path / f"weights_{pid}.npz") as f:
            for name, w in oracle_weights.items():
                np.testing.assert_allclose(
                    f[name], w, rtol=2e-3, atol=2e-5,
                    err_msg=f"proc {pid} {name}")


DEEP_WORKER = textwrap.dedent("""\
    import json
    import sys

    from znicz_tpu.virtdev import provision_cpu_devices

    provision_cpu_devices(4, verify=False)
    from znicz_tpu.parallel.mesh import distributed_init, make_mesh

    pid, n, port, snapdir = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3], sys.argv[4])
    distributed_init(coordinator=f"127.0.0.1:{port}",
                     num_processes=n, process_id=pid)
    import numpy as np

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.common.dirs.snapshots = snapdir
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 4
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf, mesh=make_mesh(axes=("data",)))
    trainer.pipeline_depth = 3
    assert trainer._deep_eligible()     # active snapshotter, async-served
    trainer.run()
    snap_written = int(wf.snapshotter.async_saves_written)
    print("RESULT " + json.dumps({
        "pid": pid, "losses": losses, "snap_written": snap_written,
        "weights_sum": {f.name: float(np.sum(f.weights.map_read()))
                        for f in wf.forwards}}), flush=True)
""")


def test_two_process_deep_pipeline_matches_single_process(tmp_path):
    """The DEEP (whole-epoch, metrics-deferred) pipeline in a 2-process
    global mesh — with the snapshotter ACTIVE through the async writer:
    trajectories match the single-process deep run, and process 0 wrote
    checkpoints."""
    from tests.test_fused import fresh_mnist
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.parallel.mesh import make_mesh

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist(max_epochs=4)
    oracle_losses = []
    wf.decision.on_epoch_end.append(
        lambda d: oracle_losses.append(d.epoch_metrics[2]["loss"]))
    tr = FusedTrainer(wf, mesh=make_mesh(axes=("data",)))
    tr.pipeline_depth = 3
    tr.run()

    worker = tmp_path / "mh_deep_worker.py"
    worker.write_text(DEEP_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 2
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(n), str(port),
         str(tmp_path)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in range(n)]
    results = {}
    try:
        for pid, proc in enumerate(procs):
            stdout, stderr = proc.communicate(timeout=420)
            assert proc.returncode == 0, (pid, stderr[-3000:])
            line = [ln for ln in stdout.splitlines()
                    if ln.startswith("RESULT ")][-1]
            results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], oracle_losses,
                               rtol=1e-4)
    # only process 0 writes host-format files; both report their counter
    assert results[0]["snap_written"] > 0
    assert results[1]["snap_written"] == 0
    wsum = {f.name: float(np.sum(f.weights.map_read()))
            for f in wf.forwards}
    for pid in range(n):
        for name, s in wsum.items():
            np.testing.assert_allclose(
                results[pid]["weights_sum"][name], s, rtol=1e-3,
                err_msg=f"proc {pid} {name}")
