"""Evaluator numerics vs numpy oracles."""

import numpy as np

from znicz_tpu.evaluator import EvaluatorMSE, EvaluatorSoftmax
from znicz_tpu.memory import Array


def softmax_fixture(n=6, k=4, valid=5, seed=3):
    rng = np.random.default_rng(seed)
    logits = rng.normal(size=(n, k)).astype(np.float32)
    e = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = (e / e.sum(axis=-1, keepdims=True)).astype(np.float32)
    labels = rng.integers(0, k, size=n).astype(np.int32)
    return probs, labels, valid


def test_evaluator_softmax_metrics():
    probs, labels, valid = softmax_fixture()
    ev = EvaluatorSoftmax(name="ev", n_classes=4)
    ev.output = Array(probs)
    ev.labels = Array(labels)
    ev.batch_size = valid
    ev.initialize(device=None)
    ev.run()

    onehot = np.eye(4, dtype=np.float32)[labels]
    mask = (np.arange(6) < valid).astype(np.float32)[:, None]
    want_err = (probs - onehot) * mask / valid
    np.testing.assert_allclose(np.array(ev.err_output.map_read()), want_err,
                               rtol=1e-5, atol=1e-6)

    pred = probs.argmax(-1)
    want_nerr = int(((pred != labels) & (np.arange(6) < valid)).sum())
    assert ev.n_err == want_nerr

    want_loss = float(-np.log(probs[np.arange(6), labels])[:valid].sum()
                      / valid)
    assert abs(ev.loss - want_loss) < 1e-5

    conf = np.array(ev.confusion_matrix.map_read())
    assert conf.sum() == valid
    for i in range(valid):
        assert conf[pred[i], labels[i]] >= 1


def test_evaluator_softmax_padded_rows_ignored():
    probs, labels, _ = softmax_fixture()
    ev = EvaluatorSoftmax(name="ev2", n_classes=4)
    ev.output = Array(probs)
    ev.labels = Array(labels)
    ev.batch_size = 3
    ev.initialize(device=None)
    ev.run()
    err = np.array(ev.err_output.map_read())
    assert np.all(err[3:] == 0)


def test_evaluator_mse():
    rng = np.random.default_rng(9)
    y = rng.normal(size=(5, 7)).astype(np.float32)
    t = rng.normal(size=(5, 7)).astype(np.float32)
    ev = EvaluatorMSE(name="evm")
    ev.output = Array(y)
    ev.target = Array(t)
    ev.batch_size = 4
    ev.initialize(device=None)
    ev.run()
    mask = (np.arange(5) < 4).astype(np.float32)[:, None]
    want_err = (y - t) * mask / 4
    np.testing.assert_allclose(np.array(ev.err_output.map_read()), want_err,
                               rtol=1e-5, atol=1e-6)
    want_se = np.sum(np.square((y - t) * mask), axis=-1)
    np.testing.assert_allclose(np.array(ev.mse.map_read()), want_se,
                               rtol=1e-5, atol=1e-6)
    assert abs(ev.loss - 0.5 * want_se.sum() / 4) < 1e-5
