"""Activation units, dropout mask-reuse, LRN, Cutter."""

import numpy as np

from znicz_tpu.activation import (
    BackwardTanh,
    ForwardMul,
    ForwardSinCos,
    ForwardTanh,
    ForwardTanhLog,
)
from znicz_tpu.cutter import Cutter, GDCutter
from znicz_tpu.dropout import DropoutBackward, DropoutForward
from znicz_tpu.lrn import LRNormalizerBackward, LRNormalizerForward
from znicz_tpu.memory import Array


def test_activation_tanh_fwd_bwd():
    rng = np.random.default_rng(12)
    x = rng.normal(size=(4, 6)).astype(np.float32)
    fwd = ForwardTanh(name="at")
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    want = 1.7159 * np.tanh(0.6666 * x)
    np.testing.assert_allclose(np.array(fwd.output.map_read()), want,
                               rtol=1e-5)
    err = rng.normal(size=x.shape).astype(np.float32)
    bwd = BackwardTanh(name="atb", forward=fwd)
    bwd.err_output = Array(err)
    bwd.initialize(device=None)
    bwd.run()
    deriv = 1.7159 * 0.6666 * (1 - np.tanh(0.6666 * x) ** 2)
    np.testing.assert_allclose(np.array(bwd.err_input.map_read()),
                               err * deriv, rtol=1e-4, atol=1e-5)


def test_sincos_alternates():
    x = np.linspace(-1, 1, 8).astype(np.float32).reshape(2, 4)
    fwd = ForwardSinCos(name="sc")
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    got = np.array(fwd.output.map_read()).reshape(-1)
    flat = x.reshape(-1)
    for i in range(8):
        want = np.sin(flat[i]) if i % 2 == 0 else np.cos(flat[i])
        assert abs(got[i] - want) < 1e-6


def test_tanhlog_tail():
    x = np.array([[0.5, 20.0, -20.0]], np.float32)
    fwd = ForwardTanhLog(name="tl")
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    got = np.array(fwd.output.map_read())[0]
    assert abs(got[0] - 1.7159 * np.tanh(0.6666 * 0.5)) < 1e-5
    assert abs(got[1] - (1.7159 + np.log(11.0))) < 1e-4
    assert abs(got[2] + (1.7159 + np.log(11.0))) < 1e-4


def test_mul_unit():
    a = np.full((2, 3), 2.0, np.float32)
    b = np.full((2, 3), 4.0, np.float32)
    fwd = ForwardMul(name="mul")
    fwd.input = Array(a)
    fwd.x2 = Array(b)
    fwd.initialize(device=None)
    fwd.run()
    np.testing.assert_allclose(np.array(fwd.output.map_read()), a * b)


def test_dropout_train_mask_reuse_eval_identity():
    rng = np.random.default_rng(13)
    x = rng.normal(size=(20, 30)).astype(np.float32)
    fwd = DropoutForward(name="do", dropout_ratio=0.4)
    fwd.input = Array(x)
    fwd.minibatch_class = 2                    # TRAIN
    fwd.initialize(device=None)
    fwd.run()
    y = np.array(fwd.output.map_read())
    m = np.array(fwd.mask.map_read())
    np.testing.assert_allclose(y, x * m, rtol=1e-6)
    keep = (m > 0).mean()
    assert 0.4 < keep < 0.8                    # ~0.6 keep-prob
    np.testing.assert_allclose(m[m > 0], 1.0 / 0.6, rtol=1e-5)

    err = rng.normal(size=x.shape).astype(np.float32)
    bwd = DropoutBackward(name="dob", forward=fwd)
    bwd.err_output = Array(err)
    bwd.initialize(device=None)
    bwd.run()
    np.testing.assert_allclose(np.array(bwd.err_input.map_read()), err * m,
                               rtol=1e-6)

    fwd.minibatch_class = 1                    # VALID: identity
    fwd.run()
    np.testing.assert_allclose(np.array(fwd.output.map_read()), x)
    bwd.run()
    np.testing.assert_allclose(np.array(bwd.err_input.map_read()), err)


def test_lrn_matches_numpy():
    rng = np.random.default_rng(14)
    x = rng.normal(size=(2, 3, 3, 8)).astype(np.float32)
    fwd = LRNormalizerForward(name="lrn")
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    alpha, beta, n, k = 1e-4, 0.75, 5, 2.0
    want = np.zeros_like(x)
    C = 8
    for c in range(C):
        lo, hi = max(0, c - n // 2), min(C, c + n // 2 + 1)
        s = np.sum(np.square(x[..., lo:hi]), axis=-1)
        want[..., c] = x[..., c] / (k + alpha * s) ** beta
    np.testing.assert_allclose(np.array(fwd.output.map_read()), want,
                               rtol=1e-5, atol=1e-6)
    # backward: finite-difference spot check
    err = rng.normal(size=x.shape).astype(np.float32)
    bwd = LRNormalizerBackward(name="lrnb", forward=fwd)
    bwd.err_output = Array(err)
    bwd.initialize(device=None)
    bwd.run()
    got = np.array(bwd.err_input.map_read())

    def loss(xx):
        out = np.zeros_like(xx)
        for c in range(C):
            lo, hi = max(0, c - n // 2), min(C, c + n // 2 + 1)
            s = np.sum(np.square(xx[..., lo:hi]), axis=-1)
            out[..., c] = xx[..., c] / (k + alpha * s) ** beta
        return float(np.sum(err * out))

    eps = 1e-2
    for idx in [(0, 0, 0, 0), (1, 2, 1, 5)]:
        xp = x.copy(); xp[idx] += eps
        xm = x.copy(); xm[idx] -= eps
        num = (loss(xp) - loss(xm)) / (2 * eps)
        assert abs(num - got[idx]) < 5e-3 * max(1.0, abs(num)), idx


def test_cutter_fwd_bwd():
    x = np.arange(2 * 5 * 6 * 1, dtype=np.float32).reshape(2, 5, 6, 1)
    fwd = Cutter(name="cut", padding=(1, 2, 1, 1))   # l, t, r, b
    fwd.input = Array(x)
    fwd.initialize(device=None)
    fwd.run()
    got = np.array(fwd.output.map_read())
    np.testing.assert_allclose(got, x[:, 2:4, 1:5, :])
    err = np.ones_like(got)
    bwd = GDCutter(name="cutb", forward=fwd)
    bwd.err_output = Array(err)
    bwd.initialize(device=None)
    bwd.run()
    back = np.array(bwd.err_input.map_read())
    assert back.shape == x.shape
    assert back[:, 2:4, 1:5, :].sum() == err.sum()
    assert back.sum() == err.sum()


def test_lrn_even_window_and_custom_vjp_parity():
    """Even n (asymmetric window) must keep working through plain autodiff
    (r4 review regression: reduce_window winsum broke n=4), and the odd-n
    closed-form custom vjp must match autodiff exactly."""
    import jax
    import jax.numpy as jnp

    from znicz_tpu.lrn import LRNormalizerForward

    x = np.random.default_rng(0).normal(
        0, 1, (2, 5, 5, 16)).astype(np.float32)

    u4 = LRNormalizerForward(None, name="lrn4", n=4)
    y4 = u4.apply({}, jnp.asarray(x))
    g4 = jax.grad(lambda t: jnp.sum(jnp.sin(u4.apply({}, t))))(
        jnp.asarray(x))
    assert y4.shape == x.shape
    assert np.isfinite(np.asarray(g4)).all()

    u5 = LRNormalizerForward(None, name="lrn5", n=5)

    def autodiff_ref(t):
        padded = jnp.pad(jnp.square(t), [(0, 0)] * 3 + [(2, 2)])
        acc = sum(padded[..., j:j + t.shape[-1]] for j in range(5))
        return t / jnp.power(2.0 + 1e-4 * acc, 0.75)

    g5 = jax.grad(lambda t: jnp.sum(jnp.sin(u5.apply({}, t))))(
        jnp.asarray(x))
    gr = jax.grad(lambda t: jnp.sum(jnp.sin(autodiff_ref(t))))(
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g5), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)
