"""AOT executable cache + zero-cold-start boot (ISSUE 17): the
content-addressed on-disk cache unit (store/load roundtrip, version
divergence, tamper/corruption refusal), the scoring-family cold→warm
roundtrip with bit-exact parity and the strict warm proof, the
generation-family roundtrip, swap-on-a-warm-boot staying compile-free,
and the e2e server boot gating /readyz on the proof.

Everything here runs against real jax executables —
``serialize_executable`` roundtrips are the subject under test, so
there is nothing to fake.  The whole module is skipped on jax builds
without serialization support (the cache degrades to compile-every-
boot there by design)."""

import os
import pickle

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root

aot_cache = pytest.importorskip("znicz_tpu.serving.aot_cache")
if not aot_cache.available():           # pragma: no cover - jax-version dep
    pytest.skip("this jax build cannot serialize executables",
                allow_module_level=True)

VOCAB = 32


def _tiny_mnist_wf(n_train=120):
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = n_train
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def _charlm_wf(seq_len=32):
    from znicz_tpu.samples.charlm import CharLMWorkflow

    prng.reset(1013)
    root.charlm.loader.update({"n_train": 64, "n_valid": 16, "n_test": 0,
                               "seq_len": seq_len, "minibatch_size": 16})
    root.charlm.model.update({"vocab": VOCAB, "embed": 32, "heads": 2,
                              "ffn": 64})
    wf = CharLMWorkflow()
    wf.initialize(device=None)
    return wf


def _warm_runner(tmp_path, ladder):
    """A fresh tiny-mnist runner with the cache armed, warmed over
    ``ladder``."""
    from znicz_tpu.serving import ModelRunner

    runner = ModelRunner(_tiny_mnist_wf())
    assert runner.enable_aot_cache(str(tmp_path))
    runner.warmup(ladder)
    return runner


# -- cache unit ----------------------------------------------------------------


def test_cache_unit_roundtrip_version_divergence_and_refusals(tmp_path):
    """The ExecutableCache alone, over a toy jitted function: a stored
    entry loads back callable and bit-identical; a family-key change
    (an XLA/jax upgrade, a mesh change...) is a CLEAN miss — the
    filename itself diverges, no refusal; a tampered or truncated file
    is REFUSED (counted, logged) and never returned."""
    import jax

    fam = {"toy": 1, "jax": "a"}
    cache = aot_cache.ExecutableCache(str(tmp_path), fam)
    x = np.arange(4, dtype=np.float32)
    jitted = jax.jit(lambda v: v * 2.0 + 1.0)
    compiled = jitted.lower(x).compile()
    entry = {"kind": "toy", "shape": [4]}
    assert cache.load(entry) is None          # absent: silent miss
    assert cache.store(entry, compiled)
    fn = cache.load(entry)
    assert fn is not None
    np.testing.assert_array_equal(np.asarray(fn(x)),
                                  np.asarray(compiled(x)))
    assert cache.counts["refusals"] == 0

    # version divergence: same directory, different family digest
    bumped = aot_cache.ExecutableCache(str(tmp_path),
                                       {**fam, "jax": "b"})
    assert bumped.load(entry) is None
    assert bumped.counts["refusals"] == 0     # clean miss, not refusal

    # a tampered key inside an otherwise valid pickle is refused
    path = cache._path(entry)
    with open(path, "rb") as f:
        blob = pickle.load(f)
    blob["key"]["entry"] = {"kind": "evil"}
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    assert cache.load(entry) is None
    assert cache.counts["refusals"] == 1

    # a truncated/garbage file is refused, not crashed on
    with open(path, "wb") as f:
        f.write(b"not a pickle")
    assert cache.load(entry) is None
    assert cache.counts["refusals"] == 2

    # ... and a fresh store overwrites the refused entry for good
    assert cache.store(entry, compiled)
    assert cache.load(entry) is not None
    assert cache.stats()["stores"] == 2


def test_family_key_is_structural_not_weights(tmp_path):
    """Two runners over the SAME architecture but different weights
    share a family digest (a retrained canary keeps hitting); changing
    the architecture diverges it."""
    from znicz_tpu.serving import ModelRunner

    a = aot_cache.family_key(ModelRunner(_tiny_mnist_wf()))
    b = aot_cache.family_key(ModelRunner(_tiny_mnist_wf(n_train=180)))
    assert a == b
    c = aot_cache.family_key(ModelRunner(_charlm_wf()))
    assert a != c
    # the key pins the toolchain: an XLA upgrade invalidates everything
    for field in ("jax", "jaxlib", "backend", "units", "sample_shape",
                  "dtype", "mesh", "donate"):
        assert field in a


# -- scoring family cold -> warm ----------------------------------------------


def test_scoring_cold_then_warm_roundtrip(tmp_path):
    """The tentpole contract on the scoring family: a cold boot
    compiles + stores every rung, a fresh runner over the same
    directory LOADS the whole family (zero compiles), answers are
    bit-exact, traffic over mixed sizes never recompiles, and the
    strict warm proof holds on both sides."""
    from znicz_tpu.serving import BucketLadder, ModelRunner

    ladder = BucketLadder(8)
    n = len(ladder.rungs)
    cold = _warm_runner(tmp_path, ladder)
    assert cold.compiles == n
    assert cold._warm == {"hits": 0, "misses": n}
    assert cold.warm_source == "compiled"
    assert cold._aot_cache.counts["stores"] == n
    proof = cold.warm_proof(n)
    # the explicit lower().compile() path never touches jax's implicit
    # jit cache — the strictness lever the proof rides
    assert proof["ok"] and proof["mode"] == "aot"
    assert proof["jit_cache_size"] == 0
    assert len(os.listdir(tmp_path)) == n

    rng = np.random.default_rng(7)
    xs = [rng.normal(0, 1, (b, 784)).astype(np.float32)
          for b in ladder.rungs]
    refs = [cold.infer(x) for x in xs]

    warm = ModelRunner(_tiny_mnist_wf())
    assert warm.enable_aot_cache(str(tmp_path))
    # warmup returns the compile count — ZERO on a cache-warm boot
    assert warm.warmup(ladder) == 0
    assert warm.compiles == 0                  # the whole point
    assert warm._warm == {"hits": n, "misses": 0}
    assert warm.warm_source == "cache_hit"
    proof = warm.warm_proof(n)
    assert proof["ok"] and proof["cache_hits"] == n
    assert proof["compiles"] == 0 and proof["jit_cache_size"] == 0
    # bit-exact: the deserialized executable IS the compiled one
    for x, ref in zip(xs, refs):
        np.testing.assert_array_equal(warm.infer(x), ref)
    # a mixed traffic stream stays compile-free post-load
    for rows in (1, 3, 7, 8, 2, 5, 4, 6):
        warm.infer(np.zeros((ladder.bucket_for(rows), 784), np.float32))
    assert warm.compiles == 0
    assert warm.jit_cache_size() == 0


def test_corrupt_entry_refused_recompiled_and_healed(tmp_path):
    """One corrupt file in an otherwise warm cache: the boot refuses it
    readably, recompiles JUST that entry, re-stores it, and reports
    ``mixed`` — the next boot is fully warm again."""
    from znicz_tpu.serving import BucketLadder, ModelRunner

    ladder = BucketLadder(8)
    n = len(ladder.rungs)
    _warm_runner(tmp_path, ladder)
    victim = sorted(os.listdir(tmp_path))[0]
    with open(os.path.join(tmp_path, victim), "wb") as f:
        f.write(b"\x80corrupt")

    mixed = ModelRunner(_tiny_mnist_wf())
    assert mixed.enable_aot_cache(str(tmp_path))
    mixed.warmup(ladder)
    assert mixed._warm == {"hits": n - 1, "misses": 1}
    assert mixed.compiles == 1
    assert mixed.warm_source == "mixed"
    counts = mixed._aot_cache.counts
    assert counts["refusals"] == 1 and counts["stores"] == 1
    assert mixed.warm_proof(n)["ok"]           # family complete either way

    healed = ModelRunner(_tiny_mnist_wf())
    assert healed.enable_aot_cache(str(tmp_path))
    healed.warmup(ladder)
    assert healed._warm == {"hits": n, "misses": 0}
    assert healed.compiles == 0


def test_swap_on_a_warm_boot_stays_compile_free(tmp_path):
    """A canary/heal swap on a cache-warm replica: same architecture,
    new weights — the swap's warm loop replays the AOT tables (the
    executable is a pure function of avals, not weights), so the
    rollover costs ZERO compiles and the family digest still hits."""
    from znicz_tpu import snapshotter
    from znicz_tpu.serving import BucketLadder, ModelRunner

    wf = _tiny_mnist_wf()
    wf.snapshotter.directory = str(tmp_path / "snaps")
    path = wf.snapshotter.save("gen2")

    ladder = BucketLadder(8)
    cache_dir = tmp_path / "aot"
    _warm_runner(cache_dir, ladder)            # populate the cache

    warm = ModelRunner(_tiny_mnist_wf())
    assert warm.enable_aot_cache(str(cache_dir))
    warm.warmup(ladder)
    assert warm.compiles == 0
    rep = warm.swap(path, ladder)          # returns snapshot metadata
    assert "epoch" in rep and warm.generation == 2
    assert warm.compiles == 0                  # swap warmed from tables
    assert warm.jit_cache_size() == 0
    assert warm.snapshot_path == path


# -- generation family --------------------------------------------------------


def test_generation_family_roundtrip_and_parity(tmp_path):
    """The paged generation executables (prefill/decode per (batch
    rung, page rung), plus the COW copy) roundtrip the cache too: a
    fresh runner loads every entry the drive touched with zero
    compiles and decodes the same tokens bit-for-bit, including across
    a page-table rung step (1 -> 2 pages) and a COW copy."""
    from znicz_tpu.serving.model import ModelRunner

    def boot():
        r = ModelRunner(_charlm_wf())
        assert r.enable_aot_cache(str(tmp_path))
        return r.enable_generation(page_size=8, num_pages=8, slots=2,
                                   prefill_chunk=8, prefix_cache=False,
                                   prefill_rungs=[1], decode_rungs=[1])

    def drive(g):
        rng = np.random.default_rng(17)
        prompt = rng.integers(1, VOCAB, size=5).astype(np.uint8)
        pages = [g.alloc_page()]
        x = np.zeros((1, 8), g.runner.dtype)
        x[0, :5] = prompt
        tok, _, _, _ = g.prefill(x, [0], [5], [pages], [0.0], [0], [0])
        toks = [int(tok[0])]
        t = 5
        for _ in range(6):                     # crosses the page boundary
            if t % g.page_size == 0:
                pages.append(g.alloc_page())
            tok, _, _, _ = g.decode([pages], [toks[-1]], [t],
                                    [0.0], [0], [0])
            toks.append(int(tok[0]))
            t += 1
        dst = g.alloc_page()                   # the COW executable too
        g.copy_page(pages[0], dst)
        g.release_pages(pages + [dst])
        return toks

    cold = boot()
    fam = cold.executables()
    ref = drive(cold)
    # every executable the drive touched was compiled + stored
    stores = cold.runner._aot_cache.counts["stores"]
    assert stores == cold.runner.compiles > 0

    warm = boot()
    assert drive(warm) == ref                  # bit-identical decode
    assert warm.runner.compiles == 0
    assert warm.runner._warm["misses"] == 0
    assert warm.runner._warm["hits"] == stores
    assert warm.jit_cache_size() == 0
    assert fam == warm.executables()
    assert warm.pages_active() == 0 and warm.pages_leaked() == 0


@pytest.mark.slow
def test_generation_full_warmup_roundtrip(tmp_path):
    """``GenerationRunner.warmup()`` (the boot path) over the cache:
    cold stores the full paged family — (prefill rungs + decode rungs)
    x page rungs + the copy — warm loads it: ``loaded == family`` with
    zero compiles, the /readyz equality for the generation plane."""
    from znicz_tpu.serving.model import ModelRunner

    def boot():
        r = ModelRunner(_charlm_wf())
        assert r.enable_aot_cache(str(tmp_path))
        return r.enable_generation(page_size=8, num_pages=8, slots=2,
                                   prefill_chunk=8,
                                   prefill_rungs=[1], decode_rungs=[1])

    cold = boot()
    fam = cold.warmup()
    assert fam == cold.executables()
    assert fam == 2 * len(cold.page_rungs) + 1
    assert cold.runner.compiles == fam
    assert cold.runner._aot_cache.counts["stores"] == fam

    warm = boot()
    # warmup returns the runner's compile count — zero on a warm boot
    assert warm.warmup() == 0
    assert warm.runner.compiles == 0
    assert warm.runner._warm == {"hits": fam, "misses": 0}
    assert warm.jit_cache_size() == 0
    assert warm.stats()["aot_loaded"] == fam


# -- e2e server boot ----------------------------------------------------------


def test_e2e_server_boots_warm_and_gates_readyz_on_the_proof(tmp_path):
    """Two InferenceServer boots over one cache directory: the first
    compiles + stores (warm_report mode=aot, ok), the second loads the
    whole family (cache_hit, zero compiles), serves bit-exact answers,
    and ships the warm columns in its stats/heartbeat payloads."""
    from znicz_tpu.serving import InferenceClient, InferenceServer

    root.common.serving.aot_cache.update(
        {"enabled": True, "dir": str(tmp_path)})
    try:
        boots = []
        ref = None
        x = np.arange(784, dtype=np.float32).reshape(1, 784) / 784.0
        for _ in range(2):
            srv = InferenceServer(_tiny_mnist_wf(), max_batch=8).start()
            cli = InferenceClient(srv.endpoint, timeout=30)
            try:
                y = cli.infer(x)
                ref = y if ref is None else ref
                np.testing.assert_array_equal(y, ref)
                st = cli.stats()
                boots.append((srv.warm_report, st,
                              srv.boot_to_ready_s))
            finally:
                cli.close()
                srv.stop()
        (cold, cold_st, cold_boot), (warm, warm_st, warm_boot) = boots
        n = cold["expected"]
        assert cold["ok"] and cold["mode"] == "aot"
        assert cold["cache_misses"] == n and cold["cache_hits"] == 0
        assert warm["ok"] and warm["cache_hits"] == n
        assert warm["compiles"] == 0 and warm["jit_cache_size"] == 0
        assert warm["warm_source"] == "cache_hit"
        assert warm_st["model"]["warm_source"] == "cache_hit"
        assert warm_st["model"]["aot_loaded"] == n
        assert warm_st["boot_to_ready_s"] is not None
        assert cold_boot > 0 and warm_boot > 0
    finally:
        root.common.serving.aot_cache.update(
            {"enabled": False, "dir": ""})
