"""Relay-tree gradient aggregation (ISSUE 10): O(log N) reduction over
wire v3 — planner/spec units, job batching, the LR-schedule-at-dispatch
satellite, codec byte-identity through a relay hop, per-child edge
quarantine with master counters intact, a lean 1-level tree training
run, dead-relay fallback, and (slow) a 2-level chaos soak."""

import threading
import time

import numpy as np
import pytest

from znicz_tpu.core.config import root
from znicz_tpu.parallel import wire


def _make_workflow(tmp_path, max_epochs=3):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def _handshake_fields(workflow):
    from znicz_tpu.network_common import handshake_request

    msg = handshake_request(workflow)
    del msg["cmd"]
    return msg


def _white_box_relay(n_children=3, fanout=3, **kwargs):
    """A Relay used WITHOUT sockets: pre-validated credentials, enough
    registered children that the flush threshold is never crossed by
    the test's buffered messages (no upstream to flush into)."""
    from znicz_tpu.parallel.relay import Relay

    kwargs.setdefault("flush_s", 999.0)
    relay = Relay("tcp://127.0.0.1:1", "tcp://127.0.0.1:2",
                  relay_id="wb-relay", fanout=fanout, **kwargs)
    relay._cred = (3, "cafebabecafebabe")
    now = time.time()
    for i in range(n_children):
        relay._children[f"s{i}"] = now
    return relay


# -- planner / CLI spec --------------------------------------------------------


def test_plan_tree_shapes_and_relay_spec():
    from znicz_tpu.parallel.relay import parse_relay_spec, plan_tree

    master = "tcp://127.0.0.1:5570"
    p = plan_tree(8, 2, master)
    assert p["levels"] == 2
    assert len(p["relays"]) == 6            # 2 mid + 4 leaf
    # top tier dials the master; every leaf endpoint is a relay of the
    # bottom tier; slaves spread across all leaf relays
    assert [r["upstream"] for r in p["relays"][:2]] == [master] * 2
    mid_binds = {r["bind"] for r in p["relays"][:2]}
    assert all(r["upstream"] in mid_binds for r in p["relays"][2:])
    leaf_binds = [r["bind"] for r in p["relays"][2:]]
    assert set(p["slave_endpoints"]) == set(leaf_binds)
    assert len(p["slave_endpoints"]) == 8
    # 2 slaves -> one relay proves the hop; 1 slave -> no relays at all
    assert len(plan_tree(2, 2, master)["relays"]) == 1
    assert plan_tree(1, 2, master) == {
        "relays": [], "slave_endpoints": [master], "levels": 0}

    assert parse_relay_spec("tcp://h:5570") == ("tcp://h:5570",
                                                "tcp://*:5571")
    assert parse_relay_spec("tcp://h:5570:5599") == ("tcp://h:5570",
                                                     "tcp://*:5599")
    assert parse_relay_spec("tcp://h:5570:tcp://*:9") == ("tcp://h:5570",
                                                          "tcp://*:9")
    with pytest.raises(ValueError, match="--relay spec"):
        parse_relay_spec("not-an-endpoint")
    # fanout 1 is a chain, not a tree — refused, never an infinite loop
    with pytest.raises(ValueError, match="fanout"):
        plan_tree(4, 1, master)
    # the launcher surfaces of the planner and the role exclusivity
    from znicz_tpu import launcher

    assert launcher.main(["--relay", "tcp://h:5570", "--master"]) == 2


def test_job_batch_request(tmp_path):
    """``{"cmd": "job", "count": k}`` returns up to k jobs under ONE
    params broadcast; a count-less request keeps the historical flat
    reply shape (old slaves unchanged)."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "r1", "relay": True,
                           **_handshake_fields(master_wf)})["ok"]
    assert "r1" in server.relays
    rep = server._handle({"cmd": "job", "id": "r1", "count": 3})
    assert "jobs" in rep and "params" in rep
    assert len(rep["jobs"]) == 3
    assert len(server._inflight) == 3
    jids = [e["job_id"] for e in rep["jobs"]]
    assert len(set(jids)) == 3
    for e in rep["jobs"]:
        assert "job" in e and "trace_id" in e and "train" in e
        assert "params" not in e            # ONE broadcast per batch
    # flat shape for a count-less request
    flat = server._handle({"cmd": "job", "id": "r1"})
    assert "job" in flat and "params" in flat and "jobs" not in flat


# -- LR schedules under master/slave (satellite) -------------------------------


def _attach_lr_schedule(wf, gamma=0.5):
    from znicz_tpu.lr_adjust import ExpPolicy, LearningRateAdjust

    adj = LearningRateAdjust(wf, name="lr_adjust")
    for gd in wf.gds:
        adj.add_gd(gd, ExpPolicy(gamma=gamma))
    return adj


def test_lr_schedule_evaluated_at_dispatch(tmp_path):
    """The master evaluates lr_adjust policies at dispatch and stamps
    scheduled (lr, lr_bias) on each TRAIN minibatch — the unit-path
    clock exactly (minibatch k at pol(base, k-1)); eval minibatches are
    unstamped and do not advance the iteration."""
    from znicz_tpu.loader.base import TRAIN
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    _attach_lr_schedule(master_wf, gamma=0.5)
    base = float(master_wf.gds[0].learning_rate)
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    seen = []
    for _ in range(8):
        rep = server._handle({"cmd": "job", "id": "s1"})
        job = rep["job"]
        if job["class"] == TRAIN:
            seen.append(job["hypers"][master_wf.gds[0].forward.name][0])
        else:
            assert "hypers" not in job
        server._handle({"cmd": "update", "id": "s1",
                        "job_id": rep["job_id"], "deltas": None,
                        "metrics": {"loss": 1.0, "n_err": 0}})
    # mb 0 at base, mb k at base * 0.5^(k-1)
    expect = [base] + [base * 0.5 ** k for k in range(len(seen) - 1)]
    assert seen == pytest.approx(expect)
    assert server._lr_iteration == len(seen)
    # the iteration survives a crash-resume round trip
    path = str(tmp_path / "resume.pickle")
    server.save_resume(path)
    server2 = Server(_make_workflow(tmp_path / "m2"), resume_path=path)
    assert server2._lr_iteration == server._lr_iteration


def test_scheduled_hypers_rows_and_unit_slave_application(tmp_path):
    """Both engines apply the shipped schedule: scheduled_hypers_rows
    overrides exactly (lr, lr_bias) per step for the fused scan, and
    the unit slave writes the stamped rates into its gds before they
    run."""
    from znicz_tpu.client import Client, scheduled_hypers_rows
    from znicz_tpu.loader.base import TRAIN

    base = {"fc1": tuple(np.float32(v) for v in
                         (0.1, 0.2, 0.0, 0.0, 0.0, 0.9, 0.9, 0.0))}
    mbs = [{"hypers": {"fc1": (0.05, 0.07)}}, {}]
    rows = scheduled_hypers_rows(base, mbs)
    assert rows["fc1"].shape == (2, 8)
    assert rows["fc1"][0, 0] == np.float32(0.05)
    assert rows["fc1"][0, 1] == np.float32(0.07)
    np.testing.assert_array_equal(rows["fc1"][0, 2:],
                                  np.asarray(base["fc1"][2:], np.float32))
    np.testing.assert_array_equal(rows["fc1"][1],
                                  np.asarray(base["fc1"], np.float32))

    wf = _make_workflow(tmp_path / "s")
    client = Client(wf, slave_id="lr-unit")
    gd = wf.gds[0]
    job = {"indices": np.zeros(60, np.int32), "size": 60, "class": TRAIN,
           "hypers": {gd.forward.name: (0.0125, 0.025)}}
    client._run_one(job, train=True)
    assert gd.learning_rate == pytest.approx(0.0125)
    assert gd.learning_rate_bias == pytest.approx(0.025)


def test_lr_schedule_advances_end_to_end(tmp_path):
    """One unit slave through the full socket stack: after a 2-epoch
    run under an exp schedule the SLAVE's gds hold the master's last
    scheduled rate — the 'schedules do not advance' limitation is
    gone."""
    from znicz_tpu.client import Client
    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17650"
    master_wf = _make_workflow(tmp_path / "m", max_epochs=2)
    _attach_lr_schedule(master_wf, gamma=0.9)
    base = float(master_wf.gds[0].learning_rate)
    server = Server(master_wf, endpoint=endpoint, job_timeout=60.0)
    slave = Client(_make_workflow(tmp_path / "s", max_epochs=2),
                   endpoint=endpoint, slave_id="lr-slave")
    t = threading.Thread(target=slave.run, daemon=True)
    t.start()
    server.serve()
    t.join(timeout=60)
    assert not t.is_alive()
    assert bool(master_wf.decision.complete)
    # 2 epochs x 5 TRAIN mbs: the last one dispatched at iteration 9,
    # scheduled at pol(base, 8) — and the slave really applied it
    assert server._lr_iteration == 10
    assert slave.workflow.gds[0].learning_rate == \
        pytest.approx(base * 0.9 ** 8)


# -- codec byte-identity through a relay hop -----------------------------------


def test_codec_byte_identity_through_relay_hop():
    """f32 wire: a single contribution re-emerges from the relay's
    flush as byte-identical tensor frames (sum of one == the delta, no
    re-quantization); the flush encoding is deterministic (same state
    -> same bytes, the resend-same-bytes property); int8 wire: two
    relays fed identically produce identical flush bytes, and the
    decoded sum matches within one quantization step."""
    rng = np.random.default_rng(17)
    deltas = {"fc1": {"weights": rng.normal(
        0, 0.01, (32, 16)).astype(np.float32),
        "bias": rng.normal(0, 0.01, 16).astype(np.float32)}}

    relay = _white_box_relay(wire_dtype="float32")
    rep = relay._child_update({"cmd": "update", "id": "s0", "job_id": 7,
                               "deltas": deltas,
                               "metrics": {"loss": 1.0}}, "s0")
    assert rep["ok"] is True
    entries, summed = list(relay._buffer), dict(relay._sum)
    flush1, _ = wire.encode_message(relay._flush_message(entries, summed))
    flush2, _ = wire.encode_message(relay._flush_message(entries, summed))
    assert [bytes(f) for f in flush1] == [bytes(f) for f in flush2]
    child, _ = wire.encode_message(
        {"cmd": "update", "id": "s0", "job_id": 7, "deltas": deltas,
         "metrics": {"loss": 1.0}})
    # same bytes in == same tensor bytes out (frame 0 is the skeleton)
    assert [bytes(f) for f in flush1[1:]] == [bytes(f) for f in child[1:]]
    dec, _ = wire.decode_message(flush1)
    np.testing.assert_array_equal(dec["deltas"]["fc1"]["weights"],
                                  deltas["fc1"]["weights"])
    assert dec["contributors"][0]["job_id"] == 7
    assert dec["contributors"][0]["delta"] is True

    # int8 upward re-encode: deterministic and within quantization error
    flushes = []
    for _ in range(2):
        r = _white_box_relay(wire_dtype="int8")
        for jid, sid in ((1, "s0"), (2, "s1")):
            assert r._child_update(
                {"cmd": "update", "id": sid, "job_id": jid,
                 "deltas": deltas, "metrics": {"loss": 1.0}}, sid)["ok"]
        frames, _ = wire.encode_message(
            r._flush_message(list(r._buffer), dict(r._sum)))
        flushes.append([bytes(f) for f in frames])
    assert flushes[0] == flushes[1]
    dec, _ = wire.decode_message(flushes[0])
    want = 2.0 * deltas["fc1"]["weights"]
    got = dec["deltas"]["fc1"]["weights"]
    scale = float(np.max(np.abs(want))) / 127.0
    assert float(np.max(np.abs(got - want))) <= scale + 1e-7


# -- per-child quarantine at the edge, master counters intact ------------------


def test_edge_quarantine_and_master_requeue(tmp_path):
    """A poisoned child is refused AT THE RELAY (the partial sum stays
    clean), the refusal rides the manifest, and the master's books stay
    exact: quarantined_updates ticks, the child's job is re-queued, the
    healthy sibling's delta lands, jobs_done attributes to the leaf."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "wb-relay",
                           "relay": True,
                           **_handshake_fields(master_wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "wb-relay", "count": 2})
    jid_a, jid_b = (e["job_id"] for e in rep["jobs"])

    relay = _white_box_relay()
    shapes = {f.name: {k: a.shape for k, a in f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    poisoned = {n: {k: np.full(s, np.nan, np.float32)
                    for k, s in layer.items()}
                for n, layer in shapes.items()}
    healthy = {n: {k: np.full(s, 1e-4, np.float32)
                   for k, s in layer.items()}
               for n, layer in shapes.items()}
    rep = relay._child_update({"cmd": "update", "id": "s0",
                               "job_id": jid_a, "deltas": poisoned,
                               "metrics": {"loss": 1.0}}, "s0")
    assert rep["ok"] is False and rep.get("quarantined")
    assert "non-finite" in rep["error"]
    assert relay.refusals == 1
    assert not relay._sum                   # the sum never saw it
    rep = relay._child_update({"cmd": "update", "id": "s1",
                               "job_id": jid_b, "deltas": healthy,
                               "metrics": {"loss": 1.0, "n_err": 0}},
                              "s1")
    assert rep["ok"] is True

    before = {f.name: {k: np.array(a.map_read())
                       for k, a in f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    up = server._handle(dict(
        relay._flush_message(list(relay._buffer), dict(relay._sum)),
        cmd="update", id="wb-relay"))
    assert up["ok"] is True
    assert up["outcomes"][jid_a] == "quarantined"
    assert up["outcomes"][jid_b] == "ok"
    assert server.quarantined_updates == 1
    assert server.aggregated_updates == 1
    assert len(server._pending) == 1        # the poisoned job came back
    assert server.jobs_done == 1
    assert server.jobs_by_slave == {"s1": 1}
    for f in master_wf.forwards:            # exactly the healthy delta
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_allclose(
                    np.array(a.map_read()),
                    before[f.name][k] + healthy[f.name][k], rtol=1e-5)

    # an exploded COMBINED sum: requeue-per-child, the sum is
    # indivisible so neither contributor's input may land
    server._delta_norms.extend([1e-4] * 5)
    rep = server._handle({"cmd": "job", "id": "wb-relay", "count": 2})
    jids = [e["job_id"] for e in rep["jobs"]]
    exploded = {n: {k: np.full(s, 1e5, np.float32)
                    for k, s in layer.items()}
                for n, layer in shapes.items()}
    before = {f.name: {k: np.array(a.map_read())
                       for k, a in f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    up = server._handle({
        "cmd": "update", "id": "wb-relay", "deltas": exploded,
        "contributors": [
            {"id": "s0", "job_id": jids[0], "delta": True,
             "metrics": {"loss": 1.0, "n_err": 0}},
            {"id": "s1", "job_id": jids[1], "delta": True,
             "metrics": {"loss": 1.0, "n_err": 0}}]})
    assert up["ok"] is False and up.get("quarantined")
    assert server.quarantined_updates == 3  # 1 edge + 2 requeued here
    # both contributors' jobs came back (the first refused job was
    # re-issued inside this very batch, so the queue holds exactly 2)
    assert len(server._pending) == 2
    for f in master_wf.forwards:
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_array_equal(np.array(a.map_read()),
                                              before[f.name][k])
    # a stale contributor is dropped and counted, not applied
    up = server._handle({
        "cmd": "update", "id": "wb-relay", "deltas": None,
        "contributors": [{"id": "s0", "job_id": 99999,
                          "metrics": {"loss": 1.0, "n_err": 0}}]})
    assert up["ok"] is True and up["outcomes"][99999] == "stale"
    assert server.stale_updates == 1

    # resend idempotence (review finding): a relay re-sends the SAME
    # flush bytes after a lost reply; on the second delivery every
    # contributor is stale and the summed delta must be DROPPED — the
    # star's one-job-one-accepted-update invariant, kept for trees
    server._delta_norms.clear()     # drop the tiny norms seeded above
    rep = server._handle({"cmd": "job", "id": "wb-relay"})
    flush = {"cmd": "update", "id": "wb-relay", "deltas": healthy,
             "contributors": [{"id": "s0", "job_id": rep["job_id"],
                               "delta": True,
                               "metrics": {"loss": 1.0, "n_err": 0}}]}
    assert server._handle(dict(flush))["ok"] is True      # applied once
    after_first = {f.name: {k: np.array(a.map_read())
                            for k, a in f.params().items()}
                   for f in master_wf.forwards if f.has_weights}
    resent = server._handle(dict(flush))                  # same bytes
    assert resent["ok"] is True
    assert resent["outcomes"][rep["job_id"]] == "stale"
    for f in master_wf.forwards:
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_array_equal(np.array(a.map_read()),
                                              after_first[f.name][k])


def test_malformed_metrics_aborts_indivisible_aggregate(tmp_path):
    """Review finding: a DELTA-BEARING contributor with malformed
    metrics cannot be refused individually — its gradient is baked into
    the indivisible sum, and the star's order is refuse-BEFORE-apply.
    The whole aggregate is refused: nothing lands, the malformed child
    takes the bounded bad-reply strike, the innocent sibling is
    re-queued without one — so when the re-dispatched jobs come back
    their gradients land exactly once."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "r", "relay": True,
                           **_handshake_fields(master_wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "r", "count": 2})
    jid_a, jid_b = (e["job_id"] for e in rep["jobs"])
    shapes = {f.name: {k: a.shape for k, a in f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    summed = {n: {k: np.full(s, 2e-4, np.float32)
                  for k, s in layer.items()}
              for n, layer in shapes.items()}
    before = {f.name: {k: np.array(a.map_read())
                       for k, a in f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    up = server._handle({
        "cmd": "update", "id": "r", "deltas": summed,
        "contributors": [
            {"id": "s0", "job_id": jid_a, "delta": True,
             "metrics": [{"loss": 1.0}]},    # malformed: list, not dict
            {"id": "s1", "job_id": jid_b, "delta": True,
             "metrics": {"loss": 1.0, "n_err": 0}}]})
    assert up["ok"] is False and "not a dict" in up["error"]
    assert up["outcomes"][jid_a] == "refused"
    assert up["outcomes"][jid_b] == "requeued"
    assert server.bad_updates == 1          # only the malformed child
    assert server.jobs_requeued == 1        # the innocent sibling
    assert server.jobs_done == 0
    assert len(server._pending) == 2        # both jobs come back
    for f in master_wf.forwards:            # NOTHING landed
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_array_equal(np.array(a.map_read()),
                                              before[f.name][k])


def test_edge_shape_check_survives_flush_windows_and_spares_evals():
    """Round-3 review findings: (a) the relay learns param shapes from
    the first ACCEPTED delta for its lifetime, so a wrong-shaped child
    arriving FIRST in a later flush window (when the sum is empty) is
    refused itself instead of seeding the aggregate and getting its
    healthy siblings refused; (b) when an incoming aggregate's delta is
    refused, delta-less contributors (eval metrics) pass through intact
    — nothing of theirs was in the refused sum; (c) a flush that never
    shipped (stop() mid-run) does not tick relay_flushes."""
    good = {"fc": {"w": np.full((4, 3), 1e-3, np.float32)}}
    bad_shape = {"fc": {"w": np.full((2, 2), 1e-3, np.float32)}}

    relay = _white_box_relay()
    assert relay._child_update({"cmd": "update", "id": "s0", "job_id": 1,
                                "deltas": good,
                                "metrics": {"loss": 1.0}}, "s0")["ok"]
    # simulate a completed flush window: sum empties, shapes persist
    relay._buffer, relay._buffer_msgs = [], 0
    relay._sum, relay._sum_t0 = {}, None
    rep = relay._child_update({"cmd": "update", "id": "s1", "job_id": 2,
                               "deltas": bad_shape,
                               "metrics": {"loss": 1.0}}, "s1")
    assert rep["ok"] is False and "shape" in rep["error"]
    assert not relay._sum                   # never seeded the aggregate
    assert relay._child_update({"cmd": "update", "id": "s2", "job_id": 3,
                                "deltas": good,
                                "metrics": {"loss": 1.0}}, "s2")["ok"]

    # (b) eval contributors survive a refused aggregate
    relay2 = _white_box_relay()
    poisoned = {"fc": {"w": np.full((4, 3), np.nan, np.float32)}}
    rep = relay2._child_update({
        "cmd": "update", "id": "low-relay",
        "deltas": poisoned,
        "contributors": [
            {"id": "a", "job_id": 10, "delta": True,
             "metrics": {"loss": 1.0}},
            {"id": "b", "job_id": 11,
             "metrics": {"loss": 0.5, "n_err": 2}}]}, "low-relay")
    assert rep["ok"] is False and rep.get("quarantined")
    by_jid = {e["job_id"]: e for e in relay2._buffer}
    assert by_jid[10].get("refused") and "non-finite" in by_jid[10][
        "refused"]
    assert "refused" not in by_jid[11]
    assert by_jid[11]["metrics"] == {"loss": 0.5, "n_err": 2}
    assert relay2.refusals == 1

    # (c) an undelivered flush is not counted
    relay3 = _white_box_relay()
    relay3._stop.set()
    relay3._buffer = [{"id": "x", "job_id": 1}]
    relay3._buffer_msgs = 1
    relay3._flush()                         # rpc returns None: no send
    assert relay3.flushes == 0


def test_relay_child_ttl_eviction():
    """A dead sibling must not inflate the flush threshold forever: a
    child silent past child_ttl leaves the table (the master's TTL rule
    at the relay tier) and a re-register brings it straight back."""
    relay = _white_box_relay(n_children=2, fanout=2, child_ttl=0.1)
    relay._children["s0"] = time.time() - 1.0   # long silent
    relay._evict_children()
    assert set(relay.children) == {"s1"}
    # threshold follows the live membership: one child -> flush at 1
    relay._buffer.append({"id": "s1", "job_id": 1})
    relay._buffer_msgs = 1
    assert relay._flush_due()
    # rate-limited: a second call inside 1s is a no-op by design
    relay._children["ghost"] = time.time() - 9.0
    relay._evict_children()
    assert "ghost" in relay.children
    relay._last_evict = 0.0
    relay._evict_children()
    assert "ghost" not in relay.children


# -- the lean tree run ---------------------------------------------------------


def test_one_level_tree_trains_and_accounts(tmp_path):
    """2 slaves -> 1 relay -> master: training completes in the quality
    band, the master decodes FEWER update messages than jobs (the
    aggregation actually happened), jobs_done attributes to the LEAF
    ids, and the web_status topology panel shows the tree."""
    import json
    import urllib.request

    from znicz_tpu.client import Client
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server
    from znicz_tpu.web_status import WebStatus

    master_ep = "tcp://127.0.0.1:17651"
    relay_ep = "tcp://127.0.0.1:17652"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=master_ep, job_timeout=60.0)
    relay = Relay(master_ep, relay_ep, relay_id="t1-relay").start()
    slaves = [Client(_make_workflow(tmp_path / f"s{i}"),
                     endpoint=relay_ep, slave_id=f"leaf{i}")
              for i in range(2)]
    errors = []

    def worker(s):
        try:
            s.run()
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    status = WebStatus(port=0).start()
    try:
        status.register(master_wf)
        status.register_server(server)
        status.register_relay(relay)
        for t in threads:
            t.start()
        server.serve()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads)

        dec = master_wf.decision
        assert bool(dec.complete)
        valid = dec.epoch_metrics[1]
        assert valid is not None and valid["err_pct"] < 70.0, valid
        # aggregation really happened, and the books balance on LEAVES
        assert server.aggregated_updates >= 1
        assert server.updates_received < server.jobs_done
        assert server.jobs_done == sum(server.jobs_by_slave.values())
        assert server.jobs_by_slave.get("leaf0", 0) > 0
        assert server.jobs_by_slave.get("leaf1", 0) > 0
        assert "t1-relay" not in server.jobs_by_slave
        assert "t1-relay" in server.relays
        assert relay.flushes >= 1
        assert relay.contributions >= server.jobs_done
        # every slave's view went through the relay: the master's only
        # direct member is the relay
        assert set(server.jobs_by_slave) == {"leaf0", "leaf1"}
        # the tree-topology panel
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            snap = json.load(r)
        master = snap["master"]
        assert [s["id"] for s in master["slaves"]] == ["t1-relay"]
        assert master["slaves"][0]["relay"] is True
        assert {s["id"] for s in master["leaves"]} == {"leaf0", "leaf1"}
        assert master["aggregated_updates"] == server.aggregated_updates
        assert snap["relays"][0]["id"] == "t1-relay"
        assert {c["id"] for c in snap["relays"][0]["children"]} == \
            {"leaf0", "leaf1"}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "Relay t1-relay" in page and "(relay)" in page
    finally:
        status.stop()
        relay.stop()


def test_relay_death_children_fall_back_upstream(tmp_path):
    """Relay death mid-run: in-flight work requeues via the master's
    existing TTL reaper and the children — their reconnect budget to
    the dead relay spent — fall back to the UPSTREAM endpoint the relay
    advertised at register time, re-register, and finish the run."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.chaos import RelayHarness
    from znicz_tpu.server import Server

    master_ep = "tcp://127.0.0.1:17653"
    relay_ep = "tcp://127.0.0.1:17654"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=master_ep, job_timeout=4.0)
    server_thread = threading.Thread(target=server.serve, daemon=True)
    server_thread.start()
    harness = RelayHarness(master_ep, relay_ep, relay_id="doomed-relay")
    harness.start()

    slaves = [Client(_make_workflow(tmp_path / f"s{i}"),
                     endpoint=relay_ep, slave_id=f"phx{i}")
              for i in range(2)]
    errors = []

    def worker(s):
        try:
            s.run(recv_timeout=0.75, max_reconnects=2,
                  backoff_base=0.05, backoff_cap=0.2)
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    deadline = time.time() + 60
    while server.jobs_done < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert server.jobs_done >= 2
    harness.kill()                          # the relay dies for good

    server_thread.join(timeout=120)
    assert not server_thread.is_alive()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = master_wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
    # both children really switched to the advertised upstream
    for s in slaves:
        assert s.endpoint == master_ep, s.endpoint
        assert s.reconnects >= 1
    # post-fallback the leaves worked DIRECTLY for the master too; the
    # books still balance on leaf ids only
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    assert set(server.jobs_by_slave) <= {"phx0", "phx1"}
    assert sum(server.jobs_by_slave.values()) == server.jobs_done


def test_fused_slaves_through_relay_with_lr_schedule(tmp_path):
    """The fused engine through the tree: a FusedClient working via a
    relay under a master-evaluated LR schedule — segment jobs, the
    scheduled per-step hypers rows, delta aggregation and decision
    accounting all compose."""
    from znicz_tpu.client import FusedClient
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server

    master_ep = "tcp://127.0.0.1:17655"
    relay_ep = "tcp://127.0.0.1:17656"
    master_wf = _make_workflow(tmp_path / "m")
    _attach_lr_schedule(master_wf, gamma=0.9)
    server = Server(master_wf, endpoint=master_ep, job_timeout=60.0,
                    segment_steps=3)
    relay = Relay(master_ep, relay_ep, relay_id="f-relay").start()
    slave = FusedClient(_make_workflow(tmp_path / "s"),
                        endpoint=relay_ep, slave_id="fused-leaf")
    t = threading.Thread(target=slave.run, daemon=True)
    try:
        t.start()
        server.serve()
        t.join(timeout=120)
        assert not t.is_alive()
    finally:
        relay.stop()
    dec = master_wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
    assert server._lr_iteration == 15       # the schedule advanced
    assert server.aggregated_updates >= 1
    assert server.jobs_by_slave.get("fused-leaf", 0) > 0
    assert server.jobs_done == sum(server.jobs_by_slave.values())


# -- the slow 2-level chaos soak -----------------------------------------------


@pytest.mark.slow
def test_two_level_tree_chaos_soak(tmp_path):
    """Everything at once on a 2-level tree: seeded ChaosProxy
    drop/corrupt/dup/delay on the mid-relay -> master link (the relay's
    upstream machinery rides the same fault model as a slave's), a leaf
    relay killed and RESTARTED at the same bind mid-run (children
    reconnect + re-register through the existing path), 4 slaves.
    Training completes in the quality band with exact leaf
    accounting."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.chaos import (ChaosProxy, FaultSchedule,
                                          RelayHarness)
    from znicz_tpu.parallel.relay import Relay
    from znicz_tpu.server import Server

    master_ep = "tcp://127.0.0.1:17660"
    proxy_front = "tcp://127.0.0.1:17661"   # mid relay dials this
    mid_ep = "tcp://127.0.0.1:17662"
    leaf_a = "tcp://127.0.0.1:17663"
    leaf_b = "tcp://127.0.0.1:17664"
    proxy = ChaosProxy(proxy_front, master_ep,
                       FaultSchedule(5, drop=0.05, corrupt=0.05,
                                     duplicate=0.04, delay=0.06,
                                     delay_s=(0.02, 0.2))).start()
    master_wf = _make_workflow(tmp_path / "m", max_epochs=4)
    server = Server(master_wf, endpoint=master_ep, job_timeout=6.0)
    server_thread = threading.Thread(
        target=server.serve, kwargs={"linger": 8.0}, daemon=True)
    server_thread.start()
    mid = Relay(proxy_front, mid_ep, relay_id="soak-mid",
                recv_timeout=1.0, max_reconnects=60).start()
    leaf_harness = RelayHarness(mid_ep, leaf_a, relay_id="soak-leaf-a",
                                recv_timeout=2.0, max_reconnects=60)
    leaf_harness.start()
    leaf2 = Relay(mid_ep, leaf_b, relay_id="soak-leaf-b",
                  recv_timeout=2.0, max_reconnects=60).start()

    slaves = [Client(_make_workflow(tmp_path / f"s{i}", max_epochs=4),
                     endpoint=(leaf_a if i < 2 else leaf_b),
                     slave_id=f"soak{i}") for i in range(4)]
    errors = []

    def worker(s):
        try:
            s.run(recv_timeout=1.0, max_reconnects=80,
                  backoff_base=0.05, backoff_cap=0.4,
                  connect_retries=80)
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    try:
        for t in threads:
            t.start()
        deadline = time.time() + 120
        while server.jobs_done < 4 and time.time() < deadline:
            time.sleep(0.05)
        assert server.jobs_done >= 4
        leaf_harness.restart()              # leaf relay dies + comes back
        server_thread.join(timeout=300)
        assert not server_thread.is_alive()
        for t in threads:
            t.join(timeout=120)
    finally:
        proxy.stop()
        mid.stop()
        leaf_harness.kill()
        leaf2.stop()
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = master_wf.decision
    assert bool(dec.complete)
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
    assert proxy.total_faults() > 0
    assert server.aggregated_updates >= 1
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    assert set(server.jobs_by_slave) <= {f"soak{i}" for i in range(4)}
    # the relay rode the chaos out on its own reconnect machinery
    assert mid.upstream_reconnects >= 1 or proxy.counters["rep"][
        "corrupt"] + proxy.counters["req"]["drop"] == 0
