"""FusedTrainer: parity with the unit-at-a-time engine, and 8-virtual-device
data parallelism (SURVEY.md §4: multi-device tests on CPU)."""

import numpy as np
import pytest

from znicz_tpu.core.config import root


def fresh_mnist(max_epochs=2):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def run_unit(wf):
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    wf.run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def run_fused(wf, mesh=None, tp_threshold=None):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf, mesh=mesh)
    if tp_threshold is not None:
        trainer.tp_threshold = tp_threshold
    trainer.run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def test_fused_matches_unit_path(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    wfu = fresh_mnist()
    lu, wu = run_unit(wfu)
    wff = fresh_mnist()
    lf, wf_ = run_fused(wff)
    np.testing.assert_allclose(lu, lf, rtol=1e-4)
    for name in wu:
        np.testing.assert_allclose(wu[name], wf_[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)
    # confusion totals match exactly — the fused path accumulates the
    # confusion on DEVICE across each epoch and transfers once at the
    # tail, which must be invisible to the Decision's epoch metrics
    for klass in (1, 2):
        cu = wfu.decision.epoch_metrics[klass]["confusion"]
        cf = wff.decision.epoch_metrics[klass]["confusion"]
        np.testing.assert_array_equal(np.asarray(cu), np.asarray(cf),
                                      err_msg=f"class {klass}")
        assert np.asarray(cf).sum() > 0


def test_fused_data_parallel_8dev_matches_single(tmp_path):
    import jax

    root.common.dirs.snapshots = str(tmp_path)
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    from znicz_tpu.parallel.mesh import make_mesh

    l1, w1 = run_fused(fresh_mnist())
    mesh = make_mesh(axes=("data",))
    l8, w8 = run_fused(fresh_mnist(), mesh=mesh)
    np.testing.assert_allclose(l1, l8, rtol=1e-4)
    for name in w1:
        np.testing.assert_allclose(w1[name], w8[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def hybrid_mesh():
    """A (data=4, model=2) mesh: batch sharded over ``data``, the 100-wide
    hidden FC row-sharded over ``model`` (tp_threshold lowered to 64)."""
    from znicz_tpu.parallel.mesh import make_mesh

    return make_mesh((4, 2), ("data", "model"))


def test_fused_tp_hybrid_mesh_matches_single(tmp_path):
    """Tensor parallelism correctness: a hybrid data x model mesh must
    reproduce the single-device losses AND weights (GSPMD inserts the
    collectives; the math may not change)."""
    root.common.dirs.snapshots = str(tmp_path)
    l1, w1 = run_fused(fresh_mnist())
    lt, wt = run_fused(fresh_mnist(), mesh=hybrid_mesh(), tp_threshold=64)
    np.testing.assert_allclose(l1, lt, rtol=1e-4)
    for name in w1:
        np.testing.assert_allclose(w1[name], wt[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_fused_tp_hybrid_mesh_matches_single_bf16(tmp_path):
    """Same TP-parity property under mixed precision: bf16 on the hybrid
    mesh vs bf16 single-device (looser tolerances — bf16 collective
    reduction order differs)."""
    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.precision = "bfloat16"
    try:
        l1, w1 = run_fused(fresh_mnist())
        lt, wt = run_fused(fresh_mnist(), mesh=hybrid_mesh(),
                           tp_threshold=64)
    finally:
        root.common.engine.precision = "float32"
    np.testing.assert_allclose(l1, lt, rtol=5e-2)
    assert lt[-1] < lt[0] * 0.9, lt             # and it actually trains
    for name in w1:
        np.testing.assert_allclose(w1[name], wt[name], rtol=5e-2,
                                   atol=5e-3, err_msg=name)


def test_fused_snapshot_restore_continue(tmp_path):
    """Restore-then-continue UNDER FusedTrainer: velocities + prng streams
    must round-trip, and the continued trajectory must match the unit
    engine continuing from the very same snapshot."""
    from znicz_tpu import snapshotter as snap_mod
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist
    from znicz_tpu.snapshotter import Snapshotter

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist(max_epochs=2)
    FusedTrainer(wf).run()
    path = wf.snapshotter.destination
    assert path is not None
    snap = Snapshotter.load(path)

    def resume(engine):
        prng.reset(1013)
        root.mnist.decision.max_epochs = 4           # 2 more epochs
        losses = []
        wf2 = mnist.MnistWorkflow()
        wf2.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        wf2.initialize(device=None)
        snap_mod.restore(wf2, snap)
        if engine == "fused":
            trainer = FusedTrainer(wf2)
            # restored velocities must be what the trainer picks up
            for name, layer in trainer.extract_velocities().items():
                gd_name = trainer.gd_of[name].name
                for k, v in layer.items():
                    np.testing.assert_allclose(
                        np.asarray(v), snap["velocities"][gd_name][k],
                        err_msg=f"{gd_name}.{k}")
            trainer.run()
        else:
            wf2.run()
        assert bool(wf2.decision.complete)
        return losses, {f.name: np.array(f.weights.map_read())
                        for f in wf2.forwards}

    lf, wf_f = resume("fused")
    lu, wf_u = resume("unit")
    assert len(lf) >= 2 and len(lf) == len(lu)       # continuation ran
    np.testing.assert_allclose(lf, lu, rtol=1e-4)
    for name in wf_u:
        np.testing.assert_allclose(wf_u[name], wf_f[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_fused_snapshotter_fires(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    from znicz_tpu.parallel.fused import FusedTrainer

    FusedTrainer(wf).run()
    assert wf.snapshotter.destination is not None
    import os
    assert os.path.exists(wf.snapshotter.destination)


def test_fused_rejects_tied_weights(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    root.mnist_ae.loader.n_train = 100
    root.mnist_ae.loader.n_valid = 50
    root.mnist_ae.loader.minibatch_size = 50
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist_ae

    wf = mnist_ae.MnistAEWorkflow()
    wf.initialize(device=None)
    wf.forwards = [wf.conv, wf.pool, wf.depool, wf.deconv]
    wf.gds = [wf.gd_deconv, wf.gd_depool, wf.gd_pool, wf.gd_conv]
    with pytest.raises(ValueError, match="tied"):
        FusedTrainer(wf)

def test_fused_stats_observability(tmp_path):
    """The fast path reports per-step timing (VERDICT r2 item 3): stats
    accumulate in FusedTrainer.run, appear in Workflow.print_stats and in
    the web_status snapshot."""
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.web_status import WebStatus

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    trainer = FusedTrainer(wf)
    trainer.run()
    s = trainer.stats
    assert s["train_steps"] > 0 and s["eval_steps"] > 0
    assert s["images"] >= s["train_steps"]       # >= 1 image per step
    assert s["wall_s"] > 0 and s["steps_per_sec"] > 0
    assert s["img_per_sec"] > 0 and s["last_step_ms"] > 0
    # warm numbers exclude each variant's first (compiling) dispatch
    assert s["warm_steps"] > 0
    assert s["warm_steps"] < s["train_steps"] + s["eval_steps"]
    assert s["warm_img_per_sec"] > s["img_per_sec"]
    assert wf.fused_stats is s
    table = wf.print_stats()
    assert "steps/s" in table and "img/s" in table
    assert "warm (excl. compiles)" in table

    status = WebStatus(port=0).start()
    try:
        status.register(wf)
        snap = status.snapshot()
        info = next(w for w in snap["workflows"] if w["name"] == wf.name)
        assert info["fused"]["train_steps"] == s["train_steps"]
    finally:
        status.stop()


def test_fused_remat_matches(tmp_path):
    """jax.checkpoint rematerialization changes memory, not math: loss
    curves and final weights match the non-remat fused run."""
    root.common.dirs.snapshots = str(tmp_path)
    lf, wf_ = run_fused(fresh_mnist())

    from znicz_tpu.parallel.fused import FusedTrainer

    wf2 = fresh_mnist()
    losses2 = []
    wf2.decision.on_epoch_end.append(
        lambda d: losses2.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf2, remat=True)
    assert trainer.remat is True
    trainer.run()
    np.testing.assert_allclose(lf, losses2, rtol=1e-5)
    for f in wf2.forwards:
        np.testing.assert_allclose(np.array(f.weights.map_read()),
                                   wf_[f.name], rtol=1e-4, atol=1e-6,
                                   err_msg=f.name)


def test_fused_eval_segments_respect_class_boundary(tmp_path):
    """With both TEST and VALID sets, per-class confusion must match the
    unit path exactly — eval scan segments may not span the class
    boundary (their summed confusion is booked to the first class)."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    def build():
        prng.reset(1013)
        root.mnist.loader.n_train = 300
        root.mnist.loader.n_valid = 120
        root.mnist.loader.n_test = 120
        root.mnist.loader.minibatch_size = 60
        root.mnist.decision.max_epochs = 2
        root.common.dirs.snapshots = str(tmp_path)
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        return wf

    try:
        wfu = build()
        wfu.run()
        wff = build()
        from znicz_tpu.parallel.fused import FusedTrainer

        FusedTrainer(wff).run()
        for klass in (0, 1, 2):
            cu = np.asarray(wfu.decision.epoch_metrics[klass]["confusion"])
            cf = np.asarray(wff.decision.epoch_metrics[klass]["confusion"])
            np.testing.assert_array_equal(cu, cf, err_msg=f"class {klass}")
            assert cf.sum() > 0
    finally:
        root.mnist.loader.n_test = 0


def test_fused_train_only_epoch_hook_once_per_epoch(tmp_path):
    """Train-only workflows (no TEST/VALID): the epoch-end hook must fire
    exactly once per epoch — a stale epoch_ended flag used to re-run it
    after the next epoch's first pipelined segment."""
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 0
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    root.common.dirs.snapshots = str(tmp_path)
    try:
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        calls = []
        wf.snapshotter.run = lambda: calls.append(1)
        wf.snapshotter.gate_skip.set(False)
        FusedTrainer(wf).run()
        assert bool(wf.decision.complete)
        assert len(calls) == 3, calls       # once per epoch, not more
    finally:
        root.mnist.loader.n_valid = 60


def test_fused_wall_time_not_double_counted(tmp_path):
    """Pipelined accounting must charge non-overlapping intervals:
    stats wall_s may not exceed true elapsed time."""
    import time as _t

    from znicz_tpu.parallel.fused import FusedTrainer

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    trainer = FusedTrainer(wf)
    t0 = _t.perf_counter()
    trainer.run()
    elapsed = _t.perf_counter() - t0
    assert trainer.stats["wall_s"] <= elapsed * 1.02 + 0.01, \
        (trainer.stats["wall_s"], elapsed)


def test_fused_lr_schedule_matches_unit_path(tmp_path):
    """An LR schedule wired by StandardWorkflow (lr_adjust_config) must
    drive the fused path exactly like the graph engine (the fast path
    used to ignore LearningRateAdjust silently) — per-step hypers ride
    the scan as xs."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples.mnist import MnistLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    def with_schedule():
        prng.reset(1013)
        root.mnist.loader.n_train = 300
        root.mnist.loader.n_valid = 60
        root.mnist.loader.n_test = 0
        root.mnist.loader.minibatch_size = 60
        root.common.dirs.snapshots = str(tmp_path)
        gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
        wf = StandardWorkflow(
            name="MnistStdLR",
            loader=MnistLoader(name="loader", minibatch_size=60),
            layers=[{"type": "all2all_tanh",
                     "->": {"output_sample_shape": 100}, "<-": dict(gd)},
                    {"type": "softmax",
                     "->": {"output_sample_shape": 10}, "<-": dict(gd)}],
            loss_function="softmax",
            decision_config={"max_epochs": 3},
            lr_adjust_config={"policy": "exp", "gamma": 0.9})
        wf.initialize(device=None)
        return wf

    lu, wu = run_unit(with_schedule())
    wff = with_schedule()
    lf, wf_ = run_fused(wff)
    assert len(lu) == len(lf) == 3
    np.testing.assert_allclose(lu, lf, rtol=1e-4)
    for name in wu:
        np.testing.assert_allclose(wu[name], wf_[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)
    # the schedule really advanced: 3 epochs x 5 train steps, minus the
    # final tail (gd_skip gates both the update and the adjust once
    # `complete` flips — identical in both engines)
    assert wff.lr_adjust.iteration == 14
    np.testing.assert_allclose(wff.gds[0].learning_rate,
                               0.1 * 0.9 ** 13, rtol=1e-6)
