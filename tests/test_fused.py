"""FusedTrainer: parity with the unit-at-a-time engine, and 8-virtual-device
data parallelism (SURVEY.md §4: multi-device tests on CPU)."""

import numpy as np
import pytest

from znicz_tpu.core.config import root


def fresh_mnist(max_epochs=2):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng._streams.clear()
    prng.seed_all(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def run_unit(wf):
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    wf.run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def run_fused(wf, mesh=None):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    FusedTrainer(wf, mesh=mesh).run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def test_fused_matches_unit_path(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    lu, wu = run_unit(fresh_mnist())
    lf, wf_ = run_fused(fresh_mnist())
    np.testing.assert_allclose(lu, lf, rtol=1e-4)
    for name in wu:
        np.testing.assert_allclose(wu[name], wf_[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_fused_data_parallel_8dev_matches_single(tmp_path):
    import jax

    root.common.dirs.snapshots = str(tmp_path)
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    from znicz_tpu.parallel.mesh import make_mesh

    l1, w1 = run_fused(fresh_mnist())
    mesh = make_mesh(axes=("data",))
    l8, w8 = run_fused(fresh_mnist(), mesh=mesh)
    np.testing.assert_allclose(l1, l8, rtol=1e-4)
    for name in w1:
        np.testing.assert_allclose(w1[name], w8[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_fused_snapshotter_fires(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    from znicz_tpu.parallel.fused import FusedTrainer

    FusedTrainer(wf).run()
    assert wf.snapshotter.destination is not None
    import os
    assert os.path.exists(wf.snapshotter.destination)


def test_fused_rejects_tied_weights(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    root.mnist_ae.loader.n_train = 100
    root.mnist_ae.loader.n_valid = 50
    root.mnist_ae.loader.minibatch_size = 50
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist_ae

    wf = mnist_ae.MnistAEWorkflow()
    wf.initialize(device=None)
    wf.forwards = [wf.conv, wf.pool, wf.depool, wf.deconv]
    wf.gds = [wf.gd_deconv, wf.gd_depool, wf.gd_pool, wf.gd_conv]
    with pytest.raises(ValueError, match="tied"):
        FusedTrainer(wf)