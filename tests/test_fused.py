"""FusedTrainer: parity with the unit-at-a-time engine, and 8-virtual-device
data parallelism (SURVEY.md §4: multi-device tests on CPU)."""

import os

import numpy as np
import pytest

from znicz_tpu.core.config import root


def fresh_mnist(max_epochs=2):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def run_unit(wf):
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    wf.run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def run_fused(wf, mesh=None, tp_threshold=None):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf, mesh=mesh)
    if tp_threshold is not None:
        trainer.tp_threshold = tp_threshold
    trainer.run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def test_fused_matches_unit_path(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    wfu = fresh_mnist()
    lu, wu = run_unit(wfu)
    wff = fresh_mnist()
    lf, wf_ = run_fused(wff)
    np.testing.assert_allclose(lu, lf, rtol=1e-4)
    for name in wu:
        np.testing.assert_allclose(wu[name], wf_[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)
    # confusion totals match exactly — the fused path accumulates the
    # confusion on DEVICE across each epoch and transfers once at the
    # tail, which must be invisible to the Decision's epoch metrics
    for klass in (1, 2):
        cu = wfu.decision.epoch_metrics[klass]["confusion"]
        cf = wff.decision.epoch_metrics[klass]["confusion"]
        np.testing.assert_array_equal(np.asarray(cu), np.asarray(cf),
                                      err_msg=f"class {klass}")
        assert np.asarray(cf).sum() > 0


def test_fused_data_parallel_8dev_matches_single(tmp_path):
    import jax

    root.common.dirs.snapshots = str(tmp_path)
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    from znicz_tpu.parallel.mesh import make_mesh

    l1, w1 = run_fused(fresh_mnist())
    mesh = make_mesh(axes=("data",))
    l8, w8 = run_fused(fresh_mnist(), mesh=mesh)
    np.testing.assert_allclose(l1, l8, rtol=1e-4)
    for name in w1:
        np.testing.assert_allclose(w1[name], w8[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def hybrid_mesh():
    """A (data=4, model=2) mesh: batch sharded over ``data``, the 100-wide
    hidden FC row-sharded over ``model`` (tp_threshold lowered to 64)."""
    from znicz_tpu.parallel.mesh import make_mesh

    return make_mesh((4, 2), ("data", "model"))


def test_fused_tp_hybrid_mesh_matches_single(tmp_path):
    """Tensor parallelism correctness: a hybrid data x model mesh must
    reproduce the single-device losses AND weights (GSPMD inserts the
    collectives; the math may not change)."""
    root.common.dirs.snapshots = str(tmp_path)
    l1, w1 = run_fused(fresh_mnist())
    lt, wt = run_fused(fresh_mnist(), mesh=hybrid_mesh(), tp_threshold=64)
    np.testing.assert_allclose(l1, lt, rtol=1e-4)
    for name in w1:
        np.testing.assert_allclose(w1[name], wt[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_fused_tp_hybrid_mesh_matches_single_bf16(tmp_path):
    """Same TP-parity property under mixed precision: bf16 on the hybrid
    mesh vs bf16 single-device (looser tolerances — bf16 collective
    reduction order differs)."""
    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.precision = "bfloat16"
    try:
        l1, w1 = run_fused(fresh_mnist())
        lt, wt = run_fused(fresh_mnist(), mesh=hybrid_mesh(),
                           tp_threshold=64)
    finally:
        root.common.engine.precision = "float32"
    np.testing.assert_allclose(l1, lt, rtol=5e-2)
    assert lt[-1] < lt[0] * 0.9, lt             # and it actually trains
    for name in w1:
        np.testing.assert_allclose(w1[name], wt[name], rtol=5e-2,
                                   atol=5e-3, err_msg=name)


def test_fused_snapshot_restore_continue(tmp_path):
    """Restore-then-continue UNDER FusedTrainer: velocities + prng streams
    must round-trip, and the continued trajectory must match the unit
    engine continuing from the very same snapshot."""
    from znicz_tpu import snapshotter as snap_mod
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist
    from znicz_tpu.snapshotter import Snapshotter

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist(max_epochs=2)
    FusedTrainer(wf).run()
    path = wf.snapshotter.destination
    assert path is not None
    snap = Snapshotter.load(path)

    def resume(engine):
        prng.reset(1013)
        root.mnist.decision.max_epochs = 4           # 2 more epochs
        losses = []
        wf2 = mnist.MnistWorkflow()
        wf2.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        wf2.initialize(device=None)
        snap_mod.restore(wf2, snap)
        if engine == "fused":
            trainer = FusedTrainer(wf2)
            # restored velocities must be what the trainer picks up
            for name, layer in trainer.extract_velocities().items():
                gd_name = trainer.gd_of[name].name
                for k, v in layer.items():
                    np.testing.assert_allclose(
                        np.asarray(v), snap["velocities"][gd_name][k],
                        err_msg=f"{gd_name}.{k}")
            trainer.run()
        else:
            wf2.run()
        assert bool(wf2.decision.complete)
        return losses, {f.name: np.array(f.weights.map_read())
                        for f in wf2.forwards}

    lf, wf_f = resume("fused")
    lu, wf_u = resume("unit")
    assert len(lf) >= 2 and len(lf) == len(lu)       # continuation ran
    np.testing.assert_allclose(lf, lu, rtol=1e-4)
    for name in wf_u:
        np.testing.assert_allclose(wf_u[name], wf_f[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_bf16_state_dtype_parity_mnist(tmp_path):
    """root.common.engine.state_dtype="bfloat16" stores optimizer
    velocities in bf16 (HBM-traffic lever, VERDICT r3 item 3a); update
    math stays f32.  Documented semantics: the velocity is quantized once
    per step — loss curves must track f32 within tolerance and training
    must clearly progress."""
    root.common.dirs.snapshots = str(tmp_path)
    l32, w32 = run_fused(fresh_mnist(max_epochs=3))
    root.common.engine.state_dtype = "bfloat16"
    try:
        wf = fresh_mnist(max_epochs=3)
        from znicz_tpu.parallel.fused import FusedTrainer

        losses = []
        wf.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        trainer = FusedTrainer(wf)
        for gd in wf.gds:
            for k, a in gd._velocities.items():
                assert str(a.dtype) == "bfloat16", (gd.name, k, a.dtype)
        trainer.run()
    finally:
        root.common.engine.state_dtype = "float32"
    np.testing.assert_allclose(l32, losses, rtol=2e-2)
    assert losses[-1] < losses[0] * 0.7


@pytest.mark.slow
def test_bf16_state_dtype_parity_cifar(tmp_path):
    """Same property on the CIFAR anchor (conv net, the BASELINE
    config[1] gate): bf16 velocities track the f32 trajectory and the
    anchor's beats-chance bar still holds.

    Slow-marked (ISSUE 7 budget discipline): the property itself stays
    tier-1 via the mnist twin above; this conv-anchor re-run cost ~70s
    of a budget the suite had outgrown."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import cifar

    root.cifar.loader.n_train = 300
    root.cifar.loader.n_valid = 100
    root.cifar.loader.n_test = 0
    root.cifar.loader.minibatch_size = 50
    root.cifar.decision.max_epochs = 4
    root.common.dirs.snapshots = str(tmp_path)

    def run_once():
        prng.reset(1013)
        wf = cifar.CifarWorkflow()
        losses = []
        wf.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        wf.initialize(device=None)
        from znicz_tpu.parallel.fused import FusedTrainer

        FusedTrainer(wf).run()
        return losses

    l32 = run_once()
    root.common.engine.state_dtype = "bfloat16"
    try:
        lb = run_once()
    finally:
        root.common.engine.state_dtype = "float32"
    np.testing.assert_allclose(l32, lb, rtol=5e-2)
    # 4 shrunk epochs move the conv net ~9% down the curve; the parity
    # assert above is the real gate, this is just "it trains at all"
    assert lb[-1] < lb[0] * 0.95


def test_cross_topology_checkpoint_resume(tmp_path):
    """SHARDED orbax save under a {data:4, model:2} mesh, restored onto a
    {data:8} mesh AND onto a single device (VERDICT r3 item 5): orbax
    delivers every leaf already placed in the restoring trainer's
    shardings, and both continued trajectories match uninterrupted
    training."""
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.parallel.mesh import make_mesh
    from znicz_tpu.samples import mnist

    root.common.dirs.snapshots = str(tmp_path)
    lo, wo = run_fused(fresh_mnist(max_epochs=4))    # uninterrupted oracle

    # phase 1: train on the hybrid mesh; the snapshotter writes a SHARDED
    # orbax checkpoint MID-RUN at the end of epoch 1 (interval=2) — the
    # preemption-resume scenario.  (An end-of-run checkpoint could never
    # match uninterrupted training: the stop semantics deliberately skip
    # the final tail update.)
    root.mnist.snapshotter.interval = 2
    try:
        wf = fresh_mnist(max_epochs=4)
    finally:
        root.mnist.snapshotter.interval = 0
    wf.snapshotter.format = "orbax"
    wf.snapshotter.sharded = True
    trainer = FusedTrainer(wf, mesh=hybrid_mesh())
    trainer.tp_threshold = 64
    trainer.run()
    path = str(tmp_path / "mnist_epoch_1.orbax")
    assert os.path.isdir(path), os.listdir(tmp_path)
    # the saved leaves really were the live sharded device arrays
    w = wf.forwards[0].weights.devmem
    assert len(w.sharding.device_set) == 8, w.sharding

    def resume(mesh, tp_threshold=None):
        prng.reset(1013)
        root.mnist.decision.max_epochs = 4
        losses = []
        wf2 = mnist.MnistWorkflow()
        wf2.decision.on_epoch_end.append(
            lambda d: losses.append(d.epoch_metrics[2]["loss"]))
        wf2.initialize(device=None)
        tr = FusedTrainer(wf2, mesh=mesh)
        if tp_threshold is not None:
            tr.tp_threshold = tp_threshold
        tr.restore_sharded(path)
        # leaves arrive placed per the RESTORING topology
        w2 = wf2.forwards[0].weights.devmem
        n_dev = len(w2.sharding.device_set)
        assert n_dev == (1 if mesh is None else mesh.devices.size), \
            w2.sharding
        tr.run()
        assert bool(wf2.decision.complete)
        return losses, {f.name: np.array(f.weights.map_read())
                        for f in wf2.forwards}

    l8, w8 = resume(make_mesh(axes=("data",)))       # reshard 4x2 -> 8
    l1, w1 = resume(None)                            # reshard -> one device
    assert len(l8) == 2 and len(l1) == 2             # epochs 2..3 ran
    np.testing.assert_allclose(l8, l1, rtol=1e-4)    # topology-invariant
    np.testing.assert_allclose(l1, lo[2:], rtol=1e-3)  # matches oracle
    for name in w1:
        np.testing.assert_allclose(w1[name], wo[name], rtol=5e-3,
                                   atol=5e-5, err_msg=name)
        np.testing.assert_allclose(w8[name], w1[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_fused_snapshotter_fires(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    from znicz_tpu.parallel.fused import FusedTrainer

    FusedTrainer(wf).run()
    assert wf.snapshotter.destination is not None
    import os
    assert os.path.exists(wf.snapshotter.destination)


def test_fused_rejects_tied_weights(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    root.mnist_ae.loader.n_train = 100
    root.mnist_ae.loader.n_valid = 50
    root.mnist_ae.loader.minibatch_size = 50
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist_ae

    wf = mnist_ae.MnistAEWorkflow()
    wf.initialize(device=None)
    wf.forwards = [wf.conv, wf.pool, wf.depool, wf.deconv]
    wf.gds = [wf.gd_deconv, wf.gd_depool, wf.gd_pool, wf.gd_conv]
    with pytest.raises(ValueError, match="tied"):
        FusedTrainer(wf)

def test_fused_stats_observability(tmp_path):
    """The fast path reports per-step timing (VERDICT r2 item 3): stats
    accumulate in FusedTrainer.run, appear in Workflow.print_stats and in
    the web_status snapshot."""
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.web_status import WebStatus

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    trainer = FusedTrainer(wf)
    trainer.run()
    s = trainer.stats
    assert s["train_steps"] > 0 and s["eval_steps"] > 0
    assert s["images"] >= s["train_steps"]       # >= 1 image per step
    assert s["wall_s"] > 0 and s["steps_per_sec"] > 0
    assert s["img_per_sec"] > 0 and s["last_step_ms"] > 0
    # warm numbers exclude each variant's first (compiling) dispatch
    assert s["warm_steps"] > 0
    assert s["warm_steps"] < s["train_steps"] + s["eval_steps"]
    assert s["warm_img_per_sec"] > s["img_per_sec"]
    assert wf.fused_stats is s
    table = wf.print_stats()
    assert "steps/s" in table and "img/s" in table
    assert "warm (excl. compiles)" in table

    status = WebStatus(port=0).start()
    try:
        status.register(wf)
        snap = status.snapshot()
        info = next(w for w in snap["workflows"] if w["name"] == wf.name)
        assert info["fused"]["train_steps"] == s["train_steps"]
    finally:
        status.stop()


def test_fused_remat_matches(tmp_path):
    """jax.checkpoint rematerialization changes memory, not math: loss
    curves and final weights match the non-remat fused run."""
    root.common.dirs.snapshots = str(tmp_path)
    lf, wf_ = run_fused(fresh_mnist())

    from znicz_tpu.parallel.fused import FusedTrainer

    wf2 = fresh_mnist()
    losses2 = []
    wf2.decision.on_epoch_end.append(
        lambda d: losses2.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf2, remat=True)
    assert trainer.remat is True
    trainer.run()
    np.testing.assert_allclose(lf, losses2, rtol=1e-5)
    for f in wf2.forwards:
        np.testing.assert_allclose(np.array(f.weights.map_read()),
                                   wf_[f.name], rtol=1e-4, atol=1e-6,
                                   err_msg=f.name)


def test_fused_eval_segments_respect_class_boundary(tmp_path):
    """With both TEST and VALID sets, per-class confusion must match the
    unit path exactly — eval scan segments may not span the class
    boundary (their summed confusion is booked to the first class)."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    def build():
        prng.reset(1013)
        root.mnist.loader.n_train = 300
        root.mnist.loader.n_valid = 120
        root.mnist.loader.n_test = 120
        root.mnist.loader.minibatch_size = 60
        root.mnist.decision.max_epochs = 2
        root.common.dirs.snapshots = str(tmp_path)
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        return wf

    try:
        wfu = build()
        wfu.run()
        wff = build()
        from znicz_tpu.parallel.fused import FusedTrainer

        FusedTrainer(wff).run()
        for klass in (0, 1, 2):
            cu = np.asarray(wfu.decision.epoch_metrics[klass]["confusion"])
            cf = np.asarray(wff.decision.epoch_metrics[klass]["confusion"])
            np.testing.assert_array_equal(cu, cf, err_msg=f"class {klass}")
            assert cf.sum() > 0
    finally:
        root.mnist.loader.n_test = 0


def test_fused_train_only_epoch_hook_once_per_epoch(tmp_path):
    """Train-only workflows (no TEST/VALID): the epoch-end hook must fire
    exactly once per epoch — a stale epoch_ended flag used to re-run it
    after the next epoch's first pipelined segment."""
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 0
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 3
    root.common.dirs.snapshots = str(tmp_path)
    try:
        wf = mnist.MnistWorkflow()
        wf.initialize(device=None)
        calls = []
        # a due epoch goes through run() (sync) or tags_for()+save_async
        # (r5 async default) — count the hook either way
        wf.snapshotter.run = lambda: calls.append("sync")
        orig_tags = wf.snapshotter.tags_for
        wf.snapshotter.tags_for = \
            lambda e, i: (calls.append("async"), orig_tags(e, i))[1]
        wf.snapshotter.gate_skip.set(False)
        FusedTrainer(wf).run()
        assert bool(wf.decision.complete)
        assert len(calls) == 3, calls       # once per epoch, not more
    finally:
        root.mnist.loader.n_valid = 60


def test_fused_wall_time_not_double_counted(tmp_path):
    """Pipelined accounting must charge non-overlapping intervals:
    stats wall_s may not exceed true elapsed time."""
    import time as _t

    from znicz_tpu.parallel.fused import FusedTrainer

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist()
    trainer = FusedTrainer(wf)
    t0 = _t.perf_counter()
    trainer.run()
    elapsed = _t.perf_counter() - t0
    assert trainer.stats["wall_s"] <= elapsed * 1.02 + 0.01, \
        (trainer.stats["wall_s"], elapsed)


def test_fused_writeback_need_driven(tmp_path):
    """Epoch-end device->host writeback is paid only when a consumer will
    use it that epoch (a due snapshot or a wired plotter) — never as an
    unconditional per-epoch tax (VERDICT r3 weak #3).  One final
    writeback always lands the trained weights in the unit Arrays."""
    from znicz_tpu.parallel.fused import FusedTrainer

    root.common.dirs.snapshots = str(tmp_path)

    def counting(trainer):
        calls = []
        orig = trainer.writeback
        trainer.writeback = lambda p, v: (calls.append(1), orig(p, v))[1]
        return calls

    # no consumers: snapshotter gated off, no plotters -> exactly one
    # (final) writeback over the whole run
    wf = fresh_mnist(max_epochs=3)
    wf.snapshotter.gate_skip.set(True)
    tr = FusedTrainer(wf)
    calls = counting(tr)
    tr.run()
    assert len(calls) == 1, calls
    final_loss = wf.decision.epoch_metrics[2]["loss"]

    # snapshotter active, r5 ASYNC default: snapshots go through
    # snapshot_from_trees + the background writer — NO writeback at all
    # beyond the final one, and the snapshots still land
    wf2 = fresh_mnist(max_epochs=3)
    tr2 = FusedTrainer(wf2)
    calls2 = counting(tr2)
    tr2.run()
    assert wf2.snapshotter.async_saves_written > 0
    assert len(calls2) == 1, calls2
    np.testing.assert_allclose(final_loss,
                               wf2.decision.epoch_metrics[2]["loss"],
                               rtol=1e-6)

    # async off (sync fallback): one writeback per epoch that actually
    # saves, plus the final one; and the snapshotter changed no math
    root.common.engine.async_snapshot = False
    try:
        wf3 = fresh_mnist(max_epochs=3)
        tr3 = FusedTrainer(wf3)
        calls3 = counting(tr3)
        saves = []
        orig_save = wf3.snapshotter.save
        wf3.snapshotter.save = lambda tag: (saves.append(tag),
                                            orig_save(tag))[1]
        tr3.run()
    finally:
        root.common.engine.async_snapshot = True
    assert saves, "best-only snapshotter never fired"
    assert len(calls3) == len(saves) + 1, (calls3, saves)
    np.testing.assert_allclose(final_loss,
                               wf3.decision.epoch_metrics[2]["loss"],
                               rtol=1e-6)


def test_fused_confusion_wide_head_always_on(tmp_path):
    """Heads wider than the unit path's 128-class auto-off still get an
    exact per-epoch confusion matrix on the fused path: the sum lives on
    device and is transferred only when the metric is read (VERDICT r3
    missing #4)."""
    from znicz_tpu.core import prng
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import kanji

    n_classes = 160
    prng.reset(1013)
    root.kanji.loader.n_train = 320
    root.kanji.loader.n_valid = 160
    root.kanji.loader.n_classes = n_classes
    root.kanji.loader.minibatch_size = 80
    root.kanji.decision.max_epochs = 2
    root.common.dirs.snapshots = str(tmp_path)
    wf = kanji.KanjiWorkflow()
    wf.initialize(device=None)
    # the unit path's auto-off resolved OFF for this width...
    assert wf.evaluator.compute_confusion is False
    trainer = FusedTrainer(wf)
    # ...but the fused path collects anyway (device-side accumulation)
    assert trainer.compute_confusion is True
    trainer.run()
    for klass, total in ((1, 160), (2, 320)):
        conf = np.asarray(wf.decision.epoch_metrics[klass]["confusion"])
        assert conf.shape == (n_classes, n_classes)
        assert conf.sum() == total, (klass, conf.sum())
        # column sums = per-class sample counts of that split
        labels = np.asarray(wf.loader.original_labels.mem)
        lo, hi = wf.loader.class_end_offsets[klass - 1], \
            wf.loader.class_end_offsets[klass]
        hist = np.bincount(labels[lo:hi], minlength=n_classes)
        np.testing.assert_array_equal(conf.sum(axis=0), hist,
                                      err_msg=f"class {klass}")


def test_engine_fused_fallback_specific_and_logged(tmp_path):
    """--fused falls back to the unit engine ONLY for the dedicated
    FusedUnsupportedError (tied weights), with a warning; unrelated
    ValueErrors propagate (ADVICE r3)."""
    import logging

    from znicz_tpu import engine
    from znicz_tpu.parallel import fused as fused_mod

    root.common.dirs.snapshots = str(tmp_path)
    root.common.engine.fused = True
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r)
    logging.getLogger("znicz").addHandler(handler)
    try:
        wf = fresh_mnist(max_epochs=1)
        orig_init = fused_mod.FusedTrainer.__init__

        def boom(self, *a, **kw):
            raise fused_mod.FusedUnsupportedError("tied weights (test)")

        fused_mod.FusedTrainer.__init__ = boom
        try:
            engine.train(wf)                     # falls back, trains
            assert bool(wf.decision.complete)
            assert any("falling back" in r.getMessage()
                       for r in records), records
        finally:
            fused_mod.FusedTrainer.__init__ = orig_init

        def boom2(self, *a, **kw):
            raise ValueError("unrelated misconfiguration")

        fused_mod.FusedTrainer.__init__ = boom2
        try:
            with pytest.raises(ValueError, match="unrelated"):
                engine.train(fresh_mnist(max_epochs=1))
        finally:
            fused_mod.FusedTrainer.__init__ = orig_init
    finally:
        root.common.engine.fused = False
        logging.getLogger("znicz").removeHandler(handler)


def run_fused_depth(wf, depth, mesh=None):
    from znicz_tpu.parallel.fused import FusedTrainer

    wf.snapshotter.gate_skip.set(True)     # deep needs no epoch consumers
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf, mesh=mesh)
    trainer.pipeline_depth = depth
    trainer.run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}, trainer


def test_fused_deep_pipeline_matches_legacy(tmp_path):
    """pipeline_depth > 1 (whole-epoch dispatches, metrics deferred up to
    depth epochs) is a host-sync optimization, not a semantics change:
    losses, weights, confusion and decision state match the per-segment
    path exactly (VERDICT r4 product-path work)."""
    root.common.dirs.snapshots = str(tmp_path)
    wf1 = fresh_mnist(max_epochs=4)
    l1, w1, _ = run_fused_depth(wf1, 1)
    wf3 = fresh_mnist(max_epochs=4)
    l3, w3, _ = run_fused_depth(wf3, 3)
    np.testing.assert_allclose(l1, l3, rtol=1e-5)
    for name in w1:
        np.testing.assert_allclose(w1[name], w3[name], rtol=1e-4,
                                   atol=1e-6, err_msg=name)
    for klass in (1, 2):
        np.testing.assert_array_equal(
            np.asarray(wf1.decision.epoch_metrics[klass]["confusion"]),
            np.asarray(wf3.decision.epoch_metrics[klass]["confusion"]),
            err_msg=f"class {klass}")
    assert wf1.decision.epoch_number == wf3.decision.epoch_number
    np.testing.assert_allclose(wf1.decision.best_metric,
                               wf3.decision.best_metric)
    # step accounting parity: eval minibatches book under eval_steps in
    # BOTH sync profiles (the deep flush must not count them as train)
    s1, s3 = wf1.fused_stats, wf3.fused_stats
    assert s1["train_steps"] == s3["train_steps"], (s1, s3)
    assert s1["eval_steps"] == s3["eval_steps"], (s1, s3)
    assert s1["images"] == s3["images"]


def test_fused_deep_pipeline_failstop_rollback(tmp_path):
    """A fail_iterations stop lands mid-speculation (later epochs already
    dispatched): the deep path must recompute the exact stopping state —
    tail update not adopted, speculated epochs discarded, host-side
    loader/step bookkeeping rewound — matching the per-segment path."""
    root.common.dirs.snapshots = str(tmp_path)
    root.mnist.learning_rate = 1e-4        # barely moves -> fails-stop
    try:
        def build():
            wf = fresh_mnist(max_epochs=50)
            wf.decision.fail_iterations = 2
            return wf

        wf1 = build()
        l1, w1, t1 = run_fused_depth(wf1, 1)
        assert len(l1) < 50, "did not stop early"
        wf4 = build()
        l4, w4, t4 = run_fused_depth(wf4, 4)
        np.testing.assert_allclose(l1, l4, rtol=1e-5)
        for name in w1:
            np.testing.assert_allclose(w1[name], w4[name], rtol=1e-4,
                                       atol=1e-7, err_msg=name)
        assert t1.steps_done == t4.steps_done
        assert wf1.loader.epoch_number == wf4.loader.epoch_number
        assert wf1.loader.samples_served == wf4.loader.samples_served
    finally:
        root.mnist.learning_rate = 0.1


def test_fused_deep_pipeline_respects_consumers(tmp_path):
    """Epoch-granular host consumers vs the deep path (r5 revision): an
    ACTIVE host-format snapshotter no longer forces segmented mode — the
    deep pipeline serves it at flush boundaries through the async writer
    (VERDICT r4 weak #3) and a checkpoint IS written.  Consumers the
    async writer cannot serve (plotters; async_snapshot=False; orbax
    format, a collective save) still disable deep mode."""
    from znicz_tpu.parallel.fused import FusedTrainer

    root.common.dirs.snapshots = str(tmp_path)
    wf = fresh_mnist(max_epochs=3)
    trainer = FusedTrainer(wf)
    trainer.pipeline_depth = 4
    assert trainer._deep_eligible()        # active snapshotter: deep OK
    trainer.run()
    assert wf.snapshotter.destination is not None
    assert os.path.exists(wf.snapshotter.destination)
    assert wf.snapshotter.async_saves_written > 0

    # async off -> segmented fallback
    root.common.engine.async_snapshot = False
    try:
        wf2 = fresh_mnist(max_epochs=3)
        t2 = FusedTrainer(wf2)
        t2.pipeline_depth = 4
        assert not t2._deep_eligible()
    finally:
        root.common.engine.async_snapshot = True

    # orbax format (collective save) -> segmented fallback
    wf3 = fresh_mnist(max_epochs=3)
    wf3.snapshotter.format = "orbax"
    t3 = FusedTrainer(wf3)
    t3.pipeline_depth = 4
    assert not t3._deep_eligible()

    # plotters still disable deep mode
    wf4 = fresh_mnist(max_epochs=3)
    wf4.plotters = [object()]
    t4 = FusedTrainer(wf4)
    t4.pipeline_depth = 4
    assert not t4._deep_eligible()


def test_fused_lr_schedule_matches_unit_path(tmp_path):
    """An LR schedule wired by StandardWorkflow (lr_adjust_config) must
    drive the fused path exactly like the graph engine (the fast path
    used to ignore LearningRateAdjust silently) — per-step hypers ride
    the scan as xs."""
    from znicz_tpu.core import prng
    from znicz_tpu.samples.mnist import MnistLoader
    from znicz_tpu.standard_workflow import StandardWorkflow

    def with_schedule():
        prng.reset(1013)
        root.mnist.loader.n_train = 300
        root.mnist.loader.n_valid = 60
        root.mnist.loader.n_test = 0
        root.mnist.loader.minibatch_size = 60
        root.common.dirs.snapshots = str(tmp_path)
        gd = {"learning_rate": 0.1, "gradient_moment": 0.9}
        wf = StandardWorkflow(
            name="MnistStdLR",
            loader=MnistLoader(name="loader", minibatch_size=60),
            layers=[{"type": "all2all_tanh",
                     "->": {"output_sample_shape": 100}, "<-": dict(gd)},
                    {"type": "softmax",
                     "->": {"output_sample_shape": 10}, "<-": dict(gd)}],
            loss_function="softmax",
            decision_config={"max_epochs": 3},
            lr_adjust_config={"policy": "exp", "gamma": 0.9})
        wf.initialize(device=None)
        return wf

    lu, wu = run_unit(with_schedule())
    wff = with_schedule()
    lf, wf_ = run_fused(wff)
    assert len(lu) == len(lf) == 3
    np.testing.assert_allclose(lu, lf, rtol=1e-4)
    for name in wu:
        np.testing.assert_allclose(wu[name], wf_[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)
    # the schedule really advanced: 3 epochs x 5 train steps, minus the
    # final tail (gd_skip gates both the update and the adjust once
    # `complete` flips — identical in both engines)
    assert wff.lr_adjust.iteration == 14
    np.testing.assert_allclose(wff.gds[0].learning_rate,
                               0.1 * 0.9 ** 13, rtol=1e-6)
