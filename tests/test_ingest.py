"""Host ingest engine (loader/ingest.py — VERDICT r4 item 1): parallel
decode must be BIT-IDENTICAL to serial decode, the prefetch cache must be
bounded and actually hit (the staging queue stays non-empty in steady
state), and the fused streaming run over an image-file source must train
the same trajectory with 8 workers as with 0."""

import os

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader.ingest import (DecodePool, default_workers,
                                     measure_decode_rate)
from znicz_tpu.loader.streaming import StreamingLoader, class_dir_source

from tests.test_streaming import _write_class_tree


def _tree(tmp_path, n_per_class=8, size=(12, 12)):
    base = str(tmp_path / "imgs")
    os.makedirs(base)
    _write_class_tree(base, n_per_class=n_per_class, size=size)
    return base


def test_pooled_decode_matches_serial(tmp_path):
    """Same files, same indices (duplicates included — padded tails repeat
    their last index): 8 decode workers produce the exact bytes the serial
    path does, in the exact order."""
    base = _tree(tmp_path)
    serial = class_dir_source(base, target_shape=(10, 11), workers=0)
    pooled = class_dir_source(base, target_shape=(10, 11), workers=8)
    idx = np.array([3, 0, 7, 3, 3, 12, 1, 0], np.int32)
    np.testing.assert_array_equal(serial.gather(idx), pooled.gather(idx))
    # and again after prefetch seeded the cache
    pooled.prefetch(np.array([5, 6, 2], np.int32))
    idx2 = np.array([5, 2, 6, 5, 9], np.int32)
    np.testing.assert_array_equal(serial.gather(idx2), pooled.gather(idx2))


def test_decode_pool_cache_and_bounds():
    """DecodePool contract: prefetched rows are served as hits and popped
    on consumption; the outstanding-row cap bounds the cache; duplicate
    takes decode once."""
    calls = []

    def decode(i):
        calls.append(i)
        return np.full((2, 2), i, np.uint8)

    pool = DecodePool(decode, workers=2, max_outstanding_rows=4)
    assert pool.submit([0, 1, 2]) == 3
    assert pool.submit([2, 3, 4, 5]) == 1          # 2 dup-skipped; cap at 4
    assert pool.outstanding_rows == 4
    rows = pool.take([0, 1, 1, 1, 2, 3, 4])        # 4 was never submitted
    np.testing.assert_array_equal(rows[:, 0, 0],
                                  np.array([0, 1, 1, 1, 2, 3, 4]))
    st = pool.stats
    assert st["prefetch_hits"] == 4                # 0,1,2,3
    assert st["decode_misses"] == 1                # 4 (dups of 1 are free)
    assert pool.outstanding_rows == 0              # popped on consumption
    assert sorted(calls) == [0, 1, 2, 3, 4]        # each row decoded once
    pool.close()


def test_default_workers_config_override():
    try:
        root.common.engine.decode_workers = 3
        assert default_workers() == 3
    finally:
        root.common.engine.decode_workers = None
    assert default_workers() >= 1


def _build_stream_wf(src, max_epochs=2):
    from znicz_tpu.all2all import All2AllSoftmax
    from znicz_tpu.core.workflow import Repeater, Workflow
    from znicz_tpu.decision import DecisionGD
    from znicz_tpu.evaluator import EvaluatorSoftmax
    from znicz_tpu.gd import GDSoftmax

    class WF(Workflow):
        def __init__(self):
            super().__init__(name="IngestWF")
            self.repeater = Repeater(self, name="repeater")
            self.repeater.link_from(self.start_point)
            self.loader = StreamingLoader(
                self, name="loader", source=src, minibatch_size=4,
                class_lengths=[0, 4, 12], device_budget_bytes=0)
            self.loader.link_from(self.repeater)
            fwd = All2AllSoftmax(self, name="fwd0",
                                 output_sample_shape=(2,))
            fwd.link_from(self.loader)
            fwd.link_attrs(self.loader, ("input", "minibatch_data"))
            self.forwards = [fwd]
            self.evaluator = EvaluatorSoftmax(self, name="evaluator",
                                              n_classes=2)
            self.evaluator.link_from(fwd)
            self.evaluator.link_attrs(fwd, "output")
            self.evaluator.link_attrs(
                self.loader, ("labels", "minibatch_labels"),
                ("batch_size", "minibatch_size"))
            self.decision = DecisionGD(self, name="decision",
                                       max_epochs=max_epochs)
            self.decision.link_from(self.evaluator)
            self.decision.link_attrs(
                self.loader, "minibatch_class", "last_minibatch",
                "class_ended", "epoch_number", "class_lengths",
                "minibatch_size")
            self.decision.link_attrs(
                self.evaluator, ("minibatch_loss", "loss"),
                ("minibatch_n_err", "n_err"), "confusion_matrix",
                "max_err_output_sum")
            gd = GDSoftmax(self, name="gd0", forward=fwd,
                           learning_rate=0.05, need_err_input=False)
            gd.link_from(self.decision)
            gd.link_attrs(self.evaluator, ("err_output", "err_output"))
            gd.gate_skip = self.decision.gd_skip
            self.gds = [gd]
            self.repeater.link_from(gd)
            self.end_point.link_from(self.decision)
            self.end_point.gate_block = ~self.decision.complete

    wf = WF()
    wf.initialize(device=None)
    return wf


def _run_stream(base, workers, max_epochs=2):
    from znicz_tpu.parallel.fused import FusedTrainer

    prng.reset(4242)
    src = class_dir_source(base, target_shape=(12, 12), workers=workers)
    wf = _build_stream_wf(src, max_epochs=max_epochs)
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    FusedTrainer(wf).run()
    weights = {f.name: np.array(f.weights.map_read())
               for f in wf.forwards}
    return losses, weights, wf.loader.ingest_stats


def test_fused_streaming_prefetch_parity_and_hits(tmp_path):
    """The e2e ingest proof (VERDICT r4 item 1 'done' criteria): a fused
    image-file streaming run with a decode pool (a) trains bit-for-bit the
    trajectory of the serial-decode run, and (b) keeps the staging queue
    non-empty — after the first segment every staged row is served by an
    already-submitted decode future (prefetch hit), not an on-demand miss."""
    base = _tree(tmp_path)
    l0, w0, st0 = _run_stream(base, workers=0)
    assert st0 is None                        # serial path has no pool
    l1, w1, st1 = _run_stream(base, workers=4)
    np.testing.assert_array_equal(l0, l1)
    for k in w0:
        np.testing.assert_array_equal(w0[k], w1[k])
    assert st1 is not None
    assert st1["prefetch_hits"] > 0
    # only the run's very first staged segment may miss (its minibatches
    # were advanced before any lookahead existed); with minibatch_size 4
    # that bounds misses at one padded eval batch — everything after is
    # fed from the prefetch queue at the training step rate
    assert st1["decode_misses"] <= 4, st1
    total = st1["prefetch_hits"] + st1["decode_misses"]
    assert st1["prefetch_hits"] >= total - 4


# -- async double-buffered device staging (ISSUE 7) ----------------------------


def test_device_stager_contract():
    """DeviceStager unit contract: a submitted key is served as a hit
    (result identity preserved), an unknown key assembles inline as a
    miss, a prediction still pending from one miss to the NEXT miss is
    stale and evicted (it would otherwise pin its ping-pong slot
    forever — but a single miss must not evict, or the cold-start take
    would throw away the correct predictions staged behind it), the
    ping-pong bound caps outstanding work, and close() clears pending."""
    import time

    from znicz_tpu.loader.ingest import DeviceStager

    calls = []

    def assemble(rows):
        calls.append(len(rows))
        time.sleep(0.01)
        return ("staged", DeviceStager.key_of(rows))

    st = DeviceStager(assemble, depth=2)
    a = [np.array([0, 1], np.int32)]
    b = [np.array([2, 3], np.int32), np.array([4, 5], np.int32)]
    c = [np.array([6, 7], np.int32)]
    assert st.submit(a) and st.submit(b)
    assert not st.submit(a)                      # dup-skipped
    assert not st.submit(c)                      # ping-pong full
    assert st.outstanding == 2
    out = st.take(a)                             # hit
    assert out == ("staged", DeviceStager.key_of(a))
    assert st.outstanding == 1
    out = st.take(c)                             # never staged: inline miss
    assert out == ("staged", DeviceStager.key_of(c))
    # first miss: b is only MARKED stale, not evicted (cold-start rule)
    assert st.outstanding == 1
    s = st.stats()
    assert s["stage_hits"] == 1 and s["stage_misses"] == 1
    assert s["stage_evictions"] == 0
    d = [np.array([8, 9], np.int32)]
    out = st.take(d)                             # second miss: b is stale
    assert out == ("staged", DeviceStager.key_of(d))
    assert st.outstanding == 0                   # ...evicted, slot freed
    s = st.stats()
    assert s["stage_misses"] == 2 and s["stage_evictions"] == 1
    assert len(calls) == 4                       # a, b, c, d each once
    assert st.submit(a)                          # the slot is usable again
    assert st.take(a) == ("staged", DeviceStager.key_of(a))
    st.close()
    assert st.outstanding == 0


def test_ingest_overlap_gate_lean():
    """ISSUE 7 structural overlap gate, lean tier-1 version (the soak
    below and ``bench.py --ingest`` run the full protocol): a fixed delay
    injected into the decode path is absorbed by the double buffer — the
    training thread's staged-segment waits stay well under it except at
    the structurally-unhidable epoch boundaries (see
    bench.check_ingest_overlap)."""
    from bench import check_ingest_overlap, run_ingest_overlap

    vals = run_ingest_overlap(hidden=128, n_train=160, n_valid=32,
                              mb=32, max_epochs=2, with_off=False)
    bad = check_ingest_overlap(vals, max_epochs=2)
    assert not bad, (bad, vals)
    # the injected delay really was paid by SOMEONE (the stager worker):
    # every staged segment's assembly slept it
    assert vals["stager"]["h2d_ms_p50"] >= vals["delay_ms"]


@pytest.mark.slow
def test_ingest_overlap_gate_soak():
    """The full --ingest protocol (bench-sized model, three epochs, the
    async-off context run included): gate must hold and async-on must
    not be slower than async-off."""
    from bench import check_ingest_overlap, run_ingest_overlap

    vals = run_ingest_overlap(max_epochs=3)
    bad = check_ingest_overlap(vals, max_epochs=3)
    assert not bad, (bad, vals)
    assert vals["on_vs_off"] is not None and vals["on_vs_off"] > 0.9, vals


def test_measure_decode_rate(tmp_path):
    """The roofline's third term: measured, finite, and the pool is not
    CATASTROPHICALLY slower than serial (the bench records both).

    DE-FLAKE + CALIBRATION (r10): the old single-shot ``pooled >= 0.6 *
    serial`` band assumed parallel headroom this host does not reliably
    have — on a 2-cpu box whose cgroup share swings minute to minute, a
    4-worker pool legitimately measures down to ~0.3x serial under an
    external load burst (oversubscription, not a pool bug), and wall
    time cannot distinguish that from a real regression, so the band
    flaked in-suite.  Now: workers match the host's cpu count, pairs
    are interleaved best-of with early exit (PR-4/PR-5 doctrine), and
    the band is 0.25x — wide enough to sit above the oversubscription
    floor, while the regression CLASS this guard exists for (the pool
    deadlocking, or rebuilding per item — 10x-100x collapses) still
    fails every round.  The pool's true speedup on capable hosts is
    recorded by ``bench.py --stream``'s decode term."""
    import os

    base = _tree(tmp_path, n_per_class=16, size=(32, 32))
    src = class_dir_source(base, target_shape=(24, 24), workers=0)
    n_workers = max(2, min(4, os.cpu_count() or 1))
    serial = pooled = 0.0
    for _ in range(3):
        serial = max(serial, measure_decode_rate(src, n=32))
        pooled = max(pooled, measure_decode_rate(src, n=32,
                                                 workers=n_workers))
        assert np.isfinite(serial) and serial > 0
        assert np.isfinite(pooled) and pooled > 0
        if pooled >= 0.25 * serial:
            break
    assert pooled >= 0.25 * serial, (serial, pooled)
