"""Multi-host checkpoint/resume END-TO-END (VERDICT r4 item 3 / missing
#3): a 2-OS-process {data:8} run snapshots a SHARDED orbax checkpoint
mid-run, both workers die, a fresh 2-process run restores it and
finishes — and the same checkpoint also restores single-process.  Both
continued trajectories must match the uninterrupted 2-process oracle
within the tolerances of tests/test_fused.py's cross-topology test."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one worker, two phases:
#:   train  — run 4 epochs; the snapshotter writes a sharded orbax
#:            checkpoint at the end of epoch 1 (interval=2) — the
#:            preemption point; the process then runs to completion and
#:            reports the UNINTERRUPTED trajectory (the oracle)
#:   resume — fresh process: build, restore_sharded, continue to 4
WORKER = textwrap.dedent("""\
    import json
    import sys

    from znicz_tpu.virtdev import provision_cpu_devices

    provision_cpu_devices(4, verify=False)
    from znicz_tpu.parallel.mesh import distributed_init, make_mesh

    phase, pid, n, port, snapdir = (sys.argv[1], int(sys.argv[2]),
                                    int(sys.argv[3]), sys.argv[4],
                                    sys.argv[5])
    distributed_init(coordinator=f"127.0.0.1:{port}",
                     num_processes=n, process_id=pid)
    import numpy as np

    import jax

    assert jax.process_count() == n

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.common.dirs.snapshots = snapdir
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 4
    if phase == "train":
        root.mnist.snapshotter.interval = 2
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    wf.snapshotter.format = "orbax"
    wf.snapshotter.sharded = True

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    mesh = make_mesh(axes=("data",))
    assert mesh.shape["data"] == 4 * n
    trainer = FusedTrainer(wf, mesh=mesh)
    if phase == "train":
        trainer.run()
        ckpt = f"{snapdir}/mnist_epoch_1.orbax"
        import os as _os

        assert _os.path.isdir(ckpt), _os.listdir(snapdir)
    else:
        ckpt = f"{snapdir}/mnist_epoch_1.orbax"
        meta = trainer.restore_sharded(ckpt)
        assert meta["epoch"] == 1, meta["epoch"]
        # the restored leaves span the GLOBAL mesh (both processes)
        w = wf.forwards[0].weights.devmem
        assert len(w.sharding.device_set) == 4 * n, w.sharding
        trainer.run()
        assert len(losses) == 2          # epochs 2..3 ran after resume
    assert bool(wf.decision.complete)
    weights = {f.name: np.asarray(f.weights.map_read())
               for f in wf.forwards}
    np.savez(f"{snapdir}/weights_{phase}_{pid}.npz",
             **{k: np.asarray(v, np.float32) for k, v in weights.items()})
    print("RESULT " + json.dumps({"pid": pid, "losses": losses}),
          flush=True)
""")


def _spawn_pair(phase, tmp_path):
    worker = tmp_path / "mh_ckpt_worker.py"
    worker.write_text(WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 2
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), phase, str(pid), str(n), str(port),
         str(tmp_path)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in range(n)]
    results = {}
    try:
        for pid, proc in enumerate(procs):
            stdout, stderr = proc.communicate(timeout=420)
            assert proc.returncode == 0, (phase, pid, stderr[-3000:])
            line = [ln for ln in stdout.splitlines()
                    if ln.startswith("RESULT ")][-1]
            results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
    return results


@pytest.mark.slow
def test_two_process_checkpoint_kill_restore_finish(tmp_path):
    # slow since ISSUE 10 (tier-1 budget): ~20s of subprocess spawns;
    # the single-process restore path stays covered by the lean
    # snapshotter tests, the full 2-process kill/restore proof runs in
    # the slow lane.
    # phase 1: 2-process train; sharded orbax checkpoint lands at the end
    # of epoch 1; the processes then FINISH the 4 epochs, making their
    # own trajectory the uninterrupted oracle.  Both processes then exit
    # — the "kill" (nothing of the first incarnation survives except the
    # checkpoint directory).
    train = _spawn_pair("train", tmp_path)
    np.testing.assert_allclose(train[0]["losses"], train[1]["losses"],
                               rtol=1e-6)
    oracle_losses = train[0]["losses"]
    assert len(oracle_losses) == 4

    # phase 2: fresh 2-process incarnation restores and finishes
    resume = _spawn_pair("resume", tmp_path)
    np.testing.assert_allclose(resume[0]["losses"], resume[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(resume[0]["losses"], oracle_losses[2:],
                               rtol=1e-3)

    # phase 3: the SAME checkpoint restores single-process (this pytest
    # process, its own 8 virtual devices) and matches too
    from tests.test_fused import fresh_mnist
    from znicz_tpu import snapshotter  # noqa: F401  (registry warm)
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.common.dirs.snapshots = str(tmp_path)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = 4
    losses1 = []
    wf1 = mnist.MnistWorkflow()
    wf1.decision.on_epoch_end.append(
        lambda d: losses1.append(d.epoch_metrics[2]["loss"]))
    wf1.initialize(device=None)
    tr1 = FusedTrainer(wf1)
    tr1.restore_sharded(str(tmp_path / "mnist_epoch_1.orbax"))
    tr1.run()
    assert bool(wf1.decision.complete)
    np.testing.assert_allclose(losses1, oracle_losses[2:], rtol=1e-3)

    # weights: resumed (both processes) vs oracle finals
    with np.load(tmp_path / "weights_train_0.npz") as oracle_w:
        ow = {k: oracle_w[k] for k in oracle_w.files}
    for pid in range(2):
        with np.load(tmp_path / f"weights_resume_{pid}.npz") as f:
            for name, w in ow.items():
                np.testing.assert_allclose(
                    f[name], w, rtol=5e-3, atol=5e-5,
                    err_msg=f"resume proc {pid} {name}")
    for name, w in ow.items():
        np.testing.assert_allclose(
            {f.name: np.array(f.weights.map_read())
             for f in wf1.forwards}[name], w, rtol=5e-3, atol=5e-5,
            err_msg=f"single-process {name}")
