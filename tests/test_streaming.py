"""Streaming loader (loader/streaming.py): host-staged segments and
u8-HBM-residency must train EXACTLY like the resident FullBatch path —
same losses, same weights, same confusion — across segment boundaries,
short tail minibatches and epoch reshuffles (VERDICT r3 item 1)."""

import os

import numpy as np
import pytest

from znicz_tpu import datasets
from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader.streaming import (HostArraySource, ImageFileSource,
                                        StreamingLoader, class_dir_source)


def _mnist_cfg(max_epochs=2):
    # n_train NOT divisible by minibatch_size: the epoch tail is short,
    # covering the padded-gather route in both regimes
    root.mnist.loader.n_train = 290
    root.mnist.loader.n_valid = 60
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs


def _digits(u8=False):
    """The same procedural digits the MnistLoader would draw (same prng
    stream position), flattened sample-major."""
    cfg = root.mnist.loader
    total = int(cfg.n_train) + int(cfg.n_valid) + int(cfg.n_test)
    data, labels = datasets.load_or_generate(None, datasets.digits, total)
    data = data.reshape(total, -1)
    if u8:
        data = np.clip(np.round(data * 255.0), 0, 255).astype(np.uint8)
    return data, labels


class _StreamingMnistLoader(StreamingLoader):
    """Drop-in for MnistLoader: same digits data via a streaming source.
    Class attrs select the regime for the next construction."""

    u8 = False
    budget = 0          # 0 -> host-staged; big -> resident

    def __init__(self, workflow=None, name=None, **kwargs):
        cfg = root.mnist.loader
        data, labels = _digits(u8=type(self).u8)
        super().__init__(
            workflow=workflow, name=name,
            source=HostArraySource(data, labels),
            class_lengths=[int(cfg.n_test), int(cfg.n_valid),
                           int(cfg.n_train)],
            scale=(1.0 / 255.0 if type(self).u8 else 1.0), shift=0.0,
            device_budget_bytes=type(self).budget, **kwargs)


def _fresh(loader_cls=None, max_epochs=2):
    """MnistWorkflow with its loader class optionally swapped (the sample
    resolves MnistLoader as a module global)."""
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    _mnist_cfg(max_epochs)
    orig = mnist.MnistLoader
    if loader_cls is not None:
        mnist.MnistLoader = loader_cls
    try:
        wf = mnist.MnistWorkflow()
    finally:
        mnist.MnistLoader = orig
    wf.initialize(device=None)
    return wf


def _run_fused(wf, mesh=None):
    from znicz_tpu.parallel.fused import FusedTrainer

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    FusedTrainer(wf, mesh=mesh).run()
    return losses, {f.name: np.array(f.weights.map_read())
                    for f in wf.forwards}


def test_staged_f32_matches_resident(tmp_path):
    """Host-staged streaming (budget 0) reproduces the resident FullBatch
    trajectory bit-for-bit: same samples, same order, same math — only the
    residency moved."""
    root.common.dirs.snapshots = str(tmp_path)
    lr, wr = _run_fused(_fresh())
    _StreamingMnistLoader.u8, _StreamingMnistLoader.budget = False, 0
    ls, ws = _run_fused(_fresh(_StreamingMnistLoader))
    np.testing.assert_allclose(lr, ls, rtol=1e-6)
    for name in wr:
        np.testing.assert_allclose(wr[name], ws[name], rtol=1e-5,
                                   atol=1e-7, err_msg=name)


def test_staged_streaming_actually_stages(tmp_path):
    root.common.dirs.snapshots = str(tmp_path)
    from znicz_tpu.parallel.fused import FusedTrainer

    _StreamingMnistLoader.u8, _StreamingMnistLoader.budget = False, 0
    wf = _fresh(_StreamingMnistLoader)
    trainer = FusedTrainer(wf)
    assert trainer.staging
    assert not wf.loader.device_resident
    assert wf.loader.original_data.mem is None      # nothing resident
    trainer.run()
    # 10-class CE starts at ln(10) ~= 2.30; two epochs must clearly train
    assert wf.decision.epoch_metrics[2]["loss"] < 2.0


def test_u8_resident_matches_u8_staged(tmp_path):
    """Regime 2 (whole u8 dataset in HBM, decode fused into the gather)
    and regime 3 (u8 staged per segment) are the same math."""
    root.common.dirs.snapshots = str(tmp_path)
    _StreamingMnistLoader.u8, _StreamingMnistLoader.budget = True, 1 << 30
    lr, wr = _run_fused(_fresh(_StreamingMnistLoader))
    _StreamingMnistLoader.budget = 0
    ls, ws = _run_fused(_fresh(_StreamingMnistLoader))
    np.testing.assert_allclose(lr, ls, rtol=1e-6)
    for name in wr:
        np.testing.assert_allclose(wr[name], ws[name], rtol=1e-5,
                                   atol=1e-7, err_msg=name)
    assert lr[-1] < lr[0]                        # and it actually trains


def test_u8_device_decode_matches_host_decode(tmp_path):
    """u8*scale+shift on device == the host pre-decoded f32 dataset (both
    are exact f32 ops), so a u8 streaming run must match a resident f32
    run over the SAME decoded values."""
    from znicz_tpu.samples import mnist

    root.common.dirs.snapshots = str(tmp_path)

    class _PreDecoded(mnist.MnistLoader):
        def load_data(self):
            cfg = root.mnist.loader
            data, labels = _digits(u8=True)
            self.original_data.mem = (data.astype(np.float32) / 255.0)
            self.original_labels.mem = labels
            self.class_lengths = [int(cfg.n_test), int(cfg.n_valid),
                                  int(cfg.n_train)]
            from znicz_tpu.loader.fullbatch import FullBatchLoader

            FullBatchLoader.load_data(self)

    lr, wr = _run_fused(_fresh(_PreDecoded))
    _StreamingMnistLoader.u8, _StreamingMnistLoader.budget = True, 0
    ls, ws = _run_fused(_fresh(_StreamingMnistLoader))
    np.testing.assert_allclose(lr, ls, rtol=1e-5)
    for name in wr:
        np.testing.assert_allclose(wr[name], ws[name], rtol=1e-4,
                                   atol=1e-6, err_msg=name)


def test_staged_data_parallel_8dev_matches_single(tmp_path):
    """Streaming composes with the data mesh: staged segments are put
    replicated, the in-step sharding constraint shards the gathered batch."""
    import jax

    root.common.dirs.snapshots = str(tmp_path)
    assert len(jax.devices()) >= 8
    from znicz_tpu.parallel.mesh import make_mesh

    _StreamingMnistLoader.u8, _StreamingMnistLoader.budget = False, 0
    l1, w1 = _run_fused(_fresh(_StreamingMnistLoader))
    l8, w8 = _run_fused(_fresh(_StreamingMnistLoader),
                        mesh=make_mesh(axes=("data",)))
    np.testing.assert_allclose(l1, l8, rtol=1e-4)
    for name in w1:
        np.testing.assert_allclose(w1[name], w8[name], rtol=2e-3,
                                   atol=2e-5, err_msg=name)


def test_streaming_unit_engine_path(tmp_path):
    """The unit-at-a-time engine drives the streaming loader through
    fill_minibatch (host gather + decode) — slow but identical semantics."""
    root.common.dirs.snapshots = str(tmp_path)
    _StreamingMnistLoader.u8, _StreamingMnistLoader.budget = False, 0
    lr, wr = _run_fused(_fresh())
    prng.reset(1013)
    wf = _fresh(_StreamingMnistLoader)
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    wf.run()
    np.testing.assert_allclose(lr, losses, rtol=1e-4)


def _write_class_tree(base, n_per_class=4, size=(12, 12)):
    from PIL import Image

    rng = np.random.default_rng(7)
    for cname in ("cat", "dog"):
        d = os.path.join(base, cname)
        os.makedirs(d)
        for i in range(n_per_class):
            arr = rng.integers(0, 255, size + (3,), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.png"))


def test_image_file_source_streams(tmp_path):
    """Decode-on-demand image files as the host source: rows decode only
    when a segment stages them; a tiny conv net trains one epoch."""
    base = str(tmp_path / "imgs")
    os.makedirs(base)
    _write_class_tree(base)
    src = class_dir_source(base, target_shape=(12, 12))
    assert len(src) == 8 and src.dtype == np.uint8
    rows = src.gather(np.array([0, 5], np.int32))
    assert rows.shape == (2, 12, 12, 3) and rows.dtype == np.uint8

    from znicz_tpu.all2all import All2AllSoftmax
    from znicz_tpu.core.workflow import Repeater, Workflow
    from znicz_tpu.decision import DecisionGD
    from znicz_tpu.evaluator import EvaluatorSoftmax
    from znicz_tpu.gd import GDSoftmax
    from znicz_tpu.parallel.fused import FusedTrainer

    class WF(Workflow):
        def __init__(self):
            super().__init__(name="ImgStreamWF")
            self.repeater = Repeater(self, name="repeater")
            self.repeater.link_from(self.start_point)
            self.loader = StreamingLoader(
                self, name="loader", source=src, minibatch_size=4,
                class_lengths=[0, 2, 6], device_budget_bytes=0)
            self.loader.link_from(self.repeater)
            fwd = All2AllSoftmax(self, name="fwd0",
                                 output_sample_shape=(2,))
            fwd.link_from(self.loader)
            fwd.link_attrs(self.loader, ("input", "minibatch_data"))
            self.forwards = [fwd]
            self.evaluator = EvaluatorSoftmax(self, name="evaluator",
                                              n_classes=2)
            self.evaluator.link_from(fwd)
            self.evaluator.link_attrs(fwd, "output")
            self.evaluator.link_attrs(
                self.loader, ("labels", "minibatch_labels"),
                ("batch_size", "minibatch_size"))
            self.decision = DecisionGD(self, name="decision", max_epochs=1)
            self.decision.link_from(self.evaluator)
            self.decision.link_attrs(
                self.loader, "minibatch_class", "last_minibatch",
                "class_ended", "epoch_number", "class_lengths",
                "minibatch_size")
            self.decision.link_attrs(
                self.evaluator, ("minibatch_loss", "loss"),
                ("minibatch_n_err", "n_err"), "confusion_matrix",
                "max_err_output_sum")
            gd = GDSoftmax(self, name="gd0", forward=fwd,
                           learning_rate=0.05, need_err_input=False)
            gd.link_from(self.decision)
            gd.link_attrs(self.evaluator, ("err_output", "err_output"))
            gd.gate_skip = self.decision.gd_skip
            self.gds = [gd]
            self.repeater.link_from(gd)
            self.end_point.link_from(self.decision)
            self.end_point.gate_block = ~self.decision.complete

    prng.reset(1013)
    wf = WF()
    wf.initialize(device=None)
    trainer = FusedTrainer(wf)
    assert trainer.staging
    trainer.run()
    assert np.isfinite(wf.decision.epoch_metrics[2]["loss"])


@pytest.mark.slow
def test_bench_stream_protocol_smoke(capsys):
    """bench --stream at tiny shapes: the whole protocol (resident
    reference, u8-tiled window, staged segments, link probe) runs and the
    JSON line carries the self-explaining roofline fields.

    Slow-marked (ISSUE 7 budget discipline, the r24 precedent): this is
    a smoke of the ``bench.py --stream`` protocol, whose real gates run
    as the bench itself — tier-1 keeps the streaming-loader unit tests
    above, and at ~98s this was the single heaviest tier-1 entry."""
    import json

    import bench

    saved = {k: getattr(bench, k) for k in (
        "BATCH", "STEPS", "N_TRAIN", "N_VALID", "N_CLASSES",
        "N_STREAM_TILE", "N_HOST_TILE", "STAGE_SEGMENTS", "CHECK_LOSS",
        "N_DECODE_JPG", "N_DECODE_MEASURE")}
    # _build_bench_workflow mutates process-wide config from the patched
    # bench globals — snapshot and restore everything it touches
    cfg_saved = {k: root.alexnet.loader.get(k) for k in (
        "minibatch_size", "n_train", "n_valid", "n_classes", "image_size")}
    saved_epochs = root.alexnet.decision.get("max_epochs")
    saved_precision = root.common.engine.get("precision", "float32")
    saved_state = root.common.engine.get("state_dtype", "float32")
    root.alexnet.loader.image_size = 64
    try:
        bench.BATCH, bench.STEPS = 8, 4
        bench.N_TRAIN, bench.N_VALID, bench.N_CLASSES = 64, 16, 10
        bench.N_STREAM_TILE, bench.N_HOST_TILE = 2, 2
        bench.STAGE_SEGMENTS = 2
        bench.CHECK_LOSS = False
        bench.N_DECODE_JPG, bench.N_DECODE_MEASURE = 24, 16
        bench.stream_main()
    finally:
        for k, v in saved.items():
            setattr(bench, k, v)
        for k, v in cfg_saved.items():
            setattr(root.alexnet.loader, k, v)
        root.alexnet.decision.max_epochs = saved_epochs
        root.common.engine.precision = saved_precision
        root.common.engine.state_dtype = saved_state
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(out)
    assert rec["metric"] == "alexnet_stream_train_throughput_u8_resident"
    assert rec["dataset_images"] == 128
    assert np.isfinite(rec["value"]) and rec["value"] > 0
    st = rec["staged"]
    assert st["img_s"] > 0 and st["h2d_gbps_measured"] > 0
    assert st["roofline_img_s_at_measured_bw"] <= rec["value"] + 1e-6
    dec = rec["decode"]
    assert dec["img_s_serial"] > 0 and dec["img_s_pooled"] > 0
    assert dec["workers"] >= 1
    # three-term roofline never exceeds any single term
    assert dec["roofline_img_s_3term"] <= rec["value"] + 1e-6
    assert dec["roofline_img_s_3term"] <= \
        st["roofline_img_s_at_measured_bw"] + 1e-6
    assert dec["roofline_img_s_3term"] <= dec["img_s_pooled"] + 1e-6


def test_streaming_rejects_nonlinear_normalizer():
    from znicz_tpu.normalization import MeanDispNormalizer

    with pytest.raises(ValueError, match="normalizer"):
        StreamingLoader(None, name="x",
                        source=np.zeros((4, 3), np.float32),
                        normalizer=MeanDispNormalizer())


def test_streaming_mse_without_targets_raises():
    """A StreamingLoader built without regression targets must fail an MSE
    fused run with a clear config error at run start, not an opaque crash
    deep inside the staging/operand path (ADVICE r4)."""
    from znicz_tpu.all2all import All2AllTanh
    from znicz_tpu.core.workflow import Repeater, Workflow
    from znicz_tpu.decision import DecisionMSE
    from znicz_tpu.evaluator import EvaluatorMSE
    from znicz_tpu.gd import GDTanh
    from znicz_tpu.parallel.fused import FusedTrainer

    data = np.random.RandomState(0).rand(32, 6).astype(np.float32)

    class WF(Workflow):
        def __init__(self):
            super().__init__(name="MseStreamWF")
            self.repeater = Repeater(self, name="repeater")
            self.repeater.link_from(self.start_point)
            self.loader = StreamingLoader(
                self, name="loader", source=HostArraySource(data),
                minibatch_size=8, class_lengths=[0, 8, 24],
                scale=1.0, device_budget_bytes=0)
            self.loader.link_from(self.repeater)
            fwd = All2AllTanh(self, name="fwd0", output_sample_shape=(6,))
            fwd.link_from(self.loader)
            fwd.link_attrs(self.loader, ("input", "minibatch_data"))
            self.forwards = [fwd]
            self.evaluator = EvaluatorMSE(self, name="evaluator")
            self.evaluator.link_from(fwd)
            self.evaluator.link_attrs(fwd, "output")
            self.evaluator.link_attrs(
                self.loader, ("target", "minibatch_targets"),
                ("batch_size", "minibatch_size"))
            self.decision = DecisionMSE(self, name="decision", max_epochs=1)
            self.decision.link_from(self.evaluator)
            self.decision.link_attrs(
                self.loader, "minibatch_class", "last_minibatch",
                "class_ended", "epoch_number", "class_lengths",
                "minibatch_size")
            self.decision.link_attrs(self.evaluator,
                                     ("minibatch_loss", "loss"))
            gd = GDTanh(self, name="gd0", forward=fwd, learning_rate=0.01,
                        need_err_input=False)
            gd.link_from(self.decision)
            gd.link_attrs(self.evaluator, ("err_output", "err_output"))
            gd.gate_skip = self.decision.gd_skip
            self.gds = [gd]
            self.repeater.link_from(gd)
            self.end_point.link_from(self.decision)
            self.end_point.gate_block = ~self.decision.complete

    wf = WF()
    wf.initialize(device=None)
    with pytest.raises(ValueError, match="regression targets"):
        FusedTrainer(wf).run()
