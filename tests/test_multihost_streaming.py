"""Multi-host SHARDED input staging: in a 2-process x 4-device run with a
host-staged StreamingLoader, each process must assemble and ship ONLY the
rows of the batch shards its own devices hold (fused.py _stage_direct via
make_array_from_callback) — the SPMD analogue of the reference's
master/slave per-slave minibatch feed — and the training trajectory must
match the single-process staged run."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import json
    import sys

    from znicz_tpu.virtdev import provision_cpu_devices

    provision_cpu_devices(4, verify=False)
    from znicz_tpu.parallel.mesh import distributed_init, make_mesh

    pid, n, port, snapdir = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3], sys.argv[4])
    distributed_init(coordinator=f"127.0.0.1:{port}",
                     num_processes=n, process_id=pid)
    import numpy as np

    import jax

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from tests.test_multihost_streaming import build_streaming_mnist

    prng.reset(1013)
    root.common.dirs.snapshots = snapdir
    wf = build_streaming_mnist()
    wf.initialize(device=None)

    gathered = {"rows": 0}
    orig_gather = wf.loader.host_gather
    def counting_gather(idx):
        idx = np.asarray(idx)
        gathered["rows"] += int(idx.size)
        return orig_gather(idx)
    wf.loader.host_gather = counting_gather

    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    mesh = make_mesh(axes=("data",))
    trainer = FusedTrainer(wf, mesh=mesh)
    assert trainer.staging
    trainer.run()
    total_served = int(wf.loader.samples_served)
    print("RESULT " + json.dumps({
        "pid": pid, "losses": losses, "rows_gathered": gathered["rows"],
        "samples_served": total_served,
        "weights_sum": {f.name: float(np.sum(f.weights.map_read()))
                        for f in wf.forwards}}), flush=True)
""")


def build_streaming_mnist():
    """A host-staged streaming MNIST workflow with a mesh-divisible batch
    (64 over 8 data-axis devices) — shared by the workers and the
    in-process oracle."""
    from znicz_tpu import datasets
    from znicz_tpu.core.config import root
    from znicz_tpu.loader.streaming import HostArraySource, StreamingLoader
    from znicz_tpu.samples import mnist

    root.mnist.loader.n_train = 256
    root.mnist.loader.n_valid = 64
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 64
    root.mnist.decision.max_epochs = 2

    class _Loader(StreamingLoader):
        def __init__(self, workflow=None, name=None, **kwargs):
            data, labels = datasets.load_or_generate(
                None, datasets.digits, 320)
            super().__init__(
                workflow=workflow, name=name,
                source=HostArraySource(
                    data.reshape(320, -1).astype(np.float32), labels),
                class_lengths=[0, 64, 256], device_budget_bytes=0,
                scale=1.0, **kwargs)

    orig = mnist.MnistLoader
    mnist.MnistLoader = _Loader
    try:
        return mnist.MnistWorkflow()
    finally:
        mnist.MnistLoader = orig


def test_two_process_staged_streaming_shards_the_input(tmp_path):
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.parallel.mesh import make_mesh

    # in-process oracle: single-process staged streaming on the 8-dev mesh
    root.common.dirs.snapshots = str(tmp_path)
    prng.reset(1013)
    wf = build_streaming_mnist()
    wf.initialize(device=None)
    oracle_losses = []
    wf.decision.on_epoch_end.append(
        lambda d: oracle_losses.append(d.epoch_metrics[2]["loss"]))
    tr = FusedTrainer(wf, mesh=make_mesh(axes=("data",)))
    assert tr.staging
    tr.run()
    oracle_weights = {f.name: float(np.sum(f.weights.map_read()))
                      for f in wf.forwards}

    worker = tmp_path / "mhs_worker.py"
    worker.write_text(WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 2
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(n), str(port),
         str(tmp_path)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in range(n)]
    results = {}
    try:
        for pid, proc in enumerate(procs):
            stdout, stderr = proc.communicate(timeout=420)
            assert proc.returncode == 0, (pid, stderr[-3000:])
            line = [ln for ln in stdout.splitlines()
                    if ln.startswith("RESULT ")][-1]
            results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], oracle_losses,
                               rtol=1e-4)
    for pid in range(n):
        for name, s in oracle_weights.items():
            np.testing.assert_allclose(
                results[pid]["weights_sum"][name], s, rtol=1e-3,
                err_msg=f"proc {pid} {name}")
        # THE sharding property: each process host-gathered only (about)
        # HALF the rows the run consumed.  samples_served counts every
        # sample the loader state machine handed out; the oracle gathers
        # all of them, a 2-process worker only its own shards (plus eval
        # replication slack).
        served = results[pid]["samples_served"]
        gathered = results[pid]["rows_gathered"]
        assert gathered <= 0.75 * served, (pid, gathered, served)


IMG_WORKER = textwrap.dedent("""\
    import json
    import sys

    from znicz_tpu.virtdev import provision_cpu_devices

    provision_cpu_devices(4, verify=False)
    from znicz_tpu.parallel.mesh import distributed_init, make_mesh

    pid, n, port, imgdir, snapdir = (int(sys.argv[1]), int(sys.argv[2]),
                                     sys.argv[3], sys.argv[4], sys.argv[5])
    distributed_init(coordinator=f"127.0.0.1:{port}",
                     num_processes=n, process_id=pid)
    import numpy as np

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from tests.test_multihost_streaming import build_imagefile_mnist

    prng.reset(1013)
    root.common.dirs.snapshots = snapdir
    wf = build_imagefile_mnist(imgdir, workers=2)
    wf.initialize(device=None)
    losses = []
    wf.decision.on_epoch_end.append(
        lambda d: losses.append(d.epoch_metrics[2]["loss"]))
    trainer = FusedTrainer(wf, mesh=make_mesh(axes=("data",)))
    assert trainer.staging
    trainer.run()
    stats = wf.loader.ingest_stats
    print("RESULT " + json.dumps({
        "pid": pid, "losses": losses, "ingest": stats,
        "samples_served": int(wf.loader.samples_served),
        "weights_sum": {f.name: float(np.sum(f.weights.map_read()))
                        for f in wf.forwards}}), flush=True)
""")


def build_imagefile_mnist(imgdir, workers):
    """Host-staged streaming MNIST-shaped workflow over a decode-on-demand
    image-file source with a decode pool of ``workers`` threads."""
    from znicz_tpu.core.config import root
    from znicz_tpu.loader.streaming import StreamingLoader, class_dir_source
    from znicz_tpu.samples import mnist

    root.mnist.loader.n_train = 256
    root.mnist.loader.n_valid = 64
    root.mnist.loader.n_test = 0
    root.mnist.loader.minibatch_size = 64
    root.mnist.decision.max_epochs = 2

    class _Loader(StreamingLoader):
        def __init__(self, workflow=None, name=None, **kwargs):
            super().__init__(
                workflow=workflow, name=name,
                source=class_dir_source(imgdir, target_shape=(12, 12),
                                        workers=workers),
                class_lengths=[0, 64, 256], device_budget_bytes=0,
                **kwargs)

    orig = mnist.MnistLoader
    mnist.MnistLoader = _Loader
    try:
        return mnist.MnistWorkflow()
    finally:
        mnist.MnistLoader = orig


def test_two_process_imagefile_ingest_prefetches_own_rows(tmp_path):
    """The host INGEST engine in a 2-process run (the untested half of
    loader/ingest.py): the lookahead submits only the rows of batch
    shards the LOCAL process holds, the decode pool serves steady-state
    gathers from prefetched futures, and the trajectory matches the
    single-process serial-decode oracle bit-for-bit at loss tolerance."""
    from tests.test_streaming import _write_class_tree
    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.parallel.fused import FusedTrainer
    from znicz_tpu.parallel.mesh import make_mesh

    imgdir = str(tmp_path / "imgs")
    os.makedirs(imgdir)
    _write_class_tree(imgdir, n_per_class=160, size=(12, 12))

    # in-process oracle: single process, SERIAL decode (workers=0)
    root.common.dirs.snapshots = str(tmp_path)
    prng.reset(1013)
    wf = build_imagefile_mnist(imgdir, workers=0)
    wf.initialize(device=None)
    oracle_losses = []
    wf.decision.on_epoch_end.append(
        lambda d: oracle_losses.append(d.epoch_metrics[2]["loss"]))
    tr = FusedTrainer(wf, mesh=make_mesh(axes=("data",)))
    assert tr.staging
    tr.run()

    worker = tmp_path / "mhi_worker.py"
    worker.write_text(IMG_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    n = 2
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(n), str(port),
         imgdir, str(tmp_path)],
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in range(n)]
    results = {}
    try:
        for pid, proc in enumerate(procs):
            stdout, stderr = proc.communicate(timeout=420)
            assert proc.returncode == 0, (pid, stderr[-3000:])
            line = [ln for ln in stdout.splitlines()
                    if ln.startswith("RESULT ")][-1]
            results[pid] = json.loads(line[len("RESULT "):])
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(results[0]["losses"], oracle_losses,
                               rtol=1e-4)
    for pid in range(n):
        st = results[pid]["ingest"]
        served = results[pid]["samples_served"]
        # own-rows-only extends to the prefetcher: each process decoded
        # only (about) HALF the rows the run consumed
        assert st["rows_decoded"] <= 0.75 * served, (pid, st, served)
        # and the lookahead actually fed the queue
        assert st["prefetch_hits"] > 0, (pid, st)
