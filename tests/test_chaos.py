"""Fault-tolerance chaos suite (ISSUE 2): seeded frame chaos through a
ROUTER/DEALER proxy, slave kill mid-job, master kill + crash-resume,
delta quarantine, bad-frame refusal, dead-slave eviction, and the client
reconnect state machine — all CPU-only, in-process, and seeded so CI
reruns see identical fault schedules."""

import os
import pickle
import threading
import time

import numpy as np
import pytest

from znicz_tpu.core.config import root

#: the suite's fault mix — seed 5 gives every fault type >= 3 hits in
#: the first 120 frames (see test_fault_schedule_deterministic)
CHAOS = dict(drop=0.06, corrupt=0.06, duplicate=0.05, delay=0.08,
             delay_s=(0.02, 0.25))
SEED = 5


def _make_workflow(tmp_path, max_epochs=3):
    from znicz_tpu.core import prng
    from znicz_tpu.samples import mnist

    prng.reset(1013)
    root.mnist.loader.n_train = 300
    root.mnist.loader.n_valid = 60
    root.mnist.loader.minibatch_size = 60
    root.mnist.decision.max_epochs = max_epochs
    root.common.dirs.snapshots = str(tmp_path)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=None)
    return wf


def _handshake_fields(workflow):
    from znicz_tpu.network_common import handshake_request

    msg = handshake_request(workflow)
    del msg["cmd"]
    return msg


# -- the fault schedule --------------------------------------------------------


def test_fault_schedule_deterministic():
    """Two chaos runs with the same seed produce IDENTICAL fault
    schedules: decide(i) is a pure function of (seed, i) — thread timing
    and traffic volume cannot perturb it (the CI determinism contract)."""
    from znicz_tpu.parallel.chaos import FaultSchedule

    a = FaultSchedule(SEED, **CHAOS)
    b = FaultSchedule(SEED, **CHAOS)
    assert a.decisions(500) == b.decisions(500)
    # a different seed really is a different schedule
    c = FaultSchedule(SEED + 1, **CHAOS)
    assert a.decisions(500) != c.decisions(500)
    # the suite's seed exercises every fault type early
    from collections import Counter

    counts = Counter(action for action, _ in a.decisions(120))
    for action in ("drop", "corrupt", "dup", "delay", "forward"):
        assert counts[action] >= 3, counts
    # probabilities must stay a sub-distribution
    with pytest.raises(ValueError, match="sum"):
        FaultSchedule(1, drop=0.7, corrupt=0.4)


def test_compute_fault_stream_deterministic_and_independent():
    """The ISSUE 6 ``stall`` kind rides a SEPARATE seeded stream:
    adding it to a schedule leaves the wire decisions byte-identical
    (existing chaos runs replay unchanged), and decide_compute is a
    pure function of (seed, dispatch_no)."""
    from znicz_tpu.parallel.chaos import FaultSchedule

    a = FaultSchedule(7, drop=0.1, corrupt=0.1, stall=0.5,
                      stall_s=(0.01, 0.02))
    b = FaultSchedule(7, drop=0.1, corrupt=0.1)
    assert a.decisions(300) == b.decisions(300)
    c = FaultSchedule(7, stall=0.5, stall_s=(0.01, 0.02))
    assert [a.decide_compute(i) for i in range(200)] \
        == [c.decide_compute(i) for i in range(200)]
    kinds = {a.decide_compute(i)[0] for i in range(200)}
    assert kinds == {"stall", "run"}
    for act, s in (a.decide_compute(i) for i in range(200)):
        if act == "run":
            assert s == 0.0
        else:
            assert 0.01 <= s <= 0.02
    # stall never fires on a stall-free schedule
    assert all(b.decide_compute(i)[0] == "run" for i in range(100))
    with pytest.raises(ValueError, match="stall"):
        FaultSchedule(1, stall=1.5)


def test_corrupt_payload_is_undecodable():
    from znicz_tpu.parallel.chaos import corrupt_payload

    payload = pickle.dumps({"cmd": "job", "id": "s1"})
    mangled = corrupt_payload(payload)
    assert mangled != payload
    with pytest.raises(Exception):
        pickle.loads(mangled)


# -- frame chaos through the proxy ---------------------------------------------


@pytest.mark.slow
def test_chaos_proxy_faults_accounted(tmp_path):
    """The acceptance run: seeded drop/corrupt/duplicate/delay between
    two slaves and the master.  Training completes without hang or
    crash, converges to the fault-free quality band, and every injected
    fault is accounted for: corrupted requests == the master's
    bad_frames, corrupted replies == the slaves' bad_replies, and every
    starved receive (drops + corrupted replies) shows up as a client
    reconnect.

    ``slow`` since ISSUE 10 (tier-1 budget): ~20s, and the coverage is
    structural-duplicated by the lean multipart-corruption test plus
    the relay chaos suite; the full accounting proof runs in the slow
    lane with the soaks."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule
    from znicz_tpu.server import Server

    front = "tcp://127.0.0.1:17580"      # slaves connect here
    back = "tcp://127.0.0.1:17581"       # master binds here
    proxy = ChaosProxy(front, back,
                       FaultSchedule(SEED, **CHAOS)).start()
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=back, job_timeout=6.0)

    slaves = [Client(_make_workflow(tmp_path / f"s{i}"), endpoint=front,
                     slave_id=f"chaos{i}") for i in range(2)]
    errors = []

    def worker(s):
        try:
            s.run(recv_timeout=1.0, max_reconnects=40, backoff_base=0.05,
                  backoff_cap=0.4, connect_retries=40)
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    try:
        for t in threads:
            t.start()
        server.serve(linger=8.0)
        for t in threads:
            t.join(timeout=90)
    finally:
        proxy.stop()
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = master_wf.decision
    assert bool(dec.complete)            # no hang, no crash
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid

    # -- fault accounting: nothing injected was lost silently ----------
    c = proxy.counters
    assert len(proxy.log) == sum(n for d in c.values() for n in d.values())
    assert proxy.total_faults() > 0
    for action in ("drop", "corrupt", "dup", "delay"):
        assert c["req"][action] + c["rep"][action] > 0, c
    # every corrupted request was refused + counted by the master —
    # v3 framing included: whichever payload frame the proxy mutated
    # (metadata or a tensor buffer), the codec detected it
    assert server.bad_frames == c["req"]["corrupt"], (server.bad_frames, c)
    # every corrupted reply was detected + counted by a slave (main
    # socket or its prefetcher — both decode through the codec).  A dup
    # spawns one EXTRA reply the client's REQ_CORRELATE discards unseen;
    # a later drop/corrupt decision can land on that ghost frame, so the
    # client-side counters may undercount by at most the dup count.
    dups = c["req"]["dup"] + c["rep"]["dup"]
    bad_replies = sum(s.bad_replies + s.prefetch_bad_replies
                      for s in slaves)
    assert c["rep"]["corrupt"] - dups <= bad_replies <= c["rep"]["corrupt"]
    # every starved receive became a fresh-socket retry on whichever
    # socket starved (main loop reconnect or prefetcher reconnect);
    # slack below for ghost-frame absorption, above for endgame retries
    # after the master's linger expires (one per socket, two sockets per
    # slave since the v3 prefetch pipeline)
    starved = proxy.faults_toward("rep")
    reconnects = sum(s.reconnects + s.prefetch_reconnects for s in slaves)
    assert starved - dups <= reconnects <= starved + 4 * len(slaves), \
        (starved, reconnects, c)
    # books balance: every accepted update is attributed to a slave
    assert server.jobs_done == sum(server.jobs_by_slave.values())
    assert all(server.jobs_by_slave.get(s.slave_id, 0) > 0 for s in slaves)


def test_chaos_corruption_is_multipart_aware():
    """v3 framing (ISSUE 3 satellite): one fault decision covers the
    WHOLE logical multipart message, the mutation lands on exactly one
    PAYLOAD frame (metadata or a tensor buffer — never the ROUTER
    routing envelope, so refusals still route back), the pick is a pure
    function of (seed, frame_no), and whatever frame it lands on the
    codec detects the damage."""
    import numpy as np_

    from znicz_tpu.parallel import wire
    from znicz_tpu.parallel.chaos import ChaosProxy, FaultSchedule

    proxy = ChaosProxy("inproc://cfront", "inproc://cback",
                       FaultSchedule(SEED, **CHAOS))   # never started
    payload, _ = wire.encode_message(
        {"cmd": "update", "id": "s1", "job_id": 7,
         "deltas": {"l": {"w": np_.ones((8, 8), np_.float32)}},
         "metrics": {"loss": 1.0}})
    payload = [bytes(f) for f in payload]
    envelope = [b"identity", b"\x00\x00\x00\x01", b""]  # id+correlate+delim
    frames = envelope + payload
    picks = set()
    for fno in range(60):
        out1 = proxy._corrupt_one(list(frames), fno)
        assert out1 == proxy._corrupt_one(list(frames), fno)  # determinism
        assert out1[:len(envelope)] == envelope     # envelope untouched
        changed = [i for i, (a, b) in enumerate(zip(out1, frames))
                   if a != b]
        assert len(changed) == 1 and changed[0] >= len(envelope), changed
        picks.add(changed[0])
        with pytest.raises(wire.WireError):
            wire.decode_message(out1[len(envelope):])
    # over many frames the pick really ranges over ALL payload frames
    assert picks == set(range(len(envelope), len(frames))), picks


# -- slave kill + master kill/resume -------------------------------------------


def test_slave_kill_and_master_crash_resume(tmp_path):
    """Mid-job slave death AND a master kill+restart mid-epoch: the
    restarted master restores the periodic crash-resume snapshot
    (params, loader/decision cursors, outstanding jobs, counters), the
    slaves ride the outage out via reconnect/backoff and re-register,
    and training completes in the fault-free quality band."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.chaos import MasterHarness, take_job_and_die

    endpoint = "tcp://127.0.0.1:17582"
    resume = str(tmp_path / "master_resume.pickle.gz")
    harness = MasterHarness(
        lambda: _make_workflow(tmp_path / "m"), endpoint, resume,
        snapshot_every_s=0.25, linger=5.0, job_timeout=8.0)
    server1 = harness.start()
    assert not server1.resumed           # nothing to resume from yet

    slaves = [Client(_make_workflow(tmp_path / f"s{i}"), endpoint=endpoint,
                     slave_id=f"phoenix{i}") for i in range(2)]
    errors = []

    def worker(s):
        try:
            s.run(recv_timeout=1.0, max_reconnects=60, backoff_base=0.05,
                  backoff_cap=0.3)
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()

    # a slave takes a job and dies mid-job (same digest as the master)
    doomed_jid = take_job_and_die(endpoint, harness.workflow, "doomed")
    assert doomed_jid is not None

    # let it make progress, then wait for a snapshot that has SEEN that
    # progress (a save from before jobs_done crossed 3 would roll the
    # counters back past the assertion below)
    deadline = time.time() + 60
    while server1.jobs_done < 3 and time.time() < deadline:
        time.sleep(0.05)
    assert server1.jobs_done >= 3
    saves = server1.resume_saves
    while server1.resume_saves <= saves and time.time() < deadline:
        time.sleep(0.05)
    assert server1.resume_saves > saves
    harness.kill()                       # simulated crash, mid-epoch
    assert os.path.exists(resume)
    # stay dark past the slaves' recv_timeout so the outage exercises
    # the timeout->fresh-socket->backoff path, not just zmq's transparent
    # redelivery into the instantly-rebound endpoint
    time.sleep(1.5)

    server2 = harness.start()            # restarts from the snapshot
    assert server2.resumed
    assert server2.jobs_done >= 3        # counters carried over
    assert harness.wait(timeout=180)
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = harness.workflow.decision
    assert bool(dec.complete)            # resumed run finished training
    valid = dec.epoch_metrics[1]
    assert valid is not None and valid["err_pct"] < 70.0, valid
    # the slaves really rode the restart out via reconnect+re-register
    assert sum(s.reconnects for s in slaves) >= 1
    assert server2.reregistrations >= 1
    # dead slave's job never reached an accepted update
    assert server2.jobs_by_slave.get("doomed", 0) == 0
    assert server2.jobs_done == sum(server2.jobs_by_slave.values())
    # the resume file is consumed by a COMPLETED run — a rerun of the
    # same command must start fresh, not restore stale mid-training state
    assert not os.path.exists(resume)


# -- delta quarantine ----------------------------------------------------------


def test_quarantine_nonfinite_delta_never_applied(tmp_path):
    """A NaN/Inf delta is refused (never touches global params), counted,
    and the job is re-queued."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    rep = server._handle({"cmd": "job", "id": "s1"})
    jid = rep["job_id"]
    before = {f.name: {k: np.array(a.map_read())
                       for k, a in f.params().items()}
              for f in master_wf.forwards if f.has_weights}
    poisoned = {name: {k: np.full_like(v, np.nan)
                       for k, v in layer.items()}
                for name, layer in before.items()}
    rep = server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                          "deltas": poisoned,
                          "metrics": {"loss": 0.0, "n_err": 0}})
    assert rep["ok"] is False and rep.get("quarantined")
    assert "non-finite" in rep["error"]
    assert server.quarantined_updates == 1
    assert len(server._pending) == 1     # the job came back
    for f in master_wf.forwards:
        if f.has_weights:
            for k, a in f.params().items():
                np.testing.assert_array_equal(np.array(a.map_read()),
                                              before[f.name][k])


def test_quarantine_norm_exploded_bounded_retry(tmp_path):
    """A finite but norm-exploded delta (diverging slave) is quarantined
    against the running median of accepted norms; the job follows the
    bounded MAX_BAD_REPLIES policy — re-queued, then DROPPED after
    repeated bad deltas so one broken slave cannot livelock the run.
    Sane deltas keep flowing afterwards."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    server._delta_norms.extend([1.0, 1.1, 0.9, 1.0, 1.05])   # history

    def update(jid, scale):
        deltas = {f.name: {k: np.full(a.shape, scale, np.float32)
                           for k, a in f.params().items()}
                  for f in master_wf.forwards if f.has_weights}
        return server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                               "deltas": deltas,
                               "metrics": {"loss": 1.0, "n_err": 0}})

    jid = server._handle({"cmd": "job", "id": "s1"})["job_id"]
    for attempt in range(server.MAX_BAD_REPLIES):
        rep = update(jid, 1e6)           # norm >> 25 x median
        assert rep["ok"] is False and rep.get("quarantined"), rep
        assert "median" in rep["error"]
        requeued = bool(server._pending)
        if attempt < server.MAX_BAD_REPLIES - 1:
            assert requeued              # bounded retry: back in the queue
            rep = server._handle({"cmd": "job", "id": "s1"})
            jid = rep["job_id"]
        else:
            assert not requeued          # ...then dropped for good
    assert server.quarantined_updates == server.MAX_BAD_REPLIES
    # a sane update on a fresh job is still accepted
    jid = server._handle({"cmd": "job", "id": "s1"})["job_id"]
    rep = update(jid, 1e-4)
    assert rep["ok"] is True
    assert server.jobs_done == 1


def test_malformed_update_payloads_never_lose_the_job(tmp_path):
    """Post-pop safety: once an update's job has left _inflight, a
    structurally-broken payload (metrics of the wrong type, ragged or
    wrong-shape delta arrays) must refuse-and-requeue — an exception
    there would lose the job silently (and hang the epoch if it was the
    tail)."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    first = next(f for f in master_wf.forwards if f.has_weights)

    # 1) singleton metrics that is a LIST (segment-style reply to a flat
    # job) — previously raised in _feed_decision after the pop
    jid = server._handle({"cmd": "job", "id": "s1"})["job_id"]
    rep = server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                          "deltas": None, "metrics": [{"loss": 1.0}]})
    assert rep["ok"] is False and "not a dict" in rep["error"]
    assert server.bad_updates == 1
    assert len(server._pending) == 1     # requeued, not lost

    # 2) ragged delta array — np.asarray raises; must quarantine
    jid = server._handle({"cmd": "job", "id": "s1"})["job_id"]
    rep = server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                          "deltas": {first.name:
                                     {"weights": [[1.0], [2.0, 3.0]]}},
                          "metrics": {"loss": 1.0, "n_err": 0}})
    assert rep["ok"] is False and rep.get("quarantined"), rep
    assert "undecodable delta payload" in rep["error"]
    assert len(server._pending) == 1

    # 3) wrong-shape delta — apply_deltas would raise mid-apply
    jid = server._handle({"cmd": "job", "id": "s1"})["job_id"]
    rep = server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                          "deltas": {first.name:
                                     {"weights": np.zeros((2, 2),
                                                          np.float32)}},
                          "metrics": {"loss": 1.0, "n_err": 0}})
    assert rep["ok"] is False and rep.get("quarantined"), rep
    assert "shape" in rep["error"]
    assert server.quarantined_updates == 2
    # third strike on the same (non-tail) job: the bounded policy drops
    # it instead of re-queueing — no livelock
    assert not server._pending

    # the stream moves on and a sane update completes the next job
    rep = server._handle({"cmd": "job", "id": "s1"})
    jid = rep["job_id"]
    rep = server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                          "deltas": None,
                          "metrics": {"loss": 1.0, "n_err": 0}})
    assert rep["ok"] is True and server.jobs_done == 1


# -- bad frames ----------------------------------------------------------------


def test_bad_frame_refused_not_fatal(tmp_path):
    """A garbage frame gets an error reply and a bad_frames tick instead
    of raising out of the REP loop and killing the master; the next
    well-formed request is served normally."""
    import zmq

    from znicz_tpu.server import Server

    endpoint = "tcp://127.0.0.1:17583"
    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, endpoint=endpoint)
    thread = threading.Thread(target=server.serve, daemon=True)
    thread.start()
    sock = zmq.Context.instance().socket(zmq.REQ)
    sock.setsockopt(zmq.RCVTIMEO, 10_000)
    sock.setsockopt(zmq.LINGER, 0)
    sock.connect(endpoint)
    try:
        sock.send(b"\x00 definitely not a pickle")
        rep = pickle.loads(sock.recv())
        assert rep["ok"] is False and rep.get("bad_frame")
        # a frame that IS a pickle but not a request dict is refused too
        sock.send(pickle.dumps([1, 2, 3]))
        rep = pickle.loads(sock.recv())
        assert rep["ok"] is False and rep.get("bad_frame")
        assert server.bad_frames == 2
        # the master still serves well-formed peers
        msg = {"cmd": "register", "id": "s1",
               **_handshake_fields(master_wf)}
        sock.send(pickle.dumps(msg))
        assert pickle.loads(sock.recv())["ok"]
    finally:
        sock.close(0)
        server.stop()
        thread.join(timeout=10)
    assert not thread.is_alive()


# -- the client reconnect state machine ----------------------------------------


def test_client_reconnects_with_fresh_socket_after_timeout(tmp_path):
    """The REQ EFSM fix: after a silent master (zmq.Again) the client
    closes the dead socket, backs off, reconnects FRESH and re-registers
    — previously any retry on the same socket raised ZMQError(EFSM)."""
    import zmq

    from znicz_tpu.client import Client

    endpoint = "tcp://127.0.0.1:17584"
    wf = _make_workflow(tmp_path / "s")
    seen = []

    def scripted_master():
        """ROUTER-based master: replies to everything EXCEPT the first
        job request, which it swallows (a dropped reply).  Decodes v3
        multipart requests and answers in legacy pickle framing — the
        client must accept both (lenient decode)."""
        from znicz_tpu.parallel import wire

        ctx = zmq.Context.instance()
        router = ctx.socket(zmq.ROUTER)
        router.setsockopt(zmq.RCVTIMEO, 20_000)
        router.setsockopt(zmq.LINGER, 0)
        router.bind(endpoint)
        try:
            ignored_job = False
            while True:
                envelope, payload = wire.split_envelope(
                    router.recv_multipart())
                req, _ = wire.decode_message(payload)
                seen.append(req["cmd"])
                if req["cmd"] == "job" and not ignored_job:
                    ignored_job = True
                    continue                    # swallow: client times out
                if req["cmd"] == "register":
                    rep = {"ok": True, "version": req["version"],
                           "class_lengths": [0, 60, 300]}
                elif req["cmd"] == "job":
                    rep = {"done": True}
                router.send_multipart(envelope + [pickle.dumps(rep)])
                if req["cmd"] == "job":
                    return
        finally:
            router.close(0)

    thread = threading.Thread(target=scripted_master, daemon=True)
    thread.start()
    client = Client(wf, endpoint=endpoint, slave_id="efsm")
    done = client.run(recv_timeout=0.5, max_reconnects=5,
                      backoff_base=0.05, backoff_cap=0.2)
    thread.join(timeout=10)
    assert not thread.is_alive()
    assert done == 0
    assert client.reconnects == 1        # one fresh-socket retry
    # the retry re-registered before asking for work again
    assert seen == ["register", "job", "register", "job"]


def test_client_gives_up_cleanly_when_master_gone(tmp_path):
    """A registered slave whose master vanishes for good exits cleanly
    after max_reconnects consecutive failures (no exception, no hang)."""
    import zmq

    from znicz_tpu.client import Client

    endpoint = "tcp://127.0.0.1:17585"
    wf = _make_workflow(tmp_path / "s")

    def register_then_die():
        from znicz_tpu.parallel import wire

        ctx = zmq.Context.instance()
        router = ctx.socket(zmq.ROUTER)
        router.setsockopt(zmq.RCVTIMEO, 20_000)
        router.setsockopt(zmq.LINGER, 0)
        router.bind(endpoint)
        try:
            envelope, payload = wire.split_envelope(
                router.recv_multipart())
            req, _ = wire.decode_message(payload)
            rep = {"ok": True, "version": req["version"],
                   "class_lengths": [0, 60, 300]}
            router.send_multipart(envelope + [pickle.dumps(rep)])
        finally:
            router.close(0)              # master gone for good

    thread = threading.Thread(target=register_then_die, daemon=True)
    thread.start()
    client = Client(wf, endpoint=endpoint, slave_id="orphan")
    done = client.run(recv_timeout=0.3, max_reconnects=2,
                      backoff_base=0.02, backoff_cap=0.05)
    thread.join(timeout=10)
    assert done == 0
    assert client.reconnects == 2        # spent the whole budget


# -- membership hygiene --------------------------------------------------------


def test_dead_slave_evicted_and_web_status_counters(tmp_path):
    """A silent slave is evicted past slave_ttl (its job history kept for
    the report), must re-register to work again, and the dashboard
    exposes live/dead membership plus the robustness counters."""
    import json
    import urllib.request

    from znicz_tpu.server import Server
    from znicz_tpu.web_status import WebStatus

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, slave_ttl=0.1)
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    jid = server._handle({"cmd": "job", "id": "s1"})["job_id"]
    server._handle({"cmd": "update", "id": "s1", "job_id": jid,
                    "deltas": None, "metrics": {"loss": 1.0, "n_err": 0}})
    time.sleep(0.15)
    server._evict_dead_slaves()
    assert "s1" not in server.slaves and "s1" not in server.registered
    assert "s1" in server.dead_slaves
    assert server.jobs_by_slave["s1"] == 1       # history survives
    # an evicted slave gets refused until it re-registers
    rep = server._handle({"cmd": "job", "id": "s1"})
    assert rep["ok"] is False and rep.get("unregistered")
    assert server._handle({"cmd": "register", "id": "s1",
                           **_handshake_fields(master_wf)})["ok"]
    assert server.reregistrations == 1
    assert "s1" not in server.dead_slaves        # back from the dead

    server.bad_frames = 3                        # visible on the board
    status = WebStatus(port=0).start()
    try:
        status.register(master_wf)
        status.register_server(server)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/status.json") as r:
            master = json.load(r)["master"]
        assert master["bad_frames"] == 3
        for key in ("quarantined_updates", "reregistrations", "resumed",
                    "job_timeout_s", "dead_slaves", "bad_updates",
                    "resume_saves"):
            assert key in master, key
        assert master["reregistrations"] == 1
        assert [s["id"] for s in master["slaves"]] == ["s1"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{status.port}/") as r:
            page = r.read().decode()
        assert "bad frames" in page and "quarantined" in page
    finally:
        status.stop()


def test_adaptive_job_timeout(tmp_path):
    """The reap timeout tightens from observed durations (straggler
    re-dispatch) but never exceeds the configured ceiling and never
    collapses below the floor."""
    from znicz_tpu.server import Server

    master_wf = _make_workflow(tmp_path / "m")
    server = Server(master_wf, job_timeout=30.0)
    assert server.effective_job_timeout() == 30.0    # <5 samples: as-is
    server._durations.extend([0.1] * 8)
    # 8 x 0.1 median + 1s slack = 1.8s — stragglers reaped in seconds
    assert abs(server.effective_job_timeout() - 1.8) < 1e-9
    server._durations.extend([10.0] * 24)            # slow-but-alive fleet
    assert server.effective_job_timeout() == 30.0    # ceiling holds
    fast = Server(master_wf, job_timeout=0.0)        # tests reap instantly
    fast._durations.extend([0.01] * 8)
    assert fast.effective_job_timeout() == 0.0


# -- launcher / CLI ------------------------------------------------------------


def test_master_resume_cli_flag():
    from znicz_tpu import launcher

    args = launcher.Launcher(["mnist", "--master-resume", "f.pkl"]).args
    assert args.master_resume == "f.pkl"
    # resume is a master-role flag
    assert launcher.main(["mnist", "--master-resume", "f.pkl",
                          "--slave", "tcp://127.0.0.1:1"]) == 2


# -- the long soak -------------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_matches_fault_free(tmp_path):
    """Everything at once, against a fault-free reference run: frame
    chaos + mid-job slave death + master kill/resume, and the final
    validation error must land within tolerance of the undisturbed run
    (the faults cost work, not correctness)."""
    from znicz_tpu.client import Client
    from znicz_tpu.parallel.chaos import (ChaosProxy, FaultSchedule,
                                          MasterHarness, take_job_and_die)
    from znicz_tpu.server import Server

    # -- reference: no faults ------------------------------------------
    ref_wf = _make_workflow(tmp_path / "ref_m", max_epochs=4)
    ref_server = Server(ref_wf, endpoint="tcp://127.0.0.1:17590",
                        job_timeout=60.0)
    ref_slaves = [Client(_make_workflow(tmp_path / f"ref_s{i}",
                                        max_epochs=4),
                         endpoint="tcp://127.0.0.1:17590",
                         slave_id=f"ref{i}") for i in range(2)]
    threads = [threading.Thread(target=s.run, daemon=True)
               for s in ref_slaves]
    for t in threads:
        t.start()
    ref_server.serve()
    for t in threads:
        t.join(timeout=120)
    ref_err = ref_wf.decision.epoch_metrics[1]["err_pct"]

    # -- chaos run ------------------------------------------------------
    front, back = "tcp://127.0.0.1:17591", "tcp://127.0.0.1:17592"
    proxy = ChaosProxy(front, back, FaultSchedule(SEED, **CHAOS)).start()
    resume = str(tmp_path / "soak_resume.pickle.gz")
    harness = MasterHarness(
        lambda: _make_workflow(tmp_path / "m", max_epochs=4), back, resume,
        snapshot_every_s=0.25, linger=8.0, job_timeout=6.0)
    server1 = harness.start()
    slaves = [Client(_make_workflow(tmp_path / f"s{i}", max_epochs=4),
                     endpoint=front, slave_id=f"soak{i}")
              for i in range(2)]
    errors = []

    def worker(s):
        try:
            s.run(recv_timeout=1.0, max_reconnects=80, backoff_base=0.05,
                  backoff_cap=0.4, connect_retries=80)
        except BaseException as e:
            errors.append((s.slave_id, repr(e)))
            raise

    threads = [threading.Thread(target=worker, args=(s,), daemon=True)
               for s in slaves]
    try:
        for t in threads:
            t.start()
        take_job_and_die(front, harness.workflow, "doomed")
        deadline = time.time() + 90
        while server1.jobs_done < 4 and time.time() < deadline:
            time.sleep(0.05)
        assert server1.jobs_done >= 4
        saves = server1.resume_saves
        while server1.resume_saves <= saves and time.time() < deadline:
            time.sleep(0.05)
        harness.kill()                   # mid-epoch crash
        server2 = harness.start()
        assert server2.resumed
        assert harness.wait(timeout=300)
        for t in threads:
            t.join(timeout=120)
    finally:
        proxy.stop()
    assert not errors, errors
    assert not any(t.is_alive() for t in threads)

    dec = harness.workflow.decision
    assert bool(dec.complete)
    chaos_err = dec.epoch_metrics[1]["err_pct"]
    # fault-free convergence tolerance (async replicas differ anyway;
    # both runs must land in the same converged band)
    assert abs(chaos_err - ref_err) < 25.0, (chaos_err, ref_err)
    # accounting still balances under the full fault load
    assert server2.bad_frames + server1.bad_frames >= 1 or \
        proxy.counters["req"]["corrupt"] == 0
    assert server2.jobs_done == sum(server2.jobs_by_slave.values())
    assert sum(s.reconnects for s in slaves) >= 1
