"""Bitrot guard: EVERY bundled sample in the launcher registry builds and
trains end-to-end through the real CLI path (launcher.main in-process,
tiny shapes).  A sample whose config/layers/loader drifts breaks here
before it breaks a user."""

import pytest

from znicz_tpu.core.config import root
from znicz_tpu.launcher import SAMPLES


#: per-sample tiny-run overrides (keep each run a few seconds on CPU)
TINY = {
    "mnist": ["root.mnist.loader.n_train=120",
              "root.mnist.loader.n_valid=60",
              "root.mnist.loader.minibatch_size=60",
              "root.mnist.decision.max_epochs=1"],
    "cifar": ["root.cifar.loader.n_train=100",
              "root.cifar.loader.n_valid=50",
              "root.cifar.loader.minibatch_size=50",
              "root.cifar.decision.max_epochs=1"],
    "mnist_ae": ["root.mnist_ae.loader.n_train=100",
                 "root.mnist_ae.loader.n_valid=50",
                 "root.mnist_ae.loader.minibatch_size=50",
                 "root.mnist_ae.decision.max_epochs=1"],
    "kohonen": ["root.kohonen.decision.max_epochs=1"],
    "alexnet": ["root.alexnet.loader.minibatch_size=8",
                "root.alexnet.loader.n_train=16",
                "root.alexnet.loader.n_valid=8",
                "root.alexnet.loader.n_classes=10",
                "root.alexnet.loader.image_size=67",
                "root.alexnet.decision.max_epochs=1"],
    "wine": ["root.wine.decision.max_epochs=2"],
    "yale_faces": ["root.yale_faces.loader.n_subjects=3",
                   "root.yale_faces.loader.n_train_per_subject=4",
                   "root.yale_faces.loader.n_valid_per_subject=2",
                   "root.yale_faces.loader.minibatch_size=12",
                   "root.yale_faces.decision.max_epochs=1"],
    "kanji": ["root.kanji.loader.n_train=128",
              "root.kanji.loader.n_valid=64",
              "root.kanji.loader.n_classes=8",
              "root.kanji.loader.minibatch_size=64",
              "root.kanji.decision.max_epochs=1"],
    "video_ae": ["root.video_ae.loader.n_train=100",
                 "root.video_ae.loader.n_valid=50",
                 "root.video_ae.loader.minibatch_size=50",
                 "root.video_ae.decision.max_epochs=1"],
    "charlm": ["root.charlm.loader.n_train=96",
               "root.charlm.loader.n_valid=32",
               "root.charlm.loader.seq_len=16",
               "root.charlm.loader.minibatch_size=32",
               "root.charlm.decision.max_epochs=1"],
}


def test_every_registered_sample_has_tiny_overrides():
    assert set(TINY) == set(SAMPLES), (
        "new sample registered without a CLI smoke entry")


@pytest.mark.parametrize("sample", SAMPLES)
def test_sample_cli_smoke(sample, tmp_path, monkeypatch):
    from znicz_tpu import launcher
    from znicz_tpu.core import prng

    if sample == "yale_faces":
        root.yale_faces.loader.data_dir = str(tmp_path / "faces")
    monkeypatch.chdir(tmp_path)
    prng.reset(1013)
    try:
        rc = launcher.main([sample, *TINY[sample],
                            f"root.common.dirs.snapshots={tmp_path}"])
    finally:
        if sample == "yale_faces":
            root.yale_faces.loader.data_dir = "yale_faces_data"
    assert rc == 0
